"""Compressed push_pull over the DCN PS.

Per-tensor worker pipeline mirroring the reference's COMPRESS -> PUSH ->
server decompress/sum/recompress -> PULL -> DECOMPRESS dataflow
(core_loops.cc:498-648 + server.cc:92-118):

- the tensor is partitioned exactly like the dense path (every <=N-byte
  partition gets its own compressor instance, as the reference instantiates
  per-partition compressors, operations.cc:283-414);
- each partition's codec stack (momentum -> EF -> codec, host.py) runs
  worker-side; the server mirrors only the base codec;
- kwargs travel in-band per key (PSClient.comp_init);
- the per-key step counter feeds randomk/dithering seeding and matches the
  server's completed_rounds in sync mode.

``min_compress_bytes``: partitions smaller than this skip compression and
use the dense path (reference: BYTEPS_MIN_COMPRESS_BYTES,
operations.cc:361-364).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..core.types import (
    DataType, RequestType, TensorContext, get_command_type,
)
from ..ops.compression.host import make_host_codec

CMD_COMP_F32 = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                                DataType.FLOAT32)
CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


def compress_partition(stack, in_view_u8: np.ndarray,
                       step: int) -> np.ndarray:
    """One partition's wire payload: f32 view of the input bytes through the
    codec stack. Shared by the blocking path and the scheduler's COMPRESS
    stage so the wire format has exactly one producer."""
    part = in_view_u8.view(np.float32)
    return np.frombuffer(stack.compress(part, step), np.uint8)


def decompress_partition(stack, reply_u8: np.ndarray,
                         out_view_u8: np.ndarray) -> None:
    """Decode one partition's reply payload into its output slot (f32
    bytes). Shared by the blocking path and the DECOMPRESS stage."""
    out_view_u8[:] = stack.decompress(reply_u8).view(np.uint8)


class CompressedTensor:
    """Compressed PS round-trips for one named f32 tensor."""

    def __init__(self, client, ctx: TensorContext, kwargs: Dict[str, str],
                 num_workers: int, min_compress_bytes: int = 0):
        if ctx.dtype != DataType.FLOAT32:
            raise ValueError("compressed push_pull requires f32 gradients "
                             "(the codecs are f32 transforms)")
        self.client = client
        self.ctx = ctx
        self.num_workers = num_workers
        self.step = 0
        # pinned scheduler priority: set on the first pipelined submit and
        # reused for every later round — per-round priorities could reorder
        # rounds of a STATEFUL codec (EF accumulators, randomk/dithering
        # step seeds, the server's sync completed_rounds) in the admission
        # heap, which same-key serialization alone does not prevent.
        self.priority: Optional[int] = None
        self._lock = threading.Lock()
        # per-partition codec stacks; None = below min_compress_bytes,
        # dense path
        self.stacks = []
        for p in ctx.partitions:
            n = p.length // 4
            if p.length < max(min_compress_bytes, 8):
                self.stacks.append(None)
            else:
                self.stacks.append(make_host_codec(kwargs, n))
        self._installed = False

    def _install(self, nbytes: int) -> None:
        """Dense init-push (allocates the store, init barrier) then the
        per-key kwargs push. ensure_init pushes per-partition zeros, so
        the transient allocation is bounded by partition_bytes, not the
        whole tensor (a fused multi-hundred-MB bucket would otherwise
        spike host memory at startup)."""
        self.client.ensure_init(self.ctx, nbytes)
        for p, stack in zip(self.ctx.partitions, self.stacks):
            if stack is not None:
                self.client.comp_init(p.server, p.key, stack.kwargs_wire())
        self._installed = True

    def begin_round(self) -> int:
        """Claim the next compression round number (seeds the stateful
        codecs and matches the server's completed_rounds in sync mode),
        installing the server-side codecs on first use. Called by the
        pipeline scheduler before enqueuing this tensor's partitions."""
        with self._lock:
            if not self._installed:
                last = self.ctx.partitions[-1]
                self._install(last.offset + last.length)
            step = self.step
            self.step += 1
            return step

    def push_pull(self, flat: np.ndarray, average: bool = True) -> np.ndarray:
        """One compressed aggregation round; returns the decompressed
        cross-worker sum (mean when ``average``)."""
        flat = np.ascontiguousarray(flat, np.float32)
        if flat.nbytes != self.ctx.partitions[-1].offset + \
                self.ctx.partitions[-1].length:
            raise ValueError("tensor size changed; re-create the "
                             "CompressedTensor (stale partitioning)")
        with self._lock:
            if not self._installed:
                self._install(flat.nbytes)
            step = self.step
            self.step += 1
        out = np.empty_like(flat)
        view = flat.view(np.uint8)
        out_view = out.view(np.uint8)
        # ACTUAL bytes moved this round (list.append is GIL-atomic):
        # wire_bytes() is only an upper bound for varint wires
        moved: list = []

        def one(p, stack):
            lo, hi = p.offset, p.offset + p.length
            if stack is None:
                buf = np.ascontiguousarray(view[lo:hi])
                self.client.zpush(p.server, p.key, buf, CMD_F32)
                # pull straight into the output slot (contiguous view) —
                # no scratch buffer + copy on the hot path
                self.client.zpull(p.server, p.key, out_view[lo:hi],
                                  CMD_F32)
                moved.append(2 * p.length)
                if average and self.num_workers > 1:
                    res = out_view[lo:hi].view(np.float32)
                    res /= self.num_workers
                return
            wire = compress_partition(stack, view[lo:hi], step)
            self.client.zpush(p.server, p.key, wire, CMD_COMP_F32)
            reply = np.empty(stack.wire_bytes(), np.uint8)
            got = self.client.zpull(p.server, p.key, reply, CMD_COMP_F32)
            moved.append(len(wire) + got)
            decompress_partition(stack, reply[:got], out_view[lo:hi])
            if average and self.num_workers > 1:
                res = out_view[lo:hi].view(np.float32)
                res /= self.num_workers

        futures = [
            self.client._pool.submit(one, p, s)
            for p, s in zip(self.ctx.partitions, self.stacks)
        ]
        for f in futures:
            f.result()
        self.last_round_bytes = sum(moved)
        return out

    def wire_bytes(self) -> int:
        return sum(s.wire_bytes() if s is not None else p.length
                   for p, s in zip(self.ctx.partitions, self.stacks))


class CompressedRegistry:
    """name -> CompressedTensor cache for a training loop (one per named
    gradient, holding EF/momentum state across steps)."""

    def __init__(self, client, num_workers: int,
                 kwargs: Dict[str, str], min_compress_bytes: int = 0):
        self.client = client
        self.num_workers = num_workers
        self.kwargs = dict(kwargs)
        self.min_compress_bytes = min_compress_bytes
        self._tensors: Dict[str, CompressedTensor] = {}
        self._lock = threading.Lock()

    def get(self, state, name: str, flat: np.ndarray) -> CompressedTensor:
        from .client import get_or_init_ctx
        with self._lock:
            ct = self._tensors.get(name)
            if ct is None or ct.ctx.partitions[-1].offset + \
                    ct.ctx.partitions[-1].length != flat.nbytes:
                ctx = get_or_init_ctx(state, name, flat)
                ct = CompressedTensor(self.client, ctx, self.kwargs,
                                      self.num_workers,
                                      self.min_compress_bytes)
                self._tensors[name] = ct
            return ct

    def push_pull(self, state, name: str, flat: np.ndarray,
                  average: bool = True) -> np.ndarray:
        ct = self.get(state, name, flat)
        out = ct.push_pull(flat, average)
        state.telemetry.record(
            getattr(ct, "last_round_bytes", None) or ct.wire_bytes() * 2)
        return out

    def push_pull_async(self, state, name: str, flat: np.ndarray,
                        average: bool = True,
                        priority: Optional[int] = None,
                        out: Optional[np.ndarray] = None) -> int:
        """Submit a compressed push_pull through the priority-scheduled
        pipeline (COMPRESS -> PUSH -> PULL -> DECOMPRESS stages with credit
        admission — the reference's scheduled-queue splice,
        operations.cc:199-204); returns an async handle id for
        ``bps.synchronize``. Telemetry is recorded per-partition by the
        scheduler. ``out``: optional arena-staged flat f32 result buffer
        (see PipelineScheduler.submit)."""
        flat = np.ascontiguousarray(flat, np.float32)
        ct = self.get(state, name, flat)
        if ct.priority is None:
            ct.priority = (priority if priority is not None
                           else -ct.ctx.declared_key)
        handle = state.handles.allocate(name)
        handle._shape = flat.shape
        state.scheduler.submit(
            ct.ctx, flat, handle, average, self.num_workers,
            version=state.next_version(name), priority=ct.priority,
            comp=ct, out=out)
        return handle.id
