"""byteps_tpu.server — the DCN parameter server.

Run a server process with ``python -m byteps_tpu.server`` (role/topology
from DMLC_* env vars, like the reference's
``python3 -c 'import byteps.server'`` launched by bpslaunch,
reference: byteps/server/__init__.py:21-27, launcher/launch.py:241-249).

The server itself is native C++ (byteps_tpu/native/ps.cc): engine threads,
per-key stores, first-copy/sum/all-recv aggregation, parked pulls, sync +
async modes. This package holds the thin Python entry, the worker-side
client (client.py), and the in-process stats mirror below: servers that
run inside this interpreter (the loopback test/bench topology) register
their native handle while serving, so ``stage_stats()`` can read the
per-stage data-plane counters (recv → queue-wait → fold → reply, plus
the SIMD tier and the zero-copy tier engagement) that surface as the
``server`` section of ``bps.get_metrics()`` (docs/observability.md).
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Dict, List, Optional

from ..config import Config
from ..native.build import build

# native handles of servers currently serving IN THIS PROCESS
# (run_server registers around its blocking Run); remote/subprocess
# servers are invisible here by construction — their counters belong to
# their own process's snapshot
_live_mu = threading.Lock()
_live: list = []  # [(lib, ptr), ...]; every access under _live_mu

# bps_server_stats / STATS_PULL slot layout — append-only contract with
# native/ps.cc kStatSlotNames, machine-checked both directions by
# byteps-lint's slot-layout check (tools/lint/wire_layout.py); the same
# vector answers the STATS_PULL wire op, so this mirror parses the
# remote fleet's snapshots too.
_STAT_SLOTS = (
    "recv_ns", "recv_count", "queue_ns", "queue_count", "fold_ns",
    "fold_count", "fold_bytes", "reply_ns", "reply_count",
    "direct_recvs", "oob_msgs", "simd_tier", "engine_threads",
    "trace_records", "trace_dropped", "flight_records",
    "flight_dropped", "draining", "health_rounds", "health_nonfinite",
    "window_deferred", "window_rejected",
    # PR 17 wire plane: reply-batch ring (tx_batches/tx_msgs: msgs per
    # batch > 1 proves per-message sends retired), staged recv buffer
    # (rx_batches/rx_msgs), stripe reassembly (segments/payload bytes),
    # fused lossless decode-into-fold, and transport block registration
    "tx_batches", "tx_msgs", "rx_batches", "rx_msgs", "stripe_segs",
    "stripe_bytes", "fused_decode_folds", "reg_blocks", "reg_miss",
)

# Wire-sampled trace record (native/ps.cc TraceRec, drained over the
# TRACE_DRAIN control op). Field order/packing is wire contract; the
# lint slot-layout check diffs _TRACE_REC_FIELDS against the native
# kTraceRecFields manifest and TRACE_REC_FMT against the struct size.
# kind 0 = request span (t0 recv, t1 enqueue, t2 dequeue/fold start,
# t3 handler done), kind 1 = reply send (t0 = send instant).
TRACE_REC_FMT = "<QQQQQIHBB"
TRACE_REC_BYTES = 48
_TRACE_REC_FIELDS = (
    "key", "t0", "t1", "t2", "t3", "rid", "sender", "op", "kind",
)
assert struct.calcsize(TRACE_REC_FMT) == TRACE_REC_BYTES

# Server-side flight-recorder record (native/ps.cc FlightRec, drained
# over FLIGHT_DRAIN — a SNAPSHOT read: polls never steal the events a
# crash dump needs). Same lint discipline as the trace record.
FLIGHT_REC_FMT = "<QQQIHBB"
FLIGHT_REC_BYTES = 32
_FLIGHT_REC_FIELDS = (
    "ts_ns", "key", "detail", "rid", "sender", "kind", "pad",
)
assert struct.calcsize(FLIGHT_REC_FMT) == FLIGHT_REC_BYTES

# Per-key training-health record (native/ps.cc HealthRec, answered over
# the HEALTH_PULL control op and mirrored in-process by
# ``bps_server_key_health``). The two doubles (sum of squares / abs-max
# over the FINITE elements of the last published aggregate) travel as
# IEEE-754 bit patterns in u64 fields so the record stays fixed-width
# for the slot-layout lint; ``parse_health_rec`` reassembles them.
HEALTH_REC_FMT = "<QQQQQQ"
HEALTH_REC_BYTES = 48
_HEALTH_REC_FIELDS = (
    "key", "round", "sumsq_bits", "absmax_bits", "nonfinite", "elems",
)
assert struct.calcsize(HEALTH_REC_FMT) == HEALTH_REC_BYTES

# Per-conn / per-data-lane wire-counter record (native/ps.cc StripeRec,
# answered over the STRIPE_PULL control op and mirrored in-process by
# ``bps_server_stripe_stats``) — the time-series plane's de-aggregated
# stripe source: one record per live connection, counters CUMULATIVE
# since accept (readers difference them into per-stripe series).
# sender is ~0 (2**64-1) until the lane's first message identifies its
# worker. Same lint discipline as the trace record.
STRIPE_REC_FMT = "<QQQQQQQQ"
STRIPE_REC_BYTES = 64
_STRIPE_REC_FIELDS = (
    "conn", "sender", "tx_bytes", "tx_msgs", "rx_bytes", "rx_msgs",
    "seg_count", "seg_bytes",
)
assert struct.calcsize(STRIPE_REC_FMT) == STRIPE_REC_BYTES


def parse_stripe_recs(raw: bytes) -> List[Dict[str, int]]:
    """Packed StripeRec[] -> list of per-lane dicts — THE one parser
    for the STRIPE_PULL wire reply and the in-process mirror. Returns
    [] on a length mismatch (oversized/truncated reply)."""
    if not raw or len(raw) % STRIPE_REC_BYTES:
        return []
    return [dict(zip(_STRIPE_REC_FIELDS, vals))
            for vals in struct.iter_unpack(STRIPE_REC_FMT, raw)]


def parse_health_rec(raw: bytes) -> Optional[Dict[str, float]]:
    """One packed HealthRec -> dict with the doubles reassembled
    (None on a length mismatch) — THE one parser for the wire reply
    and the in-process mirror."""
    if len(raw) != HEALTH_REC_BYTES:
        return None
    vals = dict(zip(_HEALTH_REC_FIELDS, struct.unpack(HEALTH_REC_FMT,
                                                      raw)))
    out = {
        "key": vals["key"], "round": vals["round"],
        "sumsq": struct.unpack(
            "<d", struct.pack("<Q", vals["sumsq_bits"]))[0],
        "absmax": struct.unpack(
            "<d", struct.pack("<Q", vals["absmax_bits"]))[0],
        "nonfinite": vals["nonfinite"], "elems": vals["elems"],
    }
    return out

# native/ps.cc enum FlightKind — event names for the merged dump
FLIGHT_KIND_NAMES = {
    1: "replay_dedup", 2: "codec_reject", 3: "chaos_drop",
    4: "worker_departed", 5: "pull_abort", 6: "unknown_op",
    7: "round_skew", 8: "drained",
}


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.bps_server_create_dbg.restype = ctypes.c_void_p
    lib.bps_server_create_dbg.argtypes = [ctypes.c_int] * 5 + [
        ctypes.c_int64]
    lib.bps_server_run.argtypes = [ctypes.c_void_p]
    lib.bps_server_destroy.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "bps_server_stats"):
        # guarded: a stale .so predating the stats ABI must still serve
        lib.bps_server_stats.restype = ctypes.c_int
        lib.bps_server_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int]
        lib.bps_server_engine_bytes.restype = ctypes.c_int
        lib.bps_server_engine_bytes.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int]
    if hasattr(lib, "bps_server_stat_name"):
        # runtime view of the slot-layout manifest (guarded: stale .so)
        lib.bps_server_stat_name.restype = ctypes.c_char_p
        lib.bps_server_stat_name.argtypes = [ctypes.c_int]
        lib.bps_server_stat_count.restype = ctypes.c_int
        lib.bps_server_stat_count.argtypes = []
    if hasattr(lib, "bps_server_key_health"):
        # training-health in-process mirror (guarded: stale .so)
        lib.bps_server_key_health.restype = ctypes.c_int
        lib.bps_server_key_health.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
    if hasattr(lib, "bps_server_stripe_stats"):
        # per-lane wire counters, in-process mirror (guarded: stale .so)
        lib.bps_server_stripe_stats.restype = ctypes.c_int
        lib.bps_server_stripe_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int]
        lib.bps_server_stripe_field.restype = ctypes.c_char_p
        lib.bps_server_stripe_field.argtypes = [ctypes.c_int]
        lib.bps_server_stripe_field_count.restype = ctypes.c_int
        lib.bps_server_stripe_field_count.argtypes = []
    return lib


def native_stat_slot_names() -> List[str]:
    """The LOADED .so's slot-name manifest (empty on a stale .so) —
    lets a test assert the binary agrees with the ``_STAT_SLOTS``
    mirror that parses it, beyond the source-level lint check."""
    lib = _bind(ctypes.CDLL(build()))
    if not hasattr(lib, "bps_server_stat_name"):
        return []
    return [lib.bps_server_stat_name(i).decode()
            for i in range(lib.bps_server_stat_count())]


def native_stripe_field_names() -> List[str]:
    """The LOADED .so's stripe-record field manifest (empty on a stale
    .so) — the runtime half of the ``_STRIPE_REC_FIELDS`` lint check."""
    lib = _bind(ctypes.CDLL(build()))
    if not hasattr(lib, "bps_server_stripe_field"):
        return []
    return [lib.bps_server_stripe_field(i).decode()
            for i in range(lib.bps_server_stripe_field_count())]


def per_conn_stripe_stats() -> List[List[Dict[str, int]]]:
    """Per-conn / per-data-lane wire counters from the live IN-PROCESS
    servers: one list of lane record dicts (``_STRIPE_REC_FIELDS``
    keys) per server, registration order — the local half of the
    time-series plane's stripe source (remote fleets answer the same
    records over STRIPE_PULL, ``PSClient.stripe_stats``)."""
    out: List[List[Dict[str, int]]] = []
    n_fields = len(_STRIPE_REC_FIELDS)
    max_recs = 64  # native kCtrlStripeMax
    buf = (ctypes.c_uint64 * (max_recs * n_fields))()
    with _live_mu:  # see stage_stats: excludes a concurrent destroy
        for lib, ptr in _live:
            if not hasattr(lib, "bps_server_stripe_stats"):
                continue
            n = lib.bps_server_stripe_stats(ptr, buf, max_recs)
            out.append([
                dict(zip(_STRIPE_REC_FIELDS,
                         [int(buf[r * n_fields + f])
                          for f in range(n_fields)]))
                for r in range(n)])
    return out


def parse_stat_slots(raw) -> Dict[str, int]:
    """u64 slot vector (ctypes array, bytes, or int sequence) ->
    name->value dict under the append-only ``_STAT_SLOTS`` contract —
    THE one parser for both the in-process mirror and the STATS_PULL
    wire reply."""
    if isinstance(raw, (bytes, bytearray, memoryview)):
        raw = struct.unpack(f"<{len(raw) // 8}Q", bytes(raw))
    out = {k: 0 for k in _STAT_SLOTS}
    for i, v in enumerate(raw):
        if i >= len(_STAT_SLOTS):
            break  # newer server: trailing slots unknown to this mirror
        out[_STAT_SLOTS[i]] = int(v)
    return out


def derive_stage_section(raw: Dict[str, int]) -> Dict[str, float]:
    """Raw slot dict -> the documented ms-derived ``server``-section
    shape (shared by the in-process section and the per-server entries
    of ``bps.get_fleet_metrics()``, so the two surfaces can't drift)."""
    return {
        "recv_ms": raw["recv_ns"] / 1e6,
        "recv_count": raw["recv_count"],
        "queue_wait_ms": raw["queue_ns"] / 1e6,
        "queue_count": raw["queue_count"],
        "fold_ms": raw["fold_ns"] / 1e6,
        "fold_count": raw["fold_count"],
        "fold_bytes": raw["fold_bytes"],
        "reply_ms": raw["reply_ns"] / 1e6,
        "reply_count": raw["reply_count"],
        "direct_recvs": raw["direct_recvs"],
        "oob_msgs": raw["oob_msgs"],
        "simd_tier": raw["simd_tier"],
        "engine_threads": raw["engine_threads"],
        "trace_records": raw["trace_records"],
        "trace_dropped": raw["trace_dropped"],
        "flight_records": raw["flight_records"],
        "flight_dropped": raw["flight_dropped"],
        "draining": raw["draining"],
        "health_rounds": raw["health_rounds"],
        "health_nonfinite": raw["health_nonfinite"],
        "window_deferred": raw["window_deferred"],
        "window_rejected": raw["window_rejected"],
        "tx_batches": raw["tx_batches"],
        "tx_msgs": raw["tx_msgs"],
        "rx_batches": raw["rx_batches"],
        "rx_msgs": raw["rx_msgs"],
        "stripe_segs": raw["stripe_segs"],
        "stripe_bytes": raw["stripe_bytes"],
        "fused_decode_folds": raw["fused_decode_folds"],
        "reg_blocks": raw["reg_blocks"],
        "reg_miss": raw["reg_miss"],
    }


def stage_stats() -> Dict[str, int]:
    """Raw per-stage counters summed over every live in-process server
    (zeros when none — remote fleets export from their own process).
    ``simd_tier``/``engine_threads`` report the max across servers (one
    topology per process in practice)."""
    out = {k: 0 for k in _STAT_SLOTS}
    buf = (ctypes.c_uint64 * len(_STAT_SLOTS))()
    # the native calls run UNDER _live_mu: run_server destroys its
    # handle under the same lock, so a metrics poll racing a server
    # shutdown reads live-or-absent, never freed (use-after-free)
    with _live_mu:
        n_live = len(_live)
        for lib, ptr in _live:
            if not hasattr(lib, "bps_server_stats"):
                continue
            n = lib.bps_server_stats(ptr, buf, len(_STAT_SLOTS))
            for i in range(n):
                k = _STAT_SLOTS[i]
                if k in ("simd_tier", "engine_threads"):
                    out[k] = max(out[k], int(buf[i]))
                else:
                    out[k] += int(buf[i])
    out["live"] = n_live
    return out


def per_server_stats() -> List[Dict[str, int]]:
    """One raw slot dict per live IN-PROCESS server, in registration
    order — the local half of the fleet snapshot (remote/subprocess
    servers answer the same vector over the STATS_PULL control op)."""
    out: List[Dict[str, int]] = []
    buf = (ctypes.c_uint64 * len(_STAT_SLOTS))()
    with _live_mu:  # see stage_stats: excludes a concurrent destroy
        for lib, ptr in _live:
            if not hasattr(lib, "bps_server_stats"):
                continue
            n = lib.bps_server_stats(ptr, buf, len(_STAT_SLOTS))
            out.append(parse_stat_slots([buf[i] for i in range(n)]))
    return out


def key_health(key: int) -> Optional[Dict[str, float]]:
    """Per-key post-aggregation health statistics from the live
    IN-PROCESS servers (the loopback test/bench topology): the first
    server owning the key answers. None when no server holds the key
    or the health pass (BYTEPS_HEALTH) is off — remote fleets answer
    the same record over the HEALTH_PULL control op
    (``PSClient.health_pull``)."""
    buf = (ctypes.c_uint64 * 5)()
    with _live_mu:  # see stage_stats: excludes a concurrent destroy
        for lib, ptr in _live:
            if not hasattr(lib, "bps_server_key_health"):
                continue
            if lib.bps_server_key_health(ptr, int(key), buf) == 0:
                raw = struct.pack(
                    HEALTH_REC_FMT, int(key),
                    *[int(buf[i]) for i in range(5)])
                return parse_health_rec(raw)
    return None


def engine_stats() -> List[List[int]]:
    """Cumulative queued payload bytes per engine thread, one list per
    live in-process server — the balance-proof surface for the
    byte-weighted key→engine placement (tests/test_native_plane.py)."""
    out: List[List[int]] = []
    buf = (ctypes.c_uint64 * 64)()
    with _live_mu:  # see stage_stats: excludes a concurrent destroy
        for lib, ptr in _live:
            if not hasattr(lib, "bps_server_engine_bytes"):
                continue
            n = lib.bps_server_engine_bytes(ptr, buf, 64)
            out.append([int(buf[i]) for i in range(n)])
    return out


def stage_section() -> Dict[str, float]:
    """The ``server`` section of ``bps.get_metrics()``: per-stage walls
    in milliseconds plus counts, the fold-byte total (the fold_ab
    bench's HARD proof counter), zero-copy tier engagement, the active
    SIMD tier, and how many servers are live in this process. Keys are
    fixed whether or not a server is local, so the documented schema
    resolves on every deployment."""
    raw = stage_stats()
    out = derive_stage_section(raw)
    out["live"] = raw["live"]
    return out


def run_server(port: Optional[int] = None,
               config: Optional[Config] = None) -> int:
    """Start the native PS and block until all workers send SHUTDOWN."""
    config = config or Config.from_env()
    if port is None:
        server_id = int(os.environ.get("BYTEPS_SERVER_ID", "0"))
        port = config.scheduler_port + server_id
    lib = _bind(ctypes.CDLL(build()))
    # per-stage value printing for one key (reference: BYTEPS_SERVER_DEBUG
    # + BYTEPS_SERVER_DEBUG_KEY, server.cc:120-144,439-442)
    debug_key = -1
    from ..config import _env_bool
    if _env_bool("BYTEPS_SERVER_DEBUG"):
        debug_key = int(os.environ.get("BYTEPS_SERVER_DEBUG_KEY", "0"))
    srv = lib.bps_server_create_dbg(
        port, max(1, config.num_workers), config.server_engine_threads,
        1 if config.enable_async else 0,
        1 if config.server_enable_schedule else 0,
        debug_key)
    entry = (lib, srv)
    with _live_mu:
        _live.append(entry)
    try:
        rc = lib.bps_server_run(srv)
    finally:
        # unregister AND destroy under the lock: stage_stats() /
        # engine_stats() read the handle under _live_mu, so destroying
        # outside it would free a pointer a poll is mid-read on
        with _live_mu:
            try:
                _live.remove(entry)
            except ValueError:
                pass
            lib.bps_server_destroy(srv)
    return rc
