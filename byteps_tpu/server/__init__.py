"""byteps_tpu.server — the DCN parameter server.

Run a server process with ``python -m byteps_tpu.server`` (role/topology
from DMLC_* env vars, like the reference's
``python3 -c 'import byteps.server'`` launched by bpslaunch,
reference: byteps/server/__init__.py:21-27, launcher/launch.py:241-249).

The server itself is native C++ (byteps_tpu/native/ps.cc): engine threads,
per-key stores, first-copy/sum/all-recv aggregation, parked pulls, sync +
async modes. This package holds the thin Python entry and the worker-side
client (client.py).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

from ..config import Config
from ..native.build import build


def run_server(port: Optional[int] = None,
               config: Optional[Config] = None) -> int:
    """Start the native PS and block until all workers send SHUTDOWN."""
    config = config or Config.from_env()
    if port is None:
        server_id = int(os.environ.get("BYTEPS_SERVER_ID", "0"))
        port = config.scheduler_port + server_id
    lib = ctypes.CDLL(build())
    lib.bps_server_create_dbg.restype = ctypes.c_void_p
    lib.bps_server_create_dbg.argtypes = [ctypes.c_int] * 5 + [
        ctypes.c_int64]
    lib.bps_server_run.argtypes = [ctypes.c_void_p]
    lib.bps_server_destroy.argtypes = [ctypes.c_void_p]
    # per-stage value printing for one key (reference: BYTEPS_SERVER_DEBUG
    # + BYTEPS_SERVER_DEBUG_KEY, server.cc:120-144,439-442)
    debug_key = -1
    from ..config import _env_bool
    if _env_bool("BYTEPS_SERVER_DEBUG"):
        debug_key = int(os.environ.get("BYTEPS_SERVER_DEBUG_KEY", "0"))
    srv = lib.bps_server_create_dbg(
        port, max(1, config.num_workers), config.server_engine_threads,
        1 if config.enable_async else 0,
        1 if config.server_enable_schedule else 0,
        debug_key)
    rc = lib.bps_server_run(srv)
    lib.bps_server_destroy(srv)
    return rc
