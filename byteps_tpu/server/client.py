"""DCN parameter-server worker client.

The ps-lite ZPush/ZPull surface (reference: ps::KVWorker<char>, used from
byteps/common/core_loops.cc:571,609) over the native TCP client in
byteps_tpu/native/ps.cc. Per-partition push/pull runs on a thread pool in
priority order — the worker-side seed of the reference's PUSH/PULL pipeline
stages (core_loops.cc:538-618) — with partitions of one tensor fanned out
across servers by the registry's key->server assignment.

Beyond the reference surface: ``zpushpull_async`` — the fused PUSHPULL
wire op (one message per aggregation round trip, the THC shape) whose
replies are drained by a single **completion-reactor** thread off the
native completion queue, so in-flight requests are unbounded by thread
count (O(connections) threads total).
"""

from __future__ import annotations

import concurrent.futures
import ctypes
import os
import struct
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..core.types import (
    DataType, Partition, RequestType, TensorContext, get_command_type,
    trunc_divide_inplace,
)
from ..native.build import build
from ..utils.logging import log

# Python mirror of the native wire header (native/ps.cc MsgHeader).
# The transport itself is native — these constants exist so the Python
# side can NAME the contract (tests, tooling, debugging captures) and
# so byteps-lint's wire-layout rule can diff both sides statically: a
# header or magic change that lands on only one side fails the lint
# (the 36B->40B / 0xB17E5001->0xB17E5002 drift class). Keep field
# order identical to the struct: magic, op, flags, sender, rid, key,
# cmd, len, epoch, codec — little-endian, packed. 0xB17E5003 added the
# kFlagSeg striped-segment frame (MsgHeader + 32B SegHdr + chunk): a
# peer speaking the pre-stripe magic must be rejected at accept, not
# fed reassembly frames it would misparse as oversized payloads.
WIRE_MAGIC = 0xB17E5003
WIRE_HEADER_FMT = "<IBBHIQIIQI"
WIRE_HEADER_BYTES = 40
assert struct.calcsize(WIRE_HEADER_FMT) == WIRE_HEADER_BYTES

# Observability control ops (native/ps.cc enum Op; machine-checked by
# byteps-lint's slot-layout check against the enum). Header-only
# requests the server answers INLINE from the conn loop — stats/trace/
# flight pulls and the NTP-style clock echo never queue behind folds.
WIRE_CTRL_OPS = {
    "STATS_PULL": 12,
    "TRACE_DRAIN": 13,
    "FLIGHT_DRAIN": 14,
    "CLOCK_PROBE": 15,
    "JOIN_PROBE": 16,
    "DRAIN_REQ": 17,
    "HEALTH_PULL": 18,
    "STRIPE_PULL": 19,
}

# Control-pull reply size limits (native/ps.cc enum CtrlLimits, also
# lint-checked): the reply buffers below are sized from these, and a
# reply larger than its buffer is drained-not-delivered by the native
# recv loop — a silent empty drain, exactly the drift class the
# machine check exists to prevent.
WIRE_CTRL_LIMITS = {
    "kCtrlDrainBatch": 1024,
    "kCtrlFlightDrainMax": 4096,
    "kCtrlStripeMax": 64,
}


def _load_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(build())
    lib.bps_client_create.restype = ctypes.c_void_p
    lib.bps_client_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bps_client_init_key.restype = ctypes.c_int
    lib.bps_client_init_key.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_void_p,
        ctypes.c_uint32, ctypes.c_uint32]
    # push ops carry a trailing (round << 16 | attempt) epoch stamp for
    # server-side replay dedup (idempotent retry; docs/fault-tolerance.md)
    # plus a (plan_epoch << 8 | codec_id) adaptive-codec wire tag the
    # server validates per round (0 = untagged; docs/compression.md)
    epoch_argtypes = lib.bps_client_init_key.argtypes + [
        ctypes.c_uint64, ctypes.c_uint32]
    lib.bps_client_push.restype = ctypes.c_int
    lib.bps_client_push.argtypes = epoch_argtypes
    lib.bps_client_push_async.restype = ctypes.c_int
    lib.bps_client_push_async.argtypes = epoch_argtypes
    lib.bps_client_pull.restype = ctypes.c_int
    lib.bps_client_pull.argtypes = lib.bps_client_init_key.argtypes
    if hasattr(lib, "bps_client_pushpull_async"):
        # guarded: a stale .so predating the fused PUSHPULL op must
        # still load so supports_fused can return False and the
        # scheduler falls back to the two-op path (version skew)
        lib.bps_client_pushpull_async.restype = ctypes.c_int
        lib.bps_client_pushpull_async.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint32]
        lib.bps_client_cq_poll.restype = ctypes.c_int
        lib.bps_client_cq_poll.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int, ctypes.c_int]
        lib.bps_client_cq_depth.restype = ctypes.c_int
        lib.bps_client_cq_depth.argtypes = [ctypes.c_void_p]
        lib.bps_client_cq_abort.restype = None
        lib.bps_client_cq_abort.argtypes = [ctypes.c_void_p]
    lib.bps_client_comp_init.restype = ctypes.c_int
    lib.bps_client_comp_init.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64, ctypes.c_char_p]
    if hasattr(lib, "bps_client_server_dead"):
        # guarded like the fused op: a stale .so predating the probe
        # must still load (server_dead() then conservatively reports
        # False and failover never triggers — the pre-elastic behavior)
        lib.bps_client_server_dead.restype = ctypes.c_int
        lib.bps_client_server_dead.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int]
    if hasattr(lib, "bps_client_transport_stats"):
        # guarded like the probes above (stale-.so version skew)
        lib.bps_client_transport_stats.restype = ctypes.c_int
        lib.bps_client_transport_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int]
    if hasattr(lib, "bps_client_pushpull_async2"):
        # fused op reporting the wire rid back (the trace-plane flow
        # link); guarded — a stale .so degrades to rid-less tracing
        lib.bps_client_pushpull_async2.restype = ctypes.c_int
        lib.bps_client_pushpull_async2.argtypes = (
            lib.bps_client_pushpull_async.argtypes
            + [ctypes.POINTER(ctypes.c_uint32)])
    if hasattr(lib, "bps_client_ctrl"):
        # observability control plane (stats/trace/flight pulls + the
        # clock probe); guarded — supports_fleet reads False on a
        # stale .so and the fleet surfaces degrade to local-only
        lib.bps_client_ctrl.restype = ctypes.c_int
        lib.bps_client_ctrl.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_uint32, ctypes.c_int]
        lib.bps_client_clock_probe.restype = ctypes.c_int
        lib.bps_client_clock_probe.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
    if hasattr(lib, "bps_client_ctrl_key"):
        # keyed control pull (HEALTH_PULL, the training-health plane);
        # guarded — health_pull reads None on a stale .so
        lib.bps_client_ctrl_key.restype = ctypes.c_int
        lib.bps_client_ctrl_key.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_int]
    if hasattr(lib, "bps_client_stripe_bytes"):
        # striped wire plane (BYTEPS_WIRE_STRIPES): per-conn TX byte
        # ledger + the stripe-death test hook; guarded — a stale .so
        # reports no stripe instruments and never stripes
        lib.bps_client_stripe_bytes.restype = ctypes.c_int
        lib.bps_client_stripe_bytes.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.bps_client_kill_stripe.restype = ctypes.c_int
        lib.bps_client_kill_stripe.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    if hasattr(lib, "bps_client_add_server"):
        # runtime scale-up (elastic fleet); guarded — a stale .so simply
        # cannot grow its fleet and add_server() raises a clear error
        lib.bps_client_add_server.restype = ctypes.c_int
        lib.bps_client_add_server.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p]
    lib.bps_client_barrier.argtypes = [ctypes.c_void_p]
    lib.bps_client_barrier.restype = ctypes.c_int
    lib.bps_client_ipc_conns.argtypes = [ctypes.c_void_p]
    lib.bps_client_ipc_conns.restype = ctypes.c_int
    lib.bps_client_total_conns.argtypes = [ctypes.c_void_p]
    lib.bps_client_total_conns.restype = ctypes.c_int
    lib.bps_client_shutdown.argtypes = [ctypes.c_void_p]
    lib.bps_client_shutdown.restype = ctypes.c_int
    lib.bps_client_destroy.argtypes = [ctypes.c_void_p]
    return lib


def server_addresses(config: Config) -> List[str]:
    """Server endpoints: explicit BYTEPS_SERVER_HOSTS="h:p,h:p,..." or the
    scheduler URI with consecutive ports (root_port + server_id). The list
    length must equal num_servers — the registry assigns partitions to
    server indices [0, num_servers) and those index the native connection
    table unchecked."""
    hosts = os.environ.get("BYTEPS_SERVER_HOSTS", "")
    if hosts:
        addrs = [h.strip() for h in hosts.split(",") if h.strip()]
        if len(addrs) != config.num_servers:
            raise ValueError(
                f"BYTEPS_SERVER_HOSTS has {len(addrs)} entries but "
                f"DMLC_NUM_SERVER={config.num_servers}")
        return addrs
    return [f"{config.scheduler_uri}:{config.scheduler_port + i}"
            for i in range(config.num_servers)]


def get_or_init_ctx(state, name: str, host: np.ndarray) -> TensorContext:
    """Registry get-or-init for a host tensor. Always goes through
    init_tensor: it is idempotent for an unchanged size and re-partitions
    on resize (stale partitions would slice the wrong byte ranges)."""
    return state.registry.init_tensor(name, host.nbytes,
                                      DataType.from_np(host.dtype))


def build_rowsparse_payload(p: Partition, nz: np.ndarray,
                            host2d: np.ndarray) -> np.ndarray:
    """One partition's row-sparse push payload
    ([u32 nrows][u32 width][i32 local_ids][f32 rows]) — THE single wire
    producer, shared by the blocking client path and the scheduler's
    pipelined path (the server parser is ps.cc DoPushSparse). Raises if
    the partition does not land on row boundaries."""
    width = host2d.shape[1]
    row_bytes = width * 4
    if p.offset % row_bytes or p.length % row_bytes:
        raise ValueError(
            f"partition {p.index} not row-aligned; declare with "
            f"init_tensor(..., align_bytes={row_bytes})")
    lo = p.offset // row_bytes
    hi = (p.offset + p.length) // row_bytes
    sel = nz[(nz >= lo) & (nz < hi)]
    payload = b"".join((
        np.uint32(len(sel)).tobytes(),
        np.uint32(width).tobytes(),
        (sel - lo).astype(np.int32).tobytes(),
        np.ascontiguousarray(host2d[sel]).tobytes(),
    ))
    return np.frombuffer(payload, np.uint8)


def ps_round_trip(state, name: str, host: np.ndarray,
                  average: bool, priority: Optional[int] = None,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
    """Shared get-or-declare + server round-trip for one flat host tensor:
    used by both the eager push_pull PS tier and make_ps_train_step.

    Fans the partitions out through the priority-scheduled pipeline when
    one is running (so eager callers get the same credit/priority semantics
    and PUSH/PULL stage overlap as the async API), falling back to the
    client's blocking fan-out otherwise. ``out``: optional arena-staged
    flat result buffer (the caller owns its reuse window)."""
    ctx = get_or_init_ctx(state, name, host)
    host = np.ascontiguousarray(host)
    if state.scheduler is not None and state.handles is not None:
        handle = state.handles.allocate(name)
        state.scheduler.submit(ctx, host, handle, average,
                               state.config.num_workers,
                               version=state.next_version(name),
                               priority=priority, out=out)
        # scheduler records telemetry per-partition on completion
        return state.handles.wait_and_clear(handle.id)
    res = state.ps_client.push_pull(
        ctx, host, average=average, num_workers=state.config.num_workers,
        out=out)
    state.telemetry.record_round_trip(host.nbytes)
    return res


class PSClient:
    """Blocking-per-call, thread-safe ZPush/ZPull client; one native
    connection per server, multiplexed by request id."""

    def __init__(self, servers: Sequence[str], worker_id: int,
                 num_threads: int = 8):
        self._lib = _load_lib()
        csv = ",".join(servers).encode()
        self._handle = self._lib.bps_client_create(csv, worker_id)
        if not self._handle:
            raise RuntimeError(
                f"failed to connect to PS servers {servers!r}")
        self._servers = list(servers)
        n_ipc = self._lib.bps_client_ipc_conns(self._handle)
        if n_ipc:
            log.info("PS client: %d/%d connections upgraded to shm IPC "
                     "transport (BYTEPS_ENABLE_IPC)", n_ipc,
                     self._lib.bps_client_total_conns(self._handle))
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=num_threads, thread_name_prefix="bps-pushpull")
        self._closed = False
        self._lock = threading.Lock()
        # key -> store length this client has init-pushed on the server
        # (server-side initialization is per-store, distinct from registry
        # declaration; a resize needs a fresh init push)
        self._inited_keys: dict = {}   # guarded-by: _lock
        # wire-layer instrument refs (core/metrics.py), attached by
        # GlobalState.init after connect; None = uninstrumented (direct
        # construction in tests/benches)
        self._m_push_req = self._m_push_bytes = None
        self._m_pull_req = self._m_pull_bytes = None
        self._m_pushpull_req = self._m_errors = None
        self._m_inflight = self._m_inflight_peak = self._m_cq_depth = None
        self._m_stripe_segs = self._m_stripe_bytes = None
        # fused PUSHPULL completion reactor: ticket -> (callback,
        # reply-buffer ref). The buffer ref is load-bearing — the native
        # recv loop writes through its pointer until the ticket's
        # completion record is drained, so it must not be collectable.
        self._fused_mu = threading.Lock()
        self._fused: dict = {}         # guarded-by: _fused_mu
        self._next_ticket = 1          # guarded-by: _fused_mu
        self._reactor: Optional[threading.Thread] = None
        self._reactor_started = False  # guarded-by: _lock
        # outstanding wire requests awaiting a server reply (fused
        # requests + blocking pulls): THE concurrency the reactor model
        # unlocks — two-op mode caps it at the pull-pool thread count,
        # fused mode at scheduling credit
        self._inflight = 0             # guarded-by: _lock
        self._inflight_peak = 0        # guarded-by: _lock

    def attach_metrics(self, metrics) -> None:
        """Cache wire counters off the registry: every ZPush/ZPull
        request and its payload bytes land on the unified surface
        (``wire/*`` — request counts, bytes each way, failed requests;
        the native transport has no app-level retry, so ``wire/errors``
        is the retry-pressure signal). Fused PUSHPULL requests count
        under ``wire/pushpull_requests`` (one per partition per round —
        half the request messages of the two-op push+pull pair);
        ``wire/inflight`` / ``wire/inflight_peak`` gauge outstanding
        wire requests, ``wire/cq_depth`` the undrained completion-queue
        backlog."""
        self._m_push_req = metrics.counter("wire/push_requests")
        self._m_push_bytes = metrics.counter("wire/push_bytes")
        self._m_pull_req = metrics.counter("wire/pull_requests")
        self._m_pull_bytes = metrics.counter("wire/pull_bytes")
        self._m_pushpull_req = metrics.counter("wire/pushpull_requests")
        self._m_errors = metrics.counter("wire/errors")
        self._m_inflight = metrics.gauge("wire/inflight")
        self._m_inflight_peak = metrics.gauge("wire/inflight_peak")
        self._m_cq_depth = metrics.gauge("wire/cq_depth")
        # striped-wire ledger (BYTEPS_WIRE_STRIPES): cumulative segments
        # and payload bytes fanned across the data conns, refreshed by
        # the completion reactor each poll batch — zeros mean the
        # striper never engaged (payloads under 2 chunks, shm transport,
        # or stripes pinned to 1)
        self._m_stripe_segs = metrics.gauge("wire/stripe_segs")
        self._m_stripe_bytes = metrics.gauge("wire/stripe_bytes")

    def _inflight_add(self, d: int) -> None:
        # gauge writes INSIDE the lock: set() calls from two threads must
        # land in counter order, or a delayed stale set could leave the
        # gauge nonzero after the last request drained
        with self._lock:
            self._inflight += d
            cur = self._inflight
            if cur > self._inflight_peak:
                self._inflight_peak = cur
            if self._m_inflight is not None:
                self._m_inflight.set(cur)
                self._m_inflight_peak.set_max(cur)

    @property
    def inflight_peak(self) -> int:
        """Max simultaneously outstanding wire requests (proof surface
        for the reactor model: fused mode sustains more in-flight
        partitions than the pull pool has threads)."""
        with self._lock:
            return self._inflight_peak

    def _check_server(self, server: int) -> None:
        # the native connection table is indexed UNCHECKED — an
        # out-of-range index from a stale/corrupt partition assignment
        # would read garbage or segfault the whole worker, so reject it
        # here, before anything touches the wire
        if not 0 <= server < len(self._servers):
            raise ValueError(
                f"server index {server} out of range "
                f"[0, {len(self._servers)}) — stale partition table?")

    @property
    def ipc_conns(self) -> int:
        """Connections riding the colocated shm transport (0 = all TCP)."""
        if self._closed:
            raise RuntimeError("PSClient is closed")
        return int(self._lib.bps_client_ipc_conns(self._handle))

    def transport_stats(self) -> dict:
        """Client-side transport counters: shm-upgraded vs total
        connections, and how many messages rode the zero-copy
        descriptor (out-of-band arena) tier each direction —
        ``oob_sent`` counts large pushes whose payload the server folds
        IN PLACE from the shared arena, ``oob_recvd`` counts aggregate
        replies copied once from the arena straight into the caller's
        buffer. Zeros (with conns populated) when the transport is TCP
        or the payloads are below the descriptor threshold; all zeros
        on a stale native lib predating the ABI. ``stripe_segs`` /
        ``stripe_bytes`` count fused PUSHPULL traffic the client split
        across the BYTEPS_WIRE_STRIPES data connections (segments and
        payload bytes; framing overhead is 72B per segment — the
        byte-conservation identity the stripe_ab bench asserts is
        ``sum(stripe_conn_bytes()) == stripe_bytes + 72*stripe_segs``)."""
        if self._closed:
            raise RuntimeError("transport_stats on a closed PSClient")
        out = {"ipc_conns": 0, "total_conns": 0, "oob_sent": 0,
               "oob_recvd": 0, "stripe_segs": 0, "stripe_bytes": 0}
        if not hasattr(self._lib, "bps_client_transport_stats"):
            return out
        buf = (ctypes.c_uint64 * 6)()
        n = self._lib.bps_client_transport_stats(self._handle, buf, 6)
        for i, k in enumerate(("ipc_conns", "total_conns", "oob_sent",
                               "oob_recvd", "stripe_segs",
                               "stripe_bytes")):
            if i < n:
                out[k] = int(buf[i])
        return out

    def stripe_conn_bytes(self, server: int) -> List[int]:
        """Cumulative TX bytes per connection of one server's group
        (slot 0 is the control lane — always 0 stripe traffic). Sums
        to ``stripe_bytes + 72*stripe_segs`` when only striped traffic
        has flowed: the per-stripe half of the conservation proof.
        Empty list on a stale native lib."""
        self._check_server(server)
        if self._closed:
            raise RuntimeError("stripe_conn_bytes on a closed PSClient")
        if not hasattr(self._lib, "bps_client_stripe_bytes"):
            return []
        buf = (ctypes.c_uint64 * 16)()
        n = self._lib.bps_client_stripe_bytes(self._handle, server,
                                              buf, 16)
        if n < 0:
            return []
        return [int(buf[i]) for i in range(n)]

    def kill_stripe(self, server: int, idx: int) -> bool:
        """TEST HOOK: hard-kill one connection of a server's group
        (socket shutdown) to exercise single-stripe-death failover —
        the striper drops the dead conn from its live set and the
        request completes on the surviving stripes. False on a stale
        native lib or bad index."""
        self._check_server(server)
        if not hasattr(self._lib, "bps_client_kill_stripe"):
            return False
        return self._lib.bps_client_kill_stripe(
            self._handle, server, idx) == 0

    # ------------------------------------------------------------ #
    # fleet observability control plane (docs/observability.md):
    # stats/trace/flight pulls + the clock probe — the wire ops that
    # make an out-of-process server as measurable as an in-process one
    # ------------------------------------------------------------ #

    @property
    def supports_fleet(self) -> bool:
        """True when the loaded native library speaks the observability
        control ops (False only under stale-.so version skew — the
        fleet surfaces then degrade to in-process servers only)."""
        return hasattr(self._lib, "bps_client_ctrl")

    def _ctrl(self, server: int, op: str, cap: int,
              timeout_s: int = 5) -> Optional[bytes]:
        """One bounded control pull; returns the reply payload or None
        (unsupported ABI / failed request). The per-request timeout is
        deliberate: a wedged server costs a metrics poll ``timeout_s``
        seconds, never the data plane's BYTEPS_CLIENT_TIMEOUT_S."""
        self._check_server(server)
        if self._closed:
            raise RuntimeError("control pull on a closed PSClient")
        if not self.supports_fleet:
            return None
        buf = (ctypes.c_uint8 * cap)()
        n = self._lib.bps_client_ctrl(
            self._handle, server, WIRE_CTRL_OPS[op], buf, cap, timeout_s)
        if n < 0:
            return None
        return bytes(buf[:n])

    def server_stats(self, server: int,
                     timeout_s: int = 5) -> Optional[dict]:
        """One remote server's full per-stage registry snapshot (the
        same slot vector as the in-process ``bps_server_stats`` mirror,
        by construction — STATS_PULL answers from one definition).
        None when the server is unreachable or the ABI is stale."""
        raw = self._ctrl(server, "STATS_PULL", 64 * 8, timeout_s)
        if raw is None or len(raw) % 8:
            return None
        from . import parse_stat_slots
        return parse_stat_slots(raw)

    def drain_trace(self, server: int, timeout_s: int = 5,
                    max_batches: int = 64) -> List[dict]:
        """Drain (destructively) the server's wire-sampled trace ring:
        a list of record dicts (``kind`` 0 = request span with recv/
        enqueue/dequeue/done server-clock ns, 1 = reply send). Loops
        full batches so one call empties the ring."""
        from . import TRACE_REC_BYTES, TRACE_REC_FMT, _TRACE_REC_FIELDS
        out: List[dict] = []
        batch_cap = WIRE_CTRL_LIMITS["kCtrlDrainBatch"] * TRACE_REC_BYTES
        for _ in range(max_batches):
            raw = self._ctrl(server, "TRACE_DRAIN", batch_cap, timeout_s)
            if not raw or len(raw) % TRACE_REC_BYTES:
                break
            out += [dict(zip(_TRACE_REC_FIELDS, rec))
                    for rec in struct.iter_unpack(TRACE_REC_FMT, raw)]
            if len(raw) < batch_cap:
                break
        return out

    def drain_flight(self, server: int, timeout_s: int = 5) -> List[dict]:
        """Snapshot the server's flight-recorder ring (non-destructive:
        a poll never steals the events a later crash dump needs); kinds
        resolve to names via ``FLIGHT_KIND_NAMES``."""
        from . import (
            FLIGHT_KIND_NAMES, FLIGHT_REC_BYTES, FLIGHT_REC_FMT,
            _FLIGHT_REC_FIELDS,
        )
        raw = self._ctrl(
            server, "FLIGHT_DRAIN",
            WIRE_CTRL_LIMITS["kCtrlFlightDrainMax"] * FLIGHT_REC_BYTES,
            timeout_s)
        if not raw or len(raw) % FLIGHT_REC_BYTES:
            return []
        out = []
        for rec in struct.iter_unpack(FLIGHT_REC_FMT, raw):
            d = dict(zip(_FLIGHT_REC_FIELDS, rec))
            d.pop("pad", None)
            d["kind"] = FLIGHT_KIND_NAMES.get(d["kind"], str(d["kind"]))
            out.append(d)
        return out

    def stripe_stats(self, server: int,
                     timeout_s: int = 5) -> List[dict]:
        """One remote server's per-conn / per-data-lane wire counters
        (the time-series plane's de-aggregated stripe source): a list
        of ``_STRIPE_REC_FIELDS`` dicts, one per live connection there,
        counters cumulative since accept. Empty when the server is
        unreachable or the ABI is stale. The in-process mirror
        (``server.per_conn_stripe_stats``) answers from the same
        StripeSlots vector, by construction."""
        from . import STRIPE_REC_BYTES, parse_stripe_recs
        raw = self._ctrl(
            server, "STRIPE_PULL",
            WIRE_CTRL_LIMITS["kCtrlStripeMax"] * STRIPE_REC_BYTES,
            timeout_s)
        if raw is None:
            return []
        return parse_stripe_recs(raw)

    def health_pull(self, server: int, key: int,
                    timeout_s: int = 5) -> Optional[dict]:
        """Per-key POST-AGGREGATION health statistics (the training-
        health plane, docs/observability.md): the server's in-fold
        pass (BYTEPS_HEALTH) computes sum-of-squares / abs-max /
        nonfinite counts of each published aggregate, and this keyed
        control pull fetches the last round's record —
        ``{key, round, sumsq, absmax, nonfinite, elems}``. None when
        the key is unknown there, the server runs with the pass off,
        or the ABI is stale. Bounded like every control pull: a wedged
        server costs ``timeout_s`` seconds, never the data plane's
        budget."""
        self._check_server(server)
        if self._closed:
            raise RuntimeError("control pull on a closed PSClient")
        if not hasattr(self._lib, "bps_client_ctrl_key"):
            return None
        from . import HEALTH_REC_BYTES, parse_health_rec
        buf = (ctypes.c_uint8 * HEALTH_REC_BYTES)()
        n = self._lib.bps_client_ctrl_key(
            self._handle, server, WIRE_CTRL_OPS["HEALTH_PULL"],
            int(key), buf, HEALTH_REC_BYTES, timeout_s)
        if n != HEALTH_REC_BYTES:
            return None
        return parse_health_rec(bytes(buf))

    def clock_probe(self, server: int, probes: int = 8,
                    timeout_s: int = 5) -> Optional[tuple]:
        """Estimate ``server``'s steady-clock offset NTP-style from
        request/reply timestamp echoes: ``probes`` round trips, keep
        the minimum-RTT sample (utils/tracing.py estimate_clock_offset).
        Returns (offset_ns, err_bound_ns) where
        ``server_clock - offset ≈ this process's clock``, or None when
        unsupported/unreachable."""
        self._check_server(server)
        if self._closed or not self.supports_fleet:
            return None
        buf = (ctypes.c_uint64 * 4)()
        samples = []
        for _ in range(max(1, probes)):
            if self._lib.bps_client_clock_probe(
                    self._handle, server, buf, timeout_s) != 0:
                continue
            samples.append((int(buf[0]), int(buf[1]), int(buf[2]),
                            int(buf[3])))
        if not samples:
            return None
        from ..utils.tracing import estimate_clock_offset
        return estimate_clock_offset(samples)

    # ------------------------------------------------------------ #
    # per-server health (the elastic/failover plane)
    # ------------------------------------------------------------ #

    def server_dead(self, server: int) -> bool:
        """True when EVERY striped native connection to ``server`` is
        dead (transport EOF after a crash/SIGKILL, or poisoned) — the
        worker-side server-death verdict. Driven by the native recv
        loops / completion reactor conn-death path, so it flips within
        milliseconds of the TCP EOF (the shm-ring transport polls the
        paired TCP fd for liveness at 5ms granularity). False for
        in-range healthy servers and when the loaded native lib
        predates the probe (version skew: failover simply never
        triggers)."""
        if self._closed or not 0 <= server < len(self._servers):
            return True
        if not hasattr(self._lib, "bps_client_server_dead"):
            return False
        return bool(self._lib.bps_client_server_dead(self._handle, server))

    def dead_servers(self) -> List[int]:
        """Indices of servers whose every connection is dead."""
        return [s for s in range(len(self._servers)) if self.server_dead(s)]

    # ------------------------------------------------------------ #
    # elastic fleet: runtime scale-up join + graceful drain
    # (core/elastic.py drives these; docs/fault-tolerance.md)
    # ------------------------------------------------------------ #

    @property
    def servers(self) -> List[str]:
        """The live server address list (grows on :meth:`add_server`)."""
        with self._lock:
            return list(self._servers)

    @property
    def supports_elastic(self) -> bool:
        """True when the loaded native library can grow its connection
        table at runtime (False only under stale-.so version skew)."""
        return hasattr(self._lib, "bps_client_add_server")

    def add_server(self, address: str) -> int:
        """Connect this client to a NEW server at runtime and return its
        index (== the previous server count). The native side publishes
        the fully-connected striped conn group atomically, so in-flight
        traffic to existing servers never races the growth. The caller
        must run :meth:`join_probe` before routing keys to the index."""
        with self._lock:
            if self._closed:
                raise RuntimeError("add_server on a closed PSClient")
        if not self.supports_elastic:
            raise RuntimeError(
                "native library predates runtime scale-up "
                "(bps_client_add_server missing) — rebuild the native "
                "lib to grow the fleet at runtime")
        idx = self._lib.bps_client_add_server(self._handle,
                                              address.encode())
        if idx < 0:
            raise RuntimeError(
                f"failed to connect new PS server at {address!r}")
        with self._lock:
            # the native index is authoritative; the Python list exists
            # for range checks and re-connect bookkeeping
            while len(self._servers) <= idx:
                self._servers.append(address)
            self._servers[idx] = address
        log.info("PS client: joined server %d at %s", idx, address)
        return idx

    def join_probe(self, server: int,
                   timeout_s: int = 5) -> Optional[dict]:
        """Scale-up join handshake: ask ``server`` for its worker count
        and draining state (JOIN_PROBE control op). Returns
        ``{"num_workers", "draining"}`` or None (unreachable / stale
        ABI). The caller validates ``num_workers`` against its own
        config BEFORE the registry routes key subranges there — a
        mismatched newcomer would wedge every aggregation round."""
        raw = self._ctrl(server, "JOIN_PROBE", 16, timeout_s)
        if raw is None or len(raw) != 16:
            return None
        nw, draining = struct.unpack("<QQ", raw)
        return {"num_workers": int(nw), "draining": bool(draining)}

    def drain_req(self, server: int,
                  timeout_s: int = 5) -> Optional[dict]:
        """Graceful-drain ACK (DRAIN_REQ control op): latch the server's
        advisory draining flag and collect ``{"keys_held",
        "draining"}``. Called AFTER the registry migrated the server's
        keys away; best-effort — a dead/stale server returns None and
        the drain proceeds regardless (the flag is forensic, not a
        correctness gate)."""
        raw = self._ctrl(server, "DRAIN_REQ", 16, timeout_s)
        if raw is None or len(raw) != 16:
            return None
        held, draining = struct.unpack("<QQ", raw)
        return {"keys_held": int(held), "draining": bool(draining)}

    def invalidate_init(self, keys) -> None:
        """Forget that ``keys`` were init-pushed: after a key migrates to
        a different server (registry ``migrate_server``), the adoptive
        server has no store for it yet — the next ``ensure_init`` must
        re-init-push there instead of trusting this client's cache (which
        only records key→length, not which server holds the store)."""
        with self._lock:
            for k in keys:
                self._inited_keys.pop(k, None)

    # ------------------------------------------------------------ #
    # raw per-key ops (ZPush/ZPull)
    # ------------------------------------------------------------ #

    def init_key(self, server: int, key: int, data: np.ndarray,
                 cmd: int) -> None:
        self._check_server(server)
        buf = np.ascontiguousarray(data)
        rc = self._lib.bps_client_init_key(
            self._handle, server, key, buf.ctypes.data, buf.nbytes, cmd)
        if rc != 0:
            raise RuntimeError(f"init_key failed key={key}")

    def zpush(self, server: int, key: int, data: np.ndarray,
              cmd: int, epoch: int = 0, codec: int = 0) -> None:
        """``epoch``: optional (round << 16 | attempt) replay-dedup stamp
        — the server folds a given (key, sender, round) at most once, so
        a retried push after a dropped reply never double-counts
        (docs/fault-tolerance.md). 0 = unstamped (legacy semantics).
        ``codec``: optional (plan_epoch << 8 | codec_id) adaptive-codec
        wire tag — the server latches the first fold's tag per round and
        loudly rejects disagreeing folds (docs/compression.md). 0 =
        untagged, no validation."""
        self._check_server(server)
        data = np.ascontiguousarray(data)  # .ctypes.data of a strided
        rc = self._lib.bps_client_push(   # view points at the base buffer
            self._handle, server, key, data.ctypes.data, data.nbytes, cmd,
            epoch, codec)
        if self._m_push_req is not None:
            self._m_push_req.inc()
            self._m_push_bytes.inc(data.nbytes)
        if rc != 0:
            if self._m_errors is not None:
                self._m_errors.inc()
            raise RuntimeError(f"push failed key={key}")

    def zpush_async(self, server: int, key: int, data: np.ndarray,
                    cmd: int, epoch: int = 0, codec: int = 0) -> None:
        """Fire-and-forget push: returns once the payload is on the wire
        (the native send copies it into the socket/ring, so ``data`` may
        be reused immediately). The ACK drains in the background; a
        server reject poisons the connection and surfaces on the paired
        zpull. Removes the ACK round-trip from the pipeline's critical
        path — the pull is the only synchronization, matching ps-lite's
        asynchronous ZPush. ``epoch``: replay-dedup stamp, ``codec``:
        adaptive wire tag (see zpush)."""
        self._check_server(server)
        data = np.ascontiguousarray(data)
        rc = self._lib.bps_client_push_async(
            self._handle, server, key, data.ctypes.data, data.nbytes, cmd,
            epoch, codec)
        if self._m_push_req is not None:
            self._m_push_req.inc()
            self._m_push_bytes.inc(data.nbytes)
        if rc != 0:
            if self._m_errors is not None:
                self._m_errors.inc()
            raise RuntimeError(f"async push failed key={key}")

    def zpull(self, server: int, key: int, out: np.ndarray,
              cmd: int, exact: bool = False) -> int:
        """Pull into ``out``; returns the ACTUAL reply length (equal to
        out.nbytes for dense/fixed formats, possibly shorter for
        variable-length wires like varint-coded dithering).

        ``exact=True``: the caller means ``out`` as the whole reply
        (dense pulls) — a SHORTER reply then raises instead of leaving
        the tail of ``out`` unwritten garbage (stale partitioning after
        a tensor resize). A reply LONGER than ``out`` always fails: the
        native side drains it whole — the byte stream stays
        message-aligned, so the connection survives — and reports the
        mismatch instead of truncating."""
        self._check_server(server)
        if not out.flags["C_CONTIGUOUS"]:
            # the native side writes through .ctypes.data — a strided
            # view would silently receive bytes at the wrong offsets
            raise ValueError("zpull requires a C-contiguous output array")
        self._inflight_add(1)
        try:
            rc = self._lib.bps_client_pull(
                self._handle, server, key, out.ctypes.data, out.nbytes, cmd)
        finally:
            self._inflight_add(-1)
        if self._m_pull_req is not None:
            self._m_pull_req.inc()
        if rc < 0:
            if self._m_errors is not None:
                self._m_errors.inc()
            raise RuntimeError(
                f"pull failed key={key} (server error, reply larger than "
                f"the {out.nbytes}-byte output view, or connection lost)")
        if exact and rc != out.nbytes:
            if self._m_errors is not None:
                self._m_errors.inc()
            raise RuntimeError(
                f"pull reply for key={key} is {rc} bytes, expected exactly "
                f"{out.nbytes} — stale partitioning after a tensor resize?")
        if self._m_pull_bytes is not None:
            self._m_pull_bytes.inc(rc)  # actual reply length
        return rc

    # ------------------------------------------------------------ #
    # fused PUSHPULL + completion reactor
    # ------------------------------------------------------------ #

    @property
    def supports_fused(self) -> bool:
        """True when the loaded native library has the fused PUSHPULL op
        (always, for in-tree builds; False only under version skew)."""
        return hasattr(self._lib, "bps_client_pushpull_async")

    def zpushpull_async(self, server: int, key: int, data: np.ndarray,
                        out: np.ndarray, cmd: int,
                        on_done: Callable[[int, Optional[Exception]], None],
                        epoch: int = 0, codec: int = 0) -> int:
        """Fused push+pull in ONE wire round trip: push ``data``, and
        when the server's aggregation round completes, the aggregate
        lands in ``out`` and ``on_done(reply_len, error)`` runs on the
        completion-reactor thread (keep it tiny or hand off). Returns
        the moment the request is on the wire — no thread parks for the
        aggregation wait, so in-flight partitions are bounded by
        scheduling credit, not pool size. ``out`` must stay alive until
        ``on_done`` fires (the registration table pins it). ``epoch``:
        replay-dedup stamp (see zpush) — a retried fused request with
        the same round is answered from the round's aggregate without
        re-folding the payload. ``codec``: adaptive wire tag (see
        zpush).

        Returns the request's wire rid (0 on a native lib predating the
        reporting ABI) — the id server-side trace spans carry, which the
        fused timeline uses to flow-link worker and server spans."""
        self._check_server(server)
        if not out.flags["C_CONTIGUOUS"]:
            raise ValueError(
                "zpushpull_async requires a C-contiguous reply buffer")
        if self._closed:
            raise RuntimeError("zpushpull_async on a closed PSClient")
        data = np.ascontiguousarray(data)
        with self._fused_mu:
            ticket = self._next_ticket
            self._next_ticket += 1
            # register BEFORE the send: the reply can complete (and the
            # reactor dispatch) before the native call returns
            self._fused[ticket] = (on_done, out)
        self._ensure_reactor()
        self._inflight_add(1)
        rid = ctypes.c_uint32(0)
        if hasattr(self._lib, "bps_client_pushpull_async2"):
            rc = self._lib.bps_client_pushpull_async2(
                self._handle, server, key, data.ctypes.data, data.nbytes,
                cmd, out.ctypes.data, out.nbytes, ticket, epoch, codec,
                ctypes.byref(rid))
        else:
            rc = self._lib.bps_client_pushpull_async(
                self._handle, server, key, data.ctypes.data, data.nbytes,
                cmd, out.ctypes.data, out.nbytes, ticket, epoch, codec)
        if self._m_pushpull_req is not None:
            self._m_pushpull_req.inc()
            self._m_push_bytes.inc(data.nbytes)
        if rc != 0:
            # rc != 0 means the native side still OWNED the waiter when
            # the send failed (a fail-all sweep that claimed it first
            # reports success and fails the ticket through the queue
            # instead) — so exactly one of {this raise, the reactor
            # callback} fires. The pop guard keeps it that way even if
            # a stray record raced us.
            with self._fused_mu:
                owned = self._fused.pop(ticket, None) is not None
            if not owned:
                return int(rid.value)  # reactor already owns the failure
            self._inflight_add(-1)
            if self._m_errors is not None:
                self._m_errors.inc()
            raise RuntimeError(
                f"fused pushpull failed to send key={key} "
                f"(connection poisoned or lost)")
        return int(rid.value)

    def _ensure_reactor(self) -> None:
        # double-checked locking: the flag only ever flips False->True,
        # so the lock-free fast path can at worst take the slow path
        # once more — keeping the lock off every post-startup send
        if self._reactor_started:  # bps-lint: disable=guarded-by
            return
        with self._lock:
            if self._reactor_started:
                return
            self._reactor = threading.Thread(
                target=self._reactor_loop, name="bps-cq-reactor",
                daemon=True)
            self._reactor_started = True
            self._reactor.start()

    def _reactor_loop(self) -> None:
        """THE receive-completion thread: drains the native completion
        queue in batches and resolves per-ticket callbacks. One thread
        regardless of how many partitions are in flight — the
        O(connections) half of the reactor model (the per-connection
        recv loops are native)."""
        max_n = 128
        tickets = (ctypes.c_uint64 * max_n)()
        statuses = (ctypes.c_int32 * max_n)()
        lens = (ctypes.c_uint32 * max_n)()
        while True:
            n = self._lib.bps_client_cq_poll(
                self._handle, tickets, statuses, lens, max_n, 250)
            if n < 0:
                return  # queue closed and drained: teardown
            if self._m_cq_depth is not None:
                self._m_cq_depth.set(
                    self._lib.bps_client_cq_depth(self._handle))
            if (self._m_stripe_segs is not None
                    and hasattr(self._lib,
                                "bps_client_transport_stats")):
                tbuf = (ctypes.c_uint64 * 6)()
                tn = self._lib.bps_client_transport_stats(
                    self._handle, tbuf, 6)
                if tn >= 6:
                    self._m_stripe_segs.set(int(tbuf[4]))
                    self._m_stripe_bytes.set(int(tbuf[5]))
            for i in range(n):
                with self._fused_mu:
                    entry = self._fused.pop(int(tickets[i]), None)
                if entry is None:
                    # already failed locally (close() / send-failure
                    # raise): that path decremented inflight — doing it
                    # again here would underflow the gauge
                    continue
                self._inflight_add(-1)
                cb, _out = entry
                status = int(statuses[i])
                err = None
                if status == -2:
                    err = TimeoutError(
                        "fused pushpull timed out waiting for the "
                        "aggregation round (BYTEPS_CLIENT_TIMEOUT_S)")
                elif status != 0:
                    err = RuntimeError(
                        "fused pushpull failed (server error reply, "
                        "oversized reply, or connection lost)")
                elif self._m_pull_bytes is not None:
                    self._m_pull_bytes.inc(int(lens[i]))
                try:
                    cb(int(lens[i]), err)
                except Exception:  # noqa: BLE001 - must not kill reactor
                    log.exception(
                        "fused completion callback raised (ticket %d)",
                        int(tickets[i]))

    def _stop_reactor(self) -> None:
        """Teardown half-step: fail outstanding fused requests into the
        queue, close it, and join the reactor so no native callback can
        run after the client handle is freed."""
        with self._lock:
            started = self._reactor_started
        if not started:
            return
        try:
            self._lib.bps_client_cq_abort(self._handle)
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass
        if self._reactor is not None:
            self._reactor.join(timeout=10)
        # anything the reactor didn't get to (it died, or records were
        # dropped after close): resolve with an error so waiters raise
        # instead of hanging
        with self._fused_mu:
            leftovers = list(self._fused.items())
            self._fused.clear()
        for ticket, (cb, _out) in leftovers:
            try:
                cb(0, RuntimeError("PSClient closed with the fused "
                                   "request still in flight"))
            except Exception:  # noqa: BLE001
                log.exception("fused teardown callback raised (ticket %d)",
                              ticket)

    def comp_init(self, server: int, key: int, kwargs_wire: str) -> None:
        """Install a server-side compressor for ``key`` (the reference's
        in-band kCompressedPushPull kwargs push, operations.cc:396-408)."""
        self._check_server(server)
        rc = self._lib.bps_client_comp_init(
            self._handle, server, key, kwargs_wire.encode())
        if rc != 0:
            raise RuntimeError(
                f"comp_init failed key={key} kwargs={kwargs_wire!r} "
                f"(is the store init-pushed as dense f32, sync mode?)")

    def barrier(self) -> None:
        if self._lib.bps_client_barrier(self._handle) != 0:
            raise RuntimeError("barrier failed")

    # ------------------------------------------------------------ #
    # tensor-level push_pull over partitions
    # ------------------------------------------------------------ #

    def init_tensor(self, ctx: TensorContext, flat: np.ndarray) -> None:
        """Blocking initial push of every partition — acts as the per-key
        init barrier (reference: operations.cc:283-414)."""
        cmd = get_command_type(RequestType.DEFAULT_PUSH_PULL, ctx.dtype)
        view = flat.view(np.uint8)
        futures = [
            self._pool.submit(self.init_key, p.server, p.key,
                              view[p.offset:p.offset + p.length], cmd)
            for p in ctx.partitions
        ]
        for f in futures:
            f.result()
        with self._lock:
            self._inited_keys.update(
                {p.key: p.length for p in ctx.partitions})

    def ensure_init(self, ctx: TensorContext, nbytes: int) -> None:
        """Init-push any partition of ctx this client hasn't initialized on
        the server at its current length (registry declaration alone doesn't
        allocate the server store; a resized tensor re-inits). Only the
        missing partitions are pushed — every worker derives the same
        ``missing`` set from the shared registry partitioning, so the
        per-key init barrier still converges."""
        total = sum(p.length for p in ctx.partitions)
        if nbytes != total:
            # the partitioning drives everything below; a caller whose
            # byte count disagrees has a stale ctx (resize without
            # re-declare) and would init the wrong store lengths
            raise ValueError(
                f"ensure_init: caller nbytes={nbytes} != partitioned "
                f"total {total} for {ctx.name!r} — re-declare the tensor "
                f"(registry.init_tensor) after a resize")
        with self._lock:
            missing = [p for p in ctx.partitions
                       if self._inited_keys.get(p.key) != p.length]
        if not missing:
            return
        cmd = get_command_type(RequestType.DEFAULT_PUSH_PULL, ctx.dtype)
        futures = [
            self._pool.submit(self.init_key, p.server, p.key,
                              np.zeros(p.length, np.uint8), cmd)
            for p in missing
        ]
        for f in futures:
            f.result()
        with self._lock:
            self._inited_keys.update({p.key: p.length for p in missing})

    def _round_trip(self, ctx: TensorContext, in_flat: np.ndarray,
                    out_flat: np.ndarray) -> None:
        """Concurrent per-partition push-then-pull against the assigned
        servers (the PUSH/PULL stage pair, core_loops.cc:538-618)."""
        cmd = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                               DataType.from_np(in_flat.dtype))
        in_view = in_flat.view(np.uint8)
        out_view = out_flat.view(np.uint8)

        def one(p: Partition):
            self.zpush(p.server, p.key,
                       in_view[p.offset:p.offset + p.length], cmd)
            self.zpull(p.server, p.key,
                       out_view[p.offset:p.offset + p.length], cmd,
                       exact=True)  # dense: a short reply is an error

        futures = [self._pool.submit(one, p) for p in ctx.partitions]
        for f in futures:
            f.result()

    def push_pull_rowsparse(self, ctx: TensorContext, host2d: np.ndarray,
                            average: bool = True,
                            num_workers: Optional[int] = None) -> np.ndarray:
        """Row-sparse aggregation round (the op the reference reserves as
        kRowSparsePushPull but leaves unimplemented): push only the NONZERO
        rows of a [R, W] f32 gradient — [u32 nrows][u32 W][i32 ids]
        [f32 rows] per partition — the server scatter-adds them into the
        dense store, and the pull returns the dense aggregate. The tensor
        must be declared with row-aligned partitions
        (init_tensor(..., align_bytes=W*4))."""
        if self._closed:
            raise RuntimeError("push_pull_rowsparse on a closed PSClient")
        host2d = np.ascontiguousarray(host2d, np.float32)
        rows, width = host2d.shape
        row_bytes = width * 4
        self.ensure_init(ctx, host2d.nbytes)
        cmd_sparse = get_command_type(RequestType.ROW_SPARSE_PUSH_PULL,
                                      DataType.FLOAT32)
        cmd_dense = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                                     DataType.FLOAT32)
        nz = np.flatnonzero(np.any(host2d != 0, axis=1)).astype(np.int32)
        out = np.empty(rows * width, np.float32)

        def one(p: Partition):
            buf = build_rowsparse_payload(p, nz, host2d)
            self.zpush(p.server, p.key, buf, cmd_sparse)
            dst = out.view(np.uint8)[p.offset:p.offset + p.length]
            self.zpull(p.server, p.key, dst, cmd_dense, exact=True)

        futures = [self._pool.submit(one, p) for p in ctx.partitions]
        for f in futures:
            f.result()
        if average and num_workers and num_workers > 1:
            out /= num_workers
        return out.reshape(rows, width)

    def push_pull(self, ctx: TensorContext, flat: np.ndarray,
                  average: bool = True,
                  num_workers: Optional[int] = None,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        """Partitioned push+pull of one tensor; returns the summed
        (averaged) flat array. ``out``: optional preallocated result
        buffer (host staging arena); ignored on any mismatch."""
        if self._closed:
            raise RuntimeError("push_pull on a closed PSClient")
        dtype = flat.dtype
        self.ensure_init(ctx, flat.nbytes)
        from ..core.arena import usable_staging
        if not usable_staging(out, dtype, flat.nbytes):
            out = np.empty_like(flat)
        self._round_trip(ctx, flat, out)
        if average and num_workers and num_workers > 1:
            if np.issubdtype(dtype, np.integer):
                # truncation toward zero (the reference's C++
                # div_(size)); shared helper — exact incl. INT_MIN
                trunc_divide_inplace(out, num_workers)
            else:
                out /= num_workers
        return out

    def init_weights(self, ctx: TensorContext, flat: np.ndarray) -> None:
        """Async-mode bootstrap: init-push the worker's initial weights so
        the server's authoritative copy starts from them (the reference
        seeds the async store with the first init push,
        server.cc:266-295,434-436). Blocks until every worker has
        init-pushed (the per-key barrier); the first arrival's values win."""
        self.init_tensor(ctx, flat)

    def push_delta_pull_weights(self, ctx: TensorContext,
                                delta: np.ndarray) -> np.ndarray:
        """Asynchronous data parallelism (BYTEPS_ENABLE_ASYNC): push this
        worker's weight DELTA — the server folds it straight into the
        authoritative weights — and pull the current weights back, with no
        cross-worker aggregation barrier (reference: server.cc:315-319,
        torch/__init__.py:188-216). Requires the server to run in async
        mode; no averaging (each worker's delta applies in full)."""
        if self._closed:
            raise RuntimeError("push_delta_pull_weights on a closed PSClient")
        out = np.empty_like(delta)
        self._round_trip(ctx, delta, out)
        return out

    def close(self, shutdown_servers: bool = True) -> None:
        """``shutdown_servers=False`` = elastic suspend: drop the
        connections but leave servers running for resume (the reference's
        Finalize-without-terminate path, global.cc:319-403)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        # drain in-flight partition tasks BEFORE freeing the native client —
        # wait=False would leave pool threads calling into freed memory
        self._pool.shutdown(wait=True)
        # fail + drain fused completions and JOIN the reactor before the
        # native handle goes away (a reactor poll on a freed handle is a
        # use-after-free)
        self._stop_reactor()
        if shutdown_servers:
            try:
                self._lib.bps_client_shutdown(self._handle)
            except Exception:  # noqa: BLE001
                pass
        self._lib.bps_client_destroy(self._handle)


def connect_from_config(config: Config) -> PSClient:
    servers = server_addresses(config)
    if not servers:
        raise RuntimeError("num_servers > 0 but no server addresses")
    rank = (config.global_rank if config.global_rank is not None
            else config.worker_id * config.local_size + config.local_rank)
    log.info("connecting PS client: servers=%s worker=%d", servers, rank)
    return PSClient(servers, rank)
