"""DCN parameter-server worker client (placeholder — native transport lands
with byteps_tpu.server).

Reference equivalent: ps::KVWorker<char>::ZPush/ZPull over ps-lite
(3rdparty/ps-lite; used from byteps/common/core_loops.cc:571,609).
"""

from __future__ import annotations

from ..config import Config


def connect_from_config(config: Config):
    raise RuntimeError(
        "byteps_tpu DCN PS transport is not available yet in this build; "
        "set DMLC_NUM_SERVER=0 (pure ICI mode) or use init(lazy=True)")
