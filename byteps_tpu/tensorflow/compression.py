"""Adapter-level wire compression for the TensorFlow adapter.

Mirror of the reference's byteps/tensorflow/compression.py: a Compressor
casts the tensor before push_pull and restores it on the way back; fp16
halves wire bytes on the DCN PS hop. (The codec stack in
byteps_tpu.ops.compression is the heavy-weight path; this is the
adapter-level convenience knob, numpy-typed because the adapter's
transport is the numpy PS client.)
"""

from __future__ import annotations

import numpy as np


class Compressor:
    @staticmethod
    def compress(array: np.ndarray):
        """Return (compressed_array, ctx) — ctx is whatever decompress
        needs."""
        raise NotImplementedError

    @staticmethod
    def decompress(array: np.ndarray, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(array):
        return array, None

    @staticmethod
    def decompress(array, ctx):
        return array


class FP16Compressor(Compressor):
    """fp32/fp64 -> fp16 for the wire, restored on the way back
    (reference: tensorflow/compression.py)."""

    @staticmethod
    def compress(array):
        if array.dtype in (np.float32, np.float64):
            return array.astype(np.float16), array.dtype
        return array, None

    @staticmethod
    def decompress(array, ctx):
        if ctx is not None:
            return array.astype(ctx)
        return array


class Compression:
    """Selection surface matching the reference
    (``compression=bps.Compression.fp16``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
