"""MirroredStrategy cross-device-ops reroute.

The reference forks MirroredStrategy so its cross-device reduction runs
over byteps instead of NCCL all-reduce
(byteps/tensorflow/distribute/cross_device_ops.py:585-627). Here the
same effect needs no strategy fork: ``BytePSCrossDeviceOps`` subclasses
TF's ``ReductionToOneDevice`` — the LOCAL (intra-worker, cross-logical-
device) reduction stays TF's own — and splices the CROSS-WORKER hop
through the shared PS ``push_pull`` on the locally-reduced tensor,
rebuilding the mirrored per-device copies afterwards. Pass it straight
to the stock strategy:

    strategy = tf.distribute.MirroredStrategy(
        cross_device_ops=byteps_tpu.tensorflow.distribute
            .BytePSCrossDeviceOps())

Semantics: ReduceOp.SUM -> cross-worker sum of local sums (the global
sum); ReduceOp.MEAN -> cross-worker average of local means, which is
the global mean when every worker runs the same local replica count
(MirroredStrategy's own assumption). Without a PS configured the op
degrades to plain ReductionToOneDevice (single-worker identity).

Uses two ``tensorflow.python.distribute`` internals
(``cross_device_ops.ReductionToOneDevice``, ``values.Mirrored``) —
import-guarded; the adapter's public surface works without them.
"""

from __future__ import annotations

import tensorflow as tf
from tensorflow.python.distribute import cross_device_ops as _cdo
from tensorflow.python.distribute import values as _values

from . import push_pull
from ..core.state import get_state

__all__ = ["BytePSCrossDeviceOps"]


class BytePSCrossDeviceOps(_cdo.ReductionToOneDevice):
    """ReductionToOneDevice locally, PS push_pull across workers."""

    def _cross_worker(self, reduce_op, mirrored, name: str):
        state = get_state()
        if not state.initialized or state.ps_client is None:
            return mirrored  # single worker / no PS: local result is it
        vals = getattr(mirrored, "values", None)
        if vals is None:
            vals = (mirrored,)
        average = reduce_op == tf.distribute.ReduceOp.MEAN
        agg = push_pull(vals[0], scope="mirrored", name=name,
                        average=average)
        out = []
        for v in vals:
            with tf.device(v.device):
                out.append(tf.identity(agg))
        if len(out) == 1 and not isinstance(mirrored,
                                            _values.DistributedValues):
            return out[0]
        return _values.Mirrored(out)

    def reduce_implementation(self, reduce_op, per_replica_value,
                              destinations, options):
        local = super().reduce_implementation(
            reduce_op, per_replica_value, destinations, options)
        shape = "x".join(str(d) for d in getattr(
            per_replica_value, "shape", ()) or ())
        return self._cross_worker(reduce_op, local,
                                  f"mirrored/r.{shape or 'scalar'}")

    def batch_reduce_implementation(self, reduce_op,
                                    value_destination_pairs, options):
        local = super().batch_reduce_implementation(
            reduce_op, value_destination_pairs, options)
        # positional names: a train step batch-reduces its gradients in
        # a stable order, which keys the PS registry across steps
        return [self._cross_worker(reduce_op, m, f"mirrored/b.{i}")
                for i, m in enumerate(local)]
