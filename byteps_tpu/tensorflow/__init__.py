"""byteps_tpu.tensorflow — Horovod-compatible TensorFlow 2 adapter.

The reference's TF adapter (byteps/tensorflow/__init__.py) splices a
``BytepsPushPull`` AsyncOpKernel (ops.cc:167-231) into TF graphs and
wraps optimizers/tapes so every gradient is push_pulled before the
update. This rebuild keeps that public surface — ``push_pull``,
``broadcast``/``broadcast_variables``, ``DistributedGradientTape``,
``DistributedOptimizer``, handle-based async ops — with TF2-first
mechanics: eager tensors hop to numpy and ride the SAME priority-
scheduled PS pipeline as the JAX and torch adapters (core/scheduler.py
-> native TCP/shm client -> C++ server), so a third framework shares
one comm stack. Inside ``tf.function`` graphs the ops run through
``tf.py_function`` (the numpy transport is host-side either way).

Documented divergences from the reference:
- no custom TF C++ op kernel: the transport is already native C++
  behind ctypes; a py_function boundary replaces the AsyncOpKernel
  (graph-compile fusion of comm ops buys nothing on a host-side wire).
- ``tf.IndexedSlices`` gradients ride the ROW-SPARSE PS path (only
  nonzero rows on the push wire — push_pull_rowsparse) and come back
  dense, instead of the reference's all-gathered IndexedSlices.
- TF1 Session/graph-mode lives in ``byteps_tpu.tensorflow.v1``: the
  ``compute_gradients``-override ``DistributedOptimizer`` +
  ``broadcast_global_variables`` / ``BroadcastGlobalVariablesHook``
  (reference __init__.py:141-268), built on the same push_pull.

Single-worker (no PS configured) everything degrades to identity,
matching the reference's size()==1 behavior.

Reference parity map:
- push_pull / handle ops            <- tensorflow/ops.py, ops.cc:167-231
- DistributedGradientTape           <- tensorflow/__init__.py:343-417
- DistributedOptimizer (keras)      <- tensorflow/__init__.py:282-341,
                                       tensorflow/keras/__init__.py:40-64
- broadcast_variables               <- tensorflow/__init__.py:110-122
- keras callbacks                   <- tensorflow/keras/callbacks.py
"""

from __future__ import annotations

import itertools
import os
import weakref
from typing import Iterable, List, Optional

import numpy as np
import tensorflow as tf

from ..core.scheduler import Handle, HandleManager
from ..core.state import get_state
from .compression import Compression

__all__ = [
    "init", "shutdown", "suspend", "resume",
    "rank", "size", "local_rank", "local_size",
    "push_pull", "push_pull_async", "poll", "synchronize",
    "broadcast", "broadcast_variables",
    "DistributedGradientTape", "DistributedOptimizer", "load_model",
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "Compression",
]


def init(*args, **kwargs) -> None:
    get_state().init(*args, **kwargs)


def shutdown() -> None:
    get_state().shutdown()


def suspend() -> None:
    get_state().suspend()


def resume(num_workers: int, num_servers: int,
           global_rank: Optional[int] = None) -> None:
    get_state().resume(num_workers, num_servers, global_rank)


def rank() -> int:
    return get_state().rank()


def size() -> int:
    return get_state().size()


def local_rank() -> int:
    return get_state().local_rank()


def local_size() -> int:
    return get_state().local_size()


# --------------------------------------------------------------------- #
# handle-based async ops on the shared PS pipeline
# --------------------------------------------------------------------- #

# Adapter-owned handles (never the core's HandleManager): TF handles
# cannot collide with JAX-side ids, and the single-worker fast path
# needs no PS connection — same arrangement as the torch adapter.
_handles = HandleManager()


def _submit(host: np.ndarray, name: str, average: bool,
            priority: Optional[int]) -> Handle:
    state = get_state()
    if not state.initialized:
        raise RuntimeError(
            "byteps_tpu.tensorflow: init() must be called first")
    flat = np.ascontiguousarray(host).reshape(-1)
    handle = _handles.allocate(name)
    handle._shape = host.shape
    if state.scheduler is None:
        # single worker: sum over 1 contributor == identity
        handle._finish(flat.copy(), None)
        return handle
    from ..server.client import get_or_init_ctx
    ctx = get_or_init_ctx(state, name, flat)
    state.scheduler.submit(ctx, flat, handle, average,
                           state.config.num_workers,
                           version=state.next_version(name),
                           priority=priority)
    return handle


def _submit_rowsparse(host2d: np.ndarray, name: str,
                      average: bool) -> Handle:
    state = get_state()
    if not state.initialized:
        raise RuntimeError(
            "byteps_tpu.tensorflow: init() must be called first")
    host2d = np.ascontiguousarray(host2d, np.float32)
    handle = _handles.allocate(name)
    handle._shape = host2d.shape
    if state.scheduler is None:
        handle._finish(host2d.copy(), None)
        return handle
    from .. import _rowsparse_submit
    _rowsparse_submit(state, name, host2d, average, handle)
    return handle


# per-wrapper-instance scope ids: two optimizers/tapes in one process
# (e.g. GAN G/D) must not collide on positional PS keys — instance
# construction order is the cross-worker contract (same script, same
# order), exactly like declaration order for layer keys
_instance_ids = itertools.count()

def _metric_timeout_s() -> float:
    """Cross-worker metric averaging deadline (read per call so setting
    the env after import still works, like callbacks.py): a metric key
    logged by only one worker can never aggregate; fail loudly instead
    of hanging."""
    return float(os.environ.get("BYTEPS_METRIC_TIMEOUT_S", "60"))


def _auto_name(prefix: str, tensor) -> str:
    """Shape-derived default name. Names key the PS registry across
    steps, so repeated push_pulls of the same logical tensor MUST reuse
    one name: two distinct anonymous tensors of the same shape share a
    key (rounds serialize; multi-worker callers should pass ``name``
    explicitly, as the adapter's own tape/optimizer/broadcast paths
    do)."""
    shape = tuple(getattr(tensor, "shape", ()))
    if any(d is None for d in shape):
        raise ValueError(
            f"{prefix}: tensor has dynamic dims {shape} — auto-names "
            f"derive from the static shape, so pass an explicit name=")
    return f"{prefix}.{'x'.join(str(int(d)) for d in shape)}"


def _to_numpy(value) -> np.ndarray:
    if isinstance(value, np.ndarray):
        return value
    return value.numpy() if hasattr(value, "numpy") else np.asarray(value)


@tf.autograph.experimental.do_not_convert
def push_pull_async(tensor, name: str, average: bool = True,
                    priority: Optional[int] = None) -> int:
    """Submit an async push_pull of an eager tensor/ndarray; returns an
    int handle for poll()/synchronize() (reference: ops.py:48-85)."""
    return _submit(_to_numpy(tensor), name, average, priority).id


def poll(handle: int) -> bool:
    return _handles.poll(handle)


def synchronize(handle: int, timeout: Optional[float] = None) -> tf.Tensor:
    h = _handles.get(handle)
    flat = _handles.wait_and_clear(handle, timeout=timeout)
    return tf.constant(np.asarray(flat).reshape(h._shape))


def _push_pull_dense(host: np.ndarray, name: str, average: bool,
                     priority, compression) -> np.ndarray:
    wire, cctx = compression.compress(host)
    h = _submit(wire, name, average, priority)
    out = _handles.wait_and_clear(h.id).reshape(wire.shape)
    return compression.decompress(out, cctx)


@tf.autograph.experimental.do_not_convert
def push_pull(tensor, scope: str = "", average: bool = True,
              name: Optional[str] = None, priority: Optional[int] = None,
              compression=Compression.none, sparse_as_dense: bool = False):
    """Cross-worker sum (mean when ``average``) of a tf tensor through
    the PS (reference: tensorflow/__init__.py:40-90).

    ``tf.IndexedSlices`` input rides the row-sparse wire (nonzero rows
    only) unless ``sparse_as_dense``; the result is a DENSE tensor
    either way. Works eagerly and inside ``tf.function`` (py_function
    boundary)."""
    if isinstance(tensor, tf.IndexedSlices) and not tf.executing_eagerly():
        # graph mode: indices/values are symbolic — densify and take the
        # dense py_function path below. The row-sparse wire optimization
        # is eager-only (the reference's device_sparse path is its own
        # op kernel; here sparse_as_dense semantics apply in graphs).
        tensor = tf.convert_to_tensor(tensor)
    if isinstance(tensor, tf.IndexedSlices):
        dense_shape = [int(d) for d in tensor.dense_shape]
        nm = name or _auto_name(f"tfsparse/{scope or 'g'}", tensor.values)
        idx = _to_numpy(tensor.indices)
        vals = _to_numpy(tensor.values).astype(np.float32)
        host = np.zeros(dense_shape, np.float32)
        np.add.at(host, idx, vals)  # duplicate ids accumulate
        if sparse_as_dense or len(dense_shape) != 2:
            out = _push_pull_dense(host, nm, average, priority, compression)
            return tf.constant(out)
        h = _submit_rowsparse(host, nm, average)
        return tf.constant(np.asarray(_handles.wait_and_clear(h.id)))

    nm = name or _auto_name(f"tf/{scope or 'g'}", tensor)

    if tf.is_tensor(tensor) and not tf.executing_eagerly():
        # graph mode (inside tf.function): hop through py_function — the
        # transport is host-side numpy either way
        def _op(t):
            out = _push_pull_dense(t.numpy(), nm, average, priority,
                                   compression)
            return tf.constant(out)

        result = tf.py_function(_op, [tensor], Tout=tensor.dtype)
        result.set_shape(tensor.shape)
        return result

    out = _push_pull_dense(_to_numpy(tensor), nm, average, priority,
                           compression)
    return tf.constant(out)


# --------------------------------------------------------------------- #
# broadcast
# --------------------------------------------------------------------- #

def broadcast(value, root_rank: int, scope: str = "",
              name: Optional[str] = None) -> tf.Tensor:
    """Root's value to every worker: non-roots contribute zeros and the
    PS sum IS the broadcast (the torch adapter's arrangement; reference
    broadcasts via its BytepsBroadcast op)."""
    host = _to_numpy(value)
    nm = name or _auto_name(f"tfbcast/{scope or 'b'}", value)
    contrib = host if rank() == root_rank else np.zeros_like(host)
    h = _submit(contrib, nm, False, None)
    return tf.constant(_handles.wait_and_clear(h.id).reshape(host.shape))


def broadcast_variables(variables: Iterable, root_rank: int = 0,
                        scope: str = "") -> None:
    """Assign every variable to the root's value (reference:
    tensorflow/__init__.py:110-122) — run after building the model and
    before training so all workers start bit-identical."""
    if size() <= 1:
        return
    # submit ALL rounds first, then wait+assign: N sequential round
    # trips would serialize startup on sum-of-RTTs (the torch adapter's
    # broadcast_parameters arrangement)
    pending = []
    for i, var in enumerate(variables):
        host = _to_numpy(var.value())
        contrib = host if rank() == root_rank else np.zeros_like(host)
        h = _submit(contrib, f"tfbcast/{scope or 'v'}/{i}", False, None)
        pending.append((var, h, host.shape))
    for var, h, shape in pending:
        var.assign(_handles.wait_and_clear(h.id).reshape(shape))


# --------------------------------------------------------------------- #
# DistributedGradientTape / DistributedOptimizer
# --------------------------------------------------------------------- #

# last live wrapper per auto-derived scope: detects the GAN G/D hazard
# (two concurrently-training models with identical gradient signatures
# silently cross-summing on shared PS keys). Weak values so the normal
# rebuild-the-tape-every-step pattern — where the previous wrapper is
# dead before the new one resolves — does not false-positive.
_AUTO_SCOPES = weakref.WeakValueDictionary()
_AUTO_SCOPE_WARNED: set = set()


class _TapeWrapper:
    """Wraps a tf.GradientTape: gradient() push_pulls every gradient
    before returning it (reference: _DistributedGradientTape,
    tensorflow/__init__.py:343-417 — same contract, delegation instead
    of dynamic subclassing)."""

    def __init__(self, tape, compression, sparse_as_dense: bool,
                 scope: Optional[str] = None):
        self._tape = tape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._scope = scope  # None -> derived from the gradient shapes

    def _resolve_scope(self, flat) -> str:
        """Stable per-LOGICAL-tape scope: tapes are typically
        re-constructed every step (the documented wrapping pattern), so
        an instance counter would mint fresh PS keys each step and grow
        the registry/server without bound; deriving the scope from the
        gradient shape signature keeps keys stable across steps and
        workers while two different models (e.g. GAN G/D) still get
        distinct scopes. Two models with IDENTICAL shape signatures must
        pass an explicit ``scope=`` to DistributedGradientTape."""
        if self._scope is None:
            import hashlib

            sig = repr([None if g is None else
                        (str(getattr(g, "shape", ())),
                         str(getattr(g, "dtype", "")))
                        for g in flat])
            digest = hashlib.md5(sig.encode()).hexdigest()[:10]
            scope = f"tfgrad_{digest}"
            holder = _AUTO_SCOPES.get(scope)
            if (holder is not None and holder is not self
                    and scope not in _AUTO_SCOPE_WARNED):
                _AUTO_SCOPE_WARNED.add(scope)
                import warnings

                warnings.warn(
                    f"two live DistributedGradientTape instances resolved "
                    f"the same auto-derived scope {scope!r} (identical "
                    f"gradient shape/dtype signatures). If these wrap "
                    f"DIFFERENT models (e.g. GAN G/D) they share PS keys "
                    f"and concurrent rounds will cross-sum — pass an "
                    f"explicit scope= to each tape. Sequential reuse on "
                    f"one model (gradient accumulation) is benign.",
                    RuntimeWarning, stacklevel=3)
            _AUTO_SCOPES[scope] = self
            self._scope = scope
        return self._scope

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    # do_not_convert: the reduce chain below is pure HOST python (numpy
    # transport, scheduler handles, py_function nodes for graph-mode
    # tensors) — AutoGraph gains nothing converting it, and letting it
    # descend is fragile: whole-suite runs have seen it mis-convert the
    # bound next_version() deep in the chain into a nullary call
    # ("tf__next_version() missing 2 required positional arguments"),
    # failing the trace. Pinning the boundary here stops the descent.
    @tf.autograph.experimental.do_not_convert
    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        if size() <= 1:
            return grads
        flat = tf.nest.flatten(grads)
        out = _reduce_grads(flat, self._compression,
                            self._sparse_as_dense,
                            scope=self._resolve_scope(flat))
        return tf.nest.pack_sequence_as(grads, out)


def DistributedGradientTape(gradtape, compression=Compression.none,
                            sparse_as_dense: bool = False,
                            device_dense: str = "", device_sparse: str = "",
                            op=None, scope: Optional[str] = None):
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns
    cross-worker-averaged gradients. ``device_*``/``op`` accepted for
    reference signature compatibility (devices are meaningless on the
    host-side wire; the reduction is always average)."""
    del device_dense, device_sparse, op
    return _TapeWrapper(gradtape, compression, sparse_as_dense, scope)


def _eager_sparse_submit(g, nm: str, compression, sparse_as_dense: bool):
    """Submit phase for an eager IndexedSlices gradient (densified; rides
    the row-sparse wire when 2D); returns resolve() -> dense tf.Tensor."""
    dense_shape = [int(d) for d in g.dense_shape]
    idx = _to_numpy(g.indices)
    vals = _to_numpy(g.values).astype(np.float32)
    host = np.zeros(dense_shape, np.float32)
    np.add.at(host, idx, vals)  # duplicate ids accumulate
    if sparse_as_dense or len(dense_shape) != 2:
        wire, cctx = compression.compress(host)
        h = _submit(wire, nm, True, None)
        shape = wire.shape

        def resolve():
            out = _handles.wait_and_clear(h.id).reshape(shape)
            return tf.constant(compression.decompress(out, cctx))

        return resolve
    h = _submit_rowsparse(host, nm, True)

    def resolve():
        return tf.constant(np.asarray(_handles.wait_and_clear(h.id)))

    return resolve


def _eager_dense_submit(g, nm: str, compression):
    """Submit phase of an eager dense push_pull; returns resolve()."""
    host = _to_numpy(g)
    wire, cctx = compression.compress(host)
    h = _submit(wire, nm, True, None)
    shape = wire.shape

    def resolve():
        out = _handles.wait_and_clear(h.id).reshape(shape)
        return tf.constant(compression.decompress(out, cctx))

    return resolve


def _reduce_grads(grads: List, compression, sparse_as_dense: bool,
                  scope: str = "tfopt") -> List:
    """push_pull every non-None gradient under stable position names,
    submit-all-then-drain: an eager step pays one round-trip depth
    instead of sum-of-RTTs over the layer count (the same argument
    broadcast_variables makes for startup, applied to the hot path).

    Graph mode batches EVERY dense gradient into a SINGLE py_function
    that submits all, waits once, then drains all. One hop instead of
    one per tensor: each py_function re-enters Python under the GIL —
    measured numbers and the 1-core caveat live in docs/tensorflow.md
    (examples/benchmark_tf_hop.py; the reference avoids the hop
    entirely with a native AsyncOpKernel, ops.cc:167-231 — the batched
    boundary is this rebuild's equivalent, same shape as
    broadcast_global_variables). ``scope`` is per-wrapper-instance
    (see _instance_ids).
    """
    if size() <= 1:
        return list(grads)
    out: List = [None] * len(grads)
    pending = []      # (slot, resolve) — eager submits, drained below
    graph_batch = []  # (slot, name, dense tensor) — ONE py_function
    for i, g in enumerate(grads):
        nm = f"{scope}/{i}"
        if g is None:
            continue
        if isinstance(g, tf.IndexedSlices) and tf.executing_eagerly():
            # eager sparse: same submit/resolve split as the dense path —
            # a blocking push_pull here would re-serialize every later
            # gradient behind the sparse round trip
            pending.append((i, _eager_sparse_submit(g, nm, compression,
                                                    sparse_as_dense)))
        elif (isinstance(g, tf.IndexedSlices)
              or (tf.is_tensor(g) and not tf.executing_eagerly())):
            # graph mode: symbolic IndexedSlices densify (the row-sparse
            # wire is eager-only, see push_pull) and join the batch
            if isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            graph_batch.append((i, nm, g))
        else:
            pending.append((i, _eager_dense_submit(g, nm, compression)))
    if graph_batch:
        results = _graph_batch_push_pull(
            [(nm, t) for _, nm, t in graph_batch], compression)
        for (slot, _, _), res in zip(graph_batch, results):
            out[slot] = res
    for slot, resolve in pending:
        out[slot] = resolve()
    return out


def _graph_batch_push_pull(named: List, compression) -> List:
    """ONE ``tf.py_function`` averaging a whole list of ``(name, dense
    symbolic tensor)`` pairs: the op body submits every tensor through
    the scheduler, parks once on a single batched GIL-releasing wait,
    then converts — one Python/GIL hop per STEP instead of per tensor
    (examples/benchmark_tf_hop.py measures this exact function;
    numbers + the 1-core caveat in docs/tensorflow.md). Shared by the
    TF2 tape/optimizer reduction and the TF1 ``compute_gradients``
    override."""
    if not named:
        return []
    names = [nm for nm, _ in named]

    def _op(*tensors):
        import threading

        subs = []
        try:
            for nm, t in zip(names, tensors):
                wire, cctx = compression.compress(t.numpy())
                subs.append((_submit(wire, nm, True, None), wire.shape,
                             cctx))
            # ONE batched wait for the whole gradient set: every handle
            # counts down a single event via its done-callback, and this
            # thread parks on that event once — releasing the GIL for
            # the full drain window — instead of the former serial
            # wait-then-decompress loop, which re-took the GIL between
            # every handle and serialized each decompress behind the
            # NEXT handle's wait (the +69%-over-raw-floor hop,
            # examples/benchmark_tf_hop.py). The decompress loop below
            # then runs over already-resolved handles with zero waiting.
            all_done = threading.Event()
            pending = [len(subs)]
            pending_mu = threading.Lock()

            def _one_done():
                with pending_mu:
                    pending[0] -= 1
                    if pending[0] == 0:
                        all_done.set()

            for h, _, _ in subs:
                h.add_done_callback(_one_done)
            all_done.wait(timeout=600)
            return [tf.constant(compression.decompress(
                        _handles.wait_and_clear(h.id).reshape(shape),
                        cctx))
                    for h, shape, cctx in subs]
        except Exception:
            # a mid-batch failure (submit, wait, or decompress) must not
            # strand the sibling handles: each holds a gradient-sized
            # result buffer in _handles for the life of the process
            # (the MetricAverageCallback leak class, fixed the same way)
            for h, _, _ in subs:
                _handles.discard(h.id)
            raise

    results = tf.py_function(_op, [t for _, t in named],
                             Tout=[t.dtype for _, t in named])
    if not isinstance(results, (list, tuple)):
        results = [results]
    results = list(results)
    for (_, t), res in zip(named, results):
        res.set_shape(t.shape)
    return results


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none,
                         sparse_as_dense: bool = False,
                         device_dense: str = "", device_sparse: str = "",
                         backward_passes_per_step: int = 1, op=None):
    """A REAL keras optimizer (dynamic subclass of the given optimizer's
    class, recreated via from_config — the reference's wrap_optimizer
    arrangement, keras/__init__.py:40-64) whose gradient application
    cross-worker-averages first. Being an actual Optimizer subclass, it
    passes ``model.compile(optimizer=...)`` type validation.

    Keras 3 routes ``apply_gradients`` through ``apply``, so only
    ``apply`` is overridden there (overriding both would reduce twice);
    optimizers predating ``apply`` get ``apply_gradients`` overridden
    instead. ``backward_passes_per_step>1`` is not supported, matching
    the reference's keras branch."""
    del name, device_dense, device_sparse, op
    if backward_passes_per_step != 1:
        raise ValueError("backward_passes_per_step > 1 is not supported "
                         "with keras optimizers (reference parity)")
    base = type(optimizer)

    if hasattr(base, "apply"):
        def _apply(self, grads, trainable_variables=None, **kwargs):
            grads = _reduce_grads(list(grads), self._bps_compression,
                                  self._bps_sparse_as_dense,
                                  scope=self._bps_scope)
            if trainable_variables is None:
                return base.apply(self, grads, **kwargs)
            return base.apply(self, grads, trainable_variables, **kwargs)

        overrides = {"apply": _apply}
    else:
        def _apply_gradients(self, grads_and_vars, *args, **kwargs):
            pairs = list(grads_and_vars)
            grads = _reduce_grads([g for g, _ in pairs],
                                  self._bps_compression,
                                  self._bps_sparse_as_dense,
                                  scope=self._bps_scope)
            return base.apply_gradients(
                self, [(g, v) for g, (_, v) in zip(grads, pairs)],
                *args, **kwargs)

        overrides = {"apply_gradients": _apply_gradients}

    cls = type("Distributed" + base.__name__, (base,), overrides)
    new = cls.from_config(optimizer.get_config())
    new._bps_compression = compression
    new._bps_sparse_as_dense = sparse_as_dense
    new._bps_scope = f"tfopt{next(_instance_ids)}"
    return new


def load_model(filepath, custom_objects=None,
               compression=Compression.none):
    """Load a saved keras model and re-wrap its optimizer as a
    ``DistributedOptimizer`` (reference: keras/__init__.py:102-133
    ``load_model`` re-wrapping on deserialize). The wrap recreates the
    optimizer via from_config, so resumed SLOT state starts fresh —
    broadcast variables after loading, as the callbacks do."""
    model = tf.keras.models.load_model(filepath,
                                       custom_objects=custom_objects)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        wrapped = DistributedOptimizer(opt, compression=compression)
        # preserve the saved compile settings (metrics, loss_weights,
        # weighted_metrics...) — recompiling with only optimizer+loss
        # would silently drop them; get_compile_config carries the full
        # serialized set and compile() deserializes its entries
        kw = {}
        try:
            ccfg = dict(model.get_compile_config() or {})
        except Exception:  # noqa: BLE001 - older keras: no compile cfg
            ccfg = {}
        for key in ("metrics", "loss_weights", "weighted_metrics",
                    "jit_compile", "steps_per_execution"):
            if ccfg.get(key) is not None:
                kw[key] = ccfg[key]
        loss = ccfg.get("loss", getattr(model, "loss", None))
        model.compile(optimizer=wrapped, loss=loss, **kw)
    return model


# --------------------------------------------------------------------- #
# keras callbacks (reference: tensorflow/keras/callbacks.py)
# --------------------------------------------------------------------- #

class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast model + optimizer variables from root at train begin so
    every worker starts from identical state."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, logs=None):
        if self._done or size() <= 1:
            return
        variables = list(self.model.variables)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and hasattr(opt, "variables"):
            v = opt.variables
            variables += list(v() if callable(v) else v)
        broadcast_variables(variables, self.root_rank, scope="fit")
        self._done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics across workers before they reach downstream
    callbacks (checkpointing/early stopping must agree on the value)."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs or size() <= 1:
            return
        hs = {k: _submit(np.asarray([logs[k]], np.float32),
                         f"tfmetric/{k}", True, None)
              for k in sorted(logs)}
        for k, h in hs.items():
            timeout = _metric_timeout_s()
            try:
                out = _handles.wait_and_clear(h.id, timeout=timeout)
            except TimeoutError as e:
                # fatal for this epoch's metrics; nothing retries — drop
                # all sibling handles so their buffers don't leak
                for h2 in hs.values():
                    _handles.discard(h2.id)
                raise TimeoutError(
                    f"metric {k!r}: cross-worker average timed out after "
                    f"{timeout:.0f}s — every worker must log "
                    f"the SAME metric keys each epoch (a key logged by "
                    f"one worker alone can never aggregate); "
                    f"BYTEPS_METRIC_TIMEOUT_S overrides") from e
            logs[k] = float(np.asarray(out)[0])
