"""TF1 graph-mode surface (reference byteps/tensorflow/__init__.py:141-268):
the ``compute_gradients``-override ``DistributedOptimizer`` (a
``tf.compat.v1.train.Optimizer``) plus ``broadcast_global_variables`` /
``BroadcastGlobalVariablesHook`` for Session-based training — the legacy
API the reference still ships. Built on the same ``push_pull`` as the TF2
adapter: inside a v1 graph it lowers to a ``py_function`` hop into the
host scheduler, so Sessions, ``MonitoredTrainingSession`` and estimators
drive the real comm path.

Usage (classic v1 shape):

    import byteps_tpu.tensorflow as bps
    from byteps_tpu.tensorflow import v1 as bps_v1
    bps.init()
    opt = bps_v1.DistributedOptimizer(
        tf.compat.v1.train.GradientDescentOptimizer(0.1))
    train_op = opt.minimize(loss)          # compute_gradients push_pulls
    hooks = [bps_v1.BroadcastGlobalVariablesHook(root_rank=0)]
    with tf.compat.v1.train.MonitoredTrainingSession(hooks=hooks) as sess:
        sess.run(train_op)

Async mode (BYTEPS_ENABLE_ASYNC, reference __init__.py:246-268):
``compute_gradients`` returns raw local gradients and ``apply_gradients``
pushes the post-step WEIGHT DELTA through the server's async store.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from . import (
    Compression, _handles, _submit, push_pull, rank, size,
)


def _enable_async() -> bool:
    from ..core.state import get_state

    return bool(get_state().config.enable_async)


def _distributed() -> bool:
    """True when gradient traffic must hit the wire: more than one
    worker OR a PS scheduler is connected (BYTEPS_FORCE_DISTRIBUTED
    single-worker runs exercise the full path — the torch adapter's
    gate, torch/__init__.py)."""
    from ..core.state import get_state

    return size() > 1 or get_state().scheduler is not None


def broadcast_global_variables(root_rank: int = 0) -> tf.Operation:
    """A graph op that assigns every ``tf.compat.v1.global_variables()``
    entry to the root's value (reference __init__.py:117-127). ONE
    py_function broadcasts all variables (submit-all-then-wait, so
    startup costs one round-trip depth, and cross-worker op scheduling
    differences can't interleave per-variable rounds)."""
    gvars = tf.compat.v1.global_variables()
    if not gvars or not _distributed():
        return tf.no_op()

    def _bcast_all(*vals):
        pending = []
        for i, v in enumerate(vals):
            host = v.numpy()
            contrib = host if rank() == root_rank \
                else np.zeros_like(host)
            pending.append((_submit(contrib, f"tf1bcast/{i}", False, None),
                            host.shape, host.dtype))
        return [_handles.wait_and_clear(h.id).reshape(shape).astype(dt)
                for h, shape, dt in pending]

    outs = tf.py_function(_bcast_all, [v.value() for v in gvars],
                          Tout=[v.dtype for v in gvars])
    if len(gvars) == 1:  # py_function unwraps single-element lists
        outs = [outs]
    assigns = [
        tf.compat.v1.assign(v, tf.reshape(o, tf.shape(v)))
        for v, o in zip(gvars, outs)
    ]
    return tf.group(*assigns)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """SessionRunHook broadcasting all global variables from ``root_rank``
    after session creation (reference __init__.py:141-173) — consistent
    init whether training starts from random weights or a checkpoint."""

    def __init__(self, root_rank: int, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.device = device
        self.bcast_op: Optional[tf.Operation] = None

    def begin(self):
        if (self.bcast_op is None
                or self.bcast_op.graph is not
                tf.compat.v1.get_default_graph()):
            with tf.device(self.device) if self.device \
                    else tf.control_dependencies([]):
                self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


class DistributedOptimizer(tf.compat.v1.train.Optimizer):
    """v1 optimizer wrapper: ``compute_gradients`` push_pulls every
    gradient before returning it (reference __init__.py:186-240), so
    ``minimize``/estimator training loops distribute without other code
    changes. ``apply_gradients`` delegates — except in async mode, where
    it pushes the post-step weight delta instead (reference
    __init__.py:246-268)."""

    def __init__(self, optimizer, name: Optional[str] = None,
                 use_locking: bool = False,
                 compression=Compression.none,
                 sparse_as_dense: bool = False):
        if name is None:
            name = "Distributed{}".format(type(optimizer).__name__)
        super().__init__(name=name, use_locking=use_locking)
        self._optimizer = optimizer
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._seeded: set = set()  # async store: names already init-pushed

    def compute_gradients(self, *args, **kwargs):
        gradients = self._optimizer.compute_gradients(*args, **kwargs)
        if not _distributed() or _enable_async():
            # async: raw local grads; the delta push happens in
            # apply_gradients against the server's authoritative weights
            return gradients
        if tf.executing_eagerly():
            # eager compat use: keep the per-tensor push_pull — it is
            # what routes eager IndexedSlices onto the row-sparse wire
            # (nonzero rows only) and honors sparse_as_dense
            return [(g if g is None else push_pull(
                        g, scope=self._name, average=True,
                        name="tf1grad/" + v.name.replace(":", "_"),
                        compression=self._compression,
                        sparse_as_dense=self._sparse_as_dense), v)
                    for g, v in gradients]
        # graph mode: ONE py_function for the whole gradient list
        # (submit-all-then-drain inside the op) instead of one per
        # tensor — each hop re-enters Python under the GIL, measured
        # +112% per-tensor vs +69% batched on a ResNet-50-shaped set
        # (examples/benchmark_tf_hop.py). Symbolic IndexedSlices densify
        # first (the row-sparse wire is eager-only, as in push_pull).
        from . import _graph_batch_push_pull

        batch = []
        for grad, var in gradients:
            if grad is not None:
                if isinstance(grad, tf.IndexedSlices):
                    grad = tf.convert_to_tensor(grad)
                batch.append(("tf1grad/" + var.name.replace(":", "_"),
                              grad))
        reduced = iter(_graph_batch_push_pull(batch, self._compression))
        return [(None if grad is None else next(reduced), var)
                for grad, var in gradients]

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        from ..core.state import get_state

        if (not _enable_async() or not _distributed()
                or get_state().ps_client is None):
            # async without a PS has no authoritative store to fold
            # deltas into — degrade to the plain optimizer (the module
            # contract: single-worker/no-PS is identity; the ps_client
            # guard keeps multi-process ICI runs off the delta path,
            # where summing deltas would destroy the weights)
            if (_enable_async() and _distributed()
                    and get_state().ps_client is None):
                # loudly: compute_gradients also skipped averaging (the
                # async gate), so this configuration trains fully
                # UNSYNCHRONIZED — each worker diverges independently
                from ..utils.logging import log

                log.warning(
                    "BYTEPS_ENABLE_ASYNC with multiple workers but no "
                    "PS configured: gradients are neither averaged nor "
                    "folded into an async store — training is local-"
                    "only. Configure DMLC_NUM_SERVER/DMLC_PS_ROOT_* or "
                    "unset BYTEPS_ENABLE_ASYNC.")
            return self._optimizer.apply_gradients(grads_and_vars, *args,
                                                   **kwargs)
        # async DP: apply locally, then push the weight DELTA — the
        # server folds it into the authoritative weights and the pull
        # returns them (no aggregation barrier). The store must be
        # SEEDED with pre-update weights on each tensor's first step
        # (the reference's first init push, server.cc:266-295): the
        # generic push_pull path init-pushes ZEROS, which would make the
        # pull return bare delta sums and silently destroy the model —
        # so this path rides client.init_weights +
        # push_delta_pull_weights directly, like the jax
        # (jax/train.py make_async_ps_train_step) and mxnet async
        # siblings. The delta wire is uncompressed, also like them.
        gv = list(grads_and_vars)
        # frozen variables (grad None) never change, so their delta is
        # identically zero — skip the per-step seed + round trip
        tvars = [v for g, v in gv if g is not None]
        # tf.identity snapshots, and apply_op is built UNDER a control
        # dependency on them: raw v1 graphs have no auto control edges
        # (unlike tf.function), so without this the Session could read a
        # variable AFTER the optimizer update and push a zero delta
        old = [tf.identity(v) for v in tvars]
        with tf.control_dependencies(old):
            apply_op = self._optimizer.apply_gradients(gv, *args,
                                                       **kwargs)
        names = ["tf1delta/" + v.name.replace(":", "_") for v in tvars]
        with tf.control_dependencies([apply_op]):
            # Seed ALL stores in ONE py_function, in variable order,
            # BEFORE any delta round trip: init_weights blocks until
            # every worker init-pushes that key, and the per-variable
            # py_functions run in executor order — nondeterministic
            # across workers — so lazy per-variable seeding could
            # cross-block on disjoint keys (worker 0 parked on key A,
            # worker 1 on key B). A single deterministic seeding pass
            # makes every worker hit the barriers in the same order.
            # Idempotent: after the first step it is a no-op hop.
            seed_op = self._seed_all_op(names, old)
            assigns = []
            with tf.control_dependencies([seed_op]):
                for v, o, name in zip(tvars, old, names):
                    delta = tf.subtract(v, o)
                    updated = self._async_delta(delta, name)
                    assigns.append(tf.compat.v1.assign(v, updated))
            return tf.group(*assigns)

    def _seed_all_op(self, names, olds):
        def _seed(*o_ts):
            from ..core.state import get_state
            from ..server.client import get_or_init_ctx

            state = get_state()
            client = state.ps_client
            for name, o_t in zip(names, o_ts):
                if name in self._seeded:
                    continue
                host_o = np.ascontiguousarray(o_t.numpy(),
                                              np.float32).reshape(-1)
                ctx = get_or_init_ctx(state, name, host_o)
                client.init_weights(ctx, host_o)
                self._seeded.add(name)
            return np.int32(0)

        # gate behind tf.cond: a bare py_function would fetch EVERY
        # variable's pre-update snapshot host-side on every step
        # (full-weights D2H per step forever) just to no-op. The pred is
        # a no-input scalar py_function reading the python-side seeded
        # set, so after step 1 the untaken branch's seeding py_function
        # never executes and no weight snapshot crosses to the host.
        # (Deliberately not a tf.Variable flag: that would ride the
        # GLOBAL_VARIABLES collection into broadcast/initializer paths.)
        pred = tf.py_function(
            lambda: np.bool_(len(self._seeded) >= len(names)), [],
            Tout=tf.bool)

        def _do_seed():
            op = tf.py_function(_seed, list(olds), Tout=tf.int32)
            with tf.control_dependencies([op]):
                return tf.constant(0, tf.int32)

        return tf.cond(pred, lambda: tf.constant(0, tf.int32), _do_seed)

    def _async_delta(self, delta, name: str):
        """One py_function hop per variable: push the post-step weight
        delta and pull the server's authoritative weights (the store was
        seeded by _seed_all_op)."""

        def _op(d_t):
            from ..core.state import get_state
            from ..server.client import get_or_init_ctx

            state = get_state()
            client = state.ps_client
            host_d = np.ascontiguousarray(d_t.numpy(),
                                          np.float32).reshape(-1)
            ctx = get_or_init_ctx(state, name, host_d)
            out = client.push_delta_pull_weights(ctx, host_d)
            state.telemetry.record_round_trip(out.nbytes)
            return tf.constant(
                out.reshape(tuple(d_t.shape)).astype(
                    d_t.dtype.as_numpy_dtype()))

        result = tf.py_function(_op, [delta], Tout=delta.dtype)
        result.set_shape(delta.shape)
        return result

    # --- pure delegation (reference __init__.py:270-292) ------------- #

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)
