"""Leveled logging for byteps_tpu.

TPU-native counterpart of the reference's BPS_LOG / BPS_CHECK macros
(reference: byteps/common/logging.h:26,90-94). Level is taken from
``BYTEPS_LOG_LEVEL`` (TRACE, DEBUG, INFO, WARNING, ERROR, FATAL); default
WARNING, matching the reference.
"""

from __future__ import annotations

import logging
import os
import sys

TRACE = 5
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "TRACE": TRACE,
    "DEBUG": logging.DEBUG,
    "INFO": logging.INFO,
    "WARNING": logging.WARNING,
    "ERROR": logging.ERROR,
    "FATAL": logging.CRITICAL,
}


def _make_logger() -> logging.Logger:
    logger = logging.getLogger("byteps_tpu")
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s] BYTEPS %(levelname)s %(message)s")
        )
        logger.addHandler(handler)
    level_name = os.environ.get("BYTEPS_LOG_LEVEL", "WARNING").upper()
    logger.setLevel(_LEVELS.get(level_name, logging.WARNING))
    logger.propagate = False
    return logger


log = _make_logger()


def debug_sample(config, name: str, stage: str, arr, dtype=None) -> None:
    """Per-stage tensor value sampling (reference: BYTEPS_DEBUG_SAMPLE_TENSOR,
    core_loops.cc:37-67): when the configured substring matches ``name``,
    print the first/last element at this pipeline stage. ``arr`` may be a
    raw uint8 view; pass ``dtype`` (numpy dtype) to reinterpret."""
    needle = getattr(config, "debug_sample_tensor", "")
    if not needle or needle not in name:
        return
    import numpy as np

    flat = np.asarray(arr).reshape(-1)
    if dtype is not None and flat.dtype == np.uint8:
        flat = flat.view(dtype)
    if flat.size == 0:
        log.info("[sample] %s @%s: <empty>", name, stage)
        return
    log.info("[sample] %s @%s: n=%d first=%s last=%s", name, stage,
             flat.size, flat[0], flat[-1])


def bps_check(cond: bool, msg: str = "") -> None:
    """Equivalent of BPS_CHECK: raise on failed invariant."""
    if not cond:
        log.critical("check failed: %s", msg)
        raise AssertionError(f"BPS_CHECK failed: {msg}")


def refresh_level() -> None:
    """Re-read BYTEPS_LOG_LEVEL (used by init() so env set after import works)."""
    level_name = os.environ.get("BYTEPS_LOG_LEVEL", "WARNING").upper()
    log.setLevel(_LEVELS.get(level_name, logging.WARNING))
