"""Chrome-trace communication timeline + fleet-fused dump.

Reference: BYTEPS_TRACE_ON dumps per-(tensor, stage) spans to
``trace_dir/<local_rank>/comm.json`` in Chrome trace-event format
(byteps/common/global.cc:448-564, docs/timeline.md). We reproduce the same
file format, and additionally mirror spans into jax.profiler trace
annotations so they appear in TensorBoard/Perfetto device traces.

Beyond the reference: ``Tracer.dump()`` emits ONE fused timeline — the
worker's PUSH/PULL spans plus every server's wire-sampled stage spans
(recv → queue-wait → fold → reply, drained over the TRACE_DRAIN control
op), clock-aligned via NTP-style offset estimation
(``estimate_clock_offset``) and rid-linked with Chrome flow events, so
a slow round is attributable to a specific server stage on a single
timeline (docs/timeline.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..config import Config

# synthetic pid base for server rows in the fused timeline (worker
# events keep the real os.getpid(); Chrome/Perfetto group rows by pid)
_SERVER_PID_BASE = 1000000


def estimate_clock_offset(
        samples: Sequence[Tuple[int, int, int, int]]) -> Tuple[int, int]:
    """NTP-style clock-offset estimate from request/reply timestamp
    echoes. Each sample is ``(t0, t1, t2, t3)``: client send, server
    recv, server send, client recv — t0/t3 on the client's steady
    clock, t1/t2 on the server's. For one sample the classic estimate
    is ``offset = ((t1 - t0) + (t2 - t3)) / 2`` with the true offset
    guaranteed inside ``± rtt/2`` where ``rtt = (t3-t0) - (t2-t1)``
    (the bound is tight under asymmetric path delay — one direction
    may consume the whole rtt). Across samples the MINIMUM-rtt probe
    carries the tightest bound, so that one decides.

    Returns ``(offset_ns, err_bound_ns)`` with
    ``server_clock - offset ≈ client_clock``.
    """
    if not samples:
        raise ValueError("estimate_clock_offset needs >= 1 sample")
    best = None
    for t0, t1, t2, t3 in samples:
        rtt = (t3 - t0) - (t2 - t1)
        if rtt < 0:
            continue  # nonsensical echo (clock step mid-probe): skip
        off = ((t1 - t0) + (t2 - t3)) // 2
        if best is None or rtt < best[1]:
            best = (off, rtt)
    if best is None:
        raise ValueError("every probe had negative rtt — broken echoes")
    # bound: half the round trip, plus 1ns so a zero-rtt synthetic
    # sample still reports a nonzero, honest uncertainty
    return int(best[0]), int(best[1] // 2 + 1)


class Tracer:
    def __init__(self, config: Config):
        self._config = config
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._step = 0
        self._t0_ns = time.monotonic_ns()
        # (tensor, stage) -> (start_us, entered TraceAnnotation or
        # None, extra args dict or None, span seq)
        self._open_spans: Dict[tuple, tuple] = {}
        # (tensor, stage) -> (seq, most recently RECORDED event dict):
        # a late annotate() (rid racing a fast reply's end()) patches
        # the event instead of vanishing; bounded by distinct spans
        self._last_closed: Dict[tuple, tuple] = {}
        # per-begin incarnation counter: annotate() callers hold the
        # token of the span THEY opened, so a late annotate can never
        # stamp the NEXT round's span for the same key
        self._span_seq = 0
        # fused-dump hook (core/state.py): () -> [{"server": idx,
        # "offset_ns": o, "err_ns": e, "records": [TraceRec dicts]}]
        self._server_collector: Optional[Callable[[], list]] = None

    def _us(self) -> float:
        return (time.monotonic_ns() - self._t0_ns) / 1e3

    def _active(self) -> bool:
        return (self._config.trace_on and
                self._config.trace_start_step <= self._step <= self._config.trace_end_step)

    def step(self) -> None:
        do_flush = False
        with self._lock:
            self._step += 1
            if self._step == self._config.trace_end_step + 1:
                do_flush = True
        if do_flush:
            self.flush()

    def begin(self, name: str, stage: str,
              cross_thread: bool = False) -> Optional[int]:
        """Mark the start of a (tensor, stage) span
        (reference: scheduled_queue.cc:105-123). begin/end normally pair
        on ONE thread (the stage's pool thread), which lets the span
        mirror into a jax.profiler.TraceAnnotation — visible in
        Perfetto/TensorBoard when a jax profiler trace is running
        (BYTEPS_JAX_PROFILER_DIR). ``cross_thread=True`` declares that
        end() will run on a DIFFERENT thread (the fused wire op: begin
        on the stage thread, end in the completion reactor) — the
        Chrome-trace event still records, but the TraceAnnotation
        mirror is skipped, since annotations stack per thread and an
        exit on another thread would unwind someone else's stack.

        Returns this span incarnation's token (None when nothing was
        opened) — pass it to ``annotate`` so a late annotation can
        never land on a LATER span of the same key."""
        # annotations mirror whenever a profiler dir is configured —
        # independent of the Chrome-trace window, which only gates the
        # comm.json events (a profiler session spans init()->shutdown())
        mirror = bool(self._config.jax_profiler_dir) and not cross_thread
        if not (mirror or self._active()):
            return None
        with self._lock:
            prev = self._open_spans.pop((name, stage), None)
        if prev is not None and prev[1] is not None:
            # double-begin without an end: close the orphan annotation
            # BEFORE entering the new one (annotations stack per thread;
            # exiting it later would unwind out of order and every
            # subsequent annotation would nest inside the orphan)
            try:
                prev[1].__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        ann = None
        if mirror:
            try:
                import jax
                ann = jax.profiler.TraceAnnotation(f"bps:{stage}:{name}")
                ann.__enter__()
            except Exception:  # noqa: BLE001 - profiler mirroring is aux
                ann = None
        with self._lock:
            self._span_seq += 1
            seq = self._span_seq
            self._open_spans[(name, stage)] = (self._us(), ann, None,
                                               seq)
        return seq

    def annotate(self, name: str, stage: str, token: Optional[int] = None,
                 **args) -> None:
        """Attach args to the (name, stage) span — how the wire stage
        stamps the request's rid onto its span after the send assigned
        one (the flow-link id the fused dump joins on). The span may
        already be CLOSED: on a loopback fleet the reply can complete
        (and the reactor run ``end()``) before the submitting thread
        even returns from the native send — so a just-closed span's
        recorded event is patched in place (the events list holds the
        dict itself). ``token`` (begin()'s return) pins the annotation
        to the caller's OWN span incarnation: a maximally-late annotate
        racing the next round's ``begin`` for the same key must drop,
        not stamp this round's rid onto the next round's span. A no-op
        when the target span no longer exists (window closed, fallback
        clients that report no rid)."""
        if not args:
            return
        with self._lock:
            entry = self._open_spans.get((name, stage))
            if entry is not None:
                start, ann, extra, seq = entry
                if token is not None and token != seq:
                    entry = None  # a later incarnation: fall through
                else:
                    merged = dict(extra) if extra else {}
                    merged.update(args)
                    self._open_spans[(name, stage)] = (start, ann,
                                                       merged, seq)
                    return
            closed = self._last_closed.get((name, stage))
            if closed is not None:
                seq, ev = closed
                if token is None or token == seq:
                    ev["args"].update(args)

    def end(self, name: str, stage: str) -> None:
        """Record span duration (reference: core_loops.cc:69-91). The
        annotation exit is NOT gated on the trace window: a span that
        straddles trace_end_step must still close its TraceAnnotation on
        this (long-lived pool) thread or every later annotation nests
        inside the orphan forever."""
        with self._lock:
            entry = self._open_spans.pop((name, stage), None)
        if entry is None:
            return
        start, ann, extra, seq = entry
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        if not self._active():
            return
        args = {"tensor": name}
        if extra:
            args.update(extra)
        ev = {
            "name": stage, "cat": "comm", "ph": "X",
            "ts": start, "dur": self._us() - start,
            "pid": os.getpid(), "tid": name, "args": args,
        }
        with self._lock:
            self._events.append(ev)
            self._last_closed[(name, stage)] = (seq, ev)

    def counter(self, name: str, values: dict) -> None:
        """Chrome-trace counter event (``ph: "C"``): Perfetto renders
        each key of ``values`` as a stacked counter track alongside the
        comm spans — how queue depth and per-step stage aggregates from
        the metrics plane (core/metrics.py StepProfiler) appear in the
        same timeline. Gated on the trace window like span events."""
        if not self._active():
            return
        with self._lock:
            self._events.append({
                "name": name, "cat": "comm", "ph": "C",
                "ts": self._us(), "pid": os.getpid(),
                "args": dict(values),
            })

    def instant(self, name: str, stage: str) -> None:
        if not self._active():
            return
        with self._lock:
            self._events.append({
                "name": stage, "cat": "comm", "ph": "i",
                "ts": self._us(), "pid": os.getpid(), "tid": name, "s": "t",
            })

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        """Dump comm.json (reference: global.cc:448-564)."""
        with self._lock:
            if not self._events:
                return None
            out_dir = path or os.path.join(
                self._config.trace_dir, str(self._config.local_rank))
            os.makedirs(out_dir, exist_ok=True)
            out_path = os.path.join(out_dir, "comm.json")
            with open(out_path, "w") as f:
                json.dump({"traceEvents": self._events,
                           "displayTimeUnit": "ms"}, f)
            return out_path

    # ---------------------------------------------------------------- #
    # fused fleet timeline (docs/timeline.md)
    # ---------------------------------------------------------------- #

    def set_server_collector(self, fn: Callable[[], list]) -> None:
        """Install the fleet hook dump() drains server spans through:
        ``fn()`` returns one entry per server — ``{"server": idx,
        "offset_ns": o, "err_ns": e, "records": [...]}`` with records
        in the TRACE_DRAIN wire shape (server/__init__.py
        ``_TRACE_REC_FIELDS``). Wired by core/state.py at init; tests
        may install synthetic collectors."""
        self._server_collector = fn

    def _server_us(self, server_ns: int, offset_ns: int) -> float:
        """Map a server steady-clock ns stamp onto this tracer's
        microsecond timeline: subtract the estimated offset
        (server_clock - offset ≈ client_clock), then rebase on t0."""
        return ((server_ns - offset_ns) - self._t0_ns) / 1e3

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Emit ONE Chrome trace fusing the worker's comm spans with
        every server's wire-sampled stage spans: servers land on their
        own synthetic pid rows (process_name metadata names them),
        each sampled request renders as recv → queue-wait → fold spans
        (plus a reply span once its aggregate left), clock-aligned via
        the collector's NTP-style offsets, and rid-linked to the worker
        span that carries the same rid with Chrome flow events — a slow
        round reads as a single arrow from the worker's PUSHPULL span
        into the server stage that ate the time.

        Writes ``<trace_dir>/<local_rank>/fused.json`` (or ``path``)
        and returns it; returns None when there is nothing at all to
        dump (no worker events AND no server records)."""
        with self._lock:
            # COPY the event dicts (args included) under the lock: a
            # stage thread's late annotate() mutates the originals in
            # place, and json.dump iterating a dict that grows a key
            # mid-serialization raises — the dump must read a frozen
            # snapshot (flush() is safe already: it serializes while
            # holding the lock)
            events = [dict(e, args=dict(e["args"])) if "args" in e
                      else dict(e) for e in self._events]
        fused: List[dict] = [{
            "name": "process_name", "ph": "M", "pid": os.getpid(),
            "args": {"name": f"bps-worker rank "
                             f"{self._config.local_rank}"},
        }]
        fused += events
        # worker spans by rid: the flow arrows start inside them
        rid_spans = {e["args"]["rid"]: e for e in events
                     if e.get("ph") == "X"
                     and isinstance(e.get("args"), dict)
                     and e["args"].get("rid")}
        flows = 0
        collected = self._server_collector() if self._server_collector \
            else []
        for entry in collected or []:
            idx = int(entry.get("server", 0))
            off = int(entry.get("offset_ns", 0))
            pid = _SERVER_PID_BASE + idx
            fused.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"bps-server {idx}",
                         "clock_offset_ns": off,
                         "clock_err_ns": int(entry.get("err_ns", 0))}})
            # reply events joined to their request span by (rid, sender)
            replies = {}
            for rec in entry.get("records", []):
                if rec.get("kind") == 1:
                    replies[(rec["rid"], rec["sender"])] = rec
            for rec in entry.get("records", []):
                if rec.get("kind") != 0:
                    continue
                tid = f"key {rec['key']}"
                args = {"rid": rec["rid"], "sender": rec["sender"],
                        "op": rec["op"], "key": rec["key"]}
                stages = (("recv", rec["t0"], rec["t1"]),
                          ("queue-wait", rec["t1"], rec["t2"]),
                          ("fold", rec["t2"], rec["t3"]))
                for sname, a, b in stages:
                    if not a or b < a:
                        continue  # PULLs skip recv; clamp bad stamps
                    fused.append({
                        "name": sname, "cat": "server", "ph": "X",
                        "ts": self._server_us(a, off),
                        "dur": max((b - a) / 1e3, 0.001),
                        "pid": pid, "tid": tid, "args": args})
                rep = replies.pop((rec["rid"], rec["sender"]), None)
                if rep is not None:
                    if rep["t0"] >= rec["t3"]:
                        # parked round: the wait + the aggregate send
                        fused.append({
                            "name": "reply", "cat": "server", "ph": "X",
                            "ts": self._server_us(rec["t3"], off),
                            "dur": max((rep["t0"] - rec["t3"]) / 1e3,
                                       0.001),
                            "pid": pid, "tid": tid, "args": args})
                    else:
                        # same-invocation reply (round completed inside
                        # THIS handler): the send instant sits inside
                        # the fold span — render a thin marker so the
                        # reply leg is visible either way
                        fused.append({
                            "name": "reply", "cat": "server", "ph": "X",
                            "ts": self._server_us(rep["t0"], off),
                            "dur": 0.001,
                            "pid": pid, "tid": tid, "args": args})
                # rid flow link: worker span -> this request's first
                # server stage (Chrome binds flow ends to the slice
                # enclosing ts on that pid/tid row)
                wspan = rid_spans.get(rec["rid"])
                if wspan is not None:
                    t_anchor = rec["t1"] if not rec["t0"] else rec["t0"]
                    fused.append({
                        "name": "rid", "cat": "bps-rid", "ph": "s",
                        "id": rec["rid"],
                        "ts": wspan["ts"] + 0.001,
                        "pid": wspan["pid"], "tid": wspan["tid"]})
                    fused.append({
                        "name": "rid", "cat": "bps-rid", "ph": "f",
                        "bp": "e", "id": rec["rid"],
                        "ts": self._server_us(t_anchor, off) + 0.001,
                        "pid": pid, "tid": tid})
                    flows += 1
        if not events and not collected:
            return None
        out_path = path
        if out_path is None:
            out_dir = os.path.join(self._config.trace_dir,
                                   str(self._config.local_rank))
            os.makedirs(out_dir, exist_ok=True)
            out_path = os.path.join(out_dir, "fused.json")
        else:
            parent = os.path.dirname(os.path.abspath(out_path))
            os.makedirs(parent, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump({"traceEvents": fused, "displayTimeUnit": "ms",
                       "metadata": {"rid_flow_links": flows}}, f)
        return out_path
