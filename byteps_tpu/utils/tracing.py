"""Chrome-trace communication timeline.

Reference: BYTEPS_TRACE_ON dumps per-(tensor, stage) spans to
``trace_dir/<local_rank>/comm.json`` in Chrome trace-event format
(byteps/common/global.cc:448-564, docs/timeline.md). We reproduce the same
file format, and additionally mirror spans into jax.profiler trace
annotations so they appear in TensorBoard/Perfetto device traces.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..config import Config


class Tracer:
    def __init__(self, config: Config):
        self._config = config
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._step = 0
        self._t0 = time.monotonic()
        # (tensor, stage) -> (start_us, entered TraceAnnotation or None)
        self._open_spans: Dict[tuple, tuple] = {}

    def _us(self) -> float:
        return (time.monotonic() - self._t0) * 1e6

    def _active(self) -> bool:
        return (self._config.trace_on and
                self._config.trace_start_step <= self._step <= self._config.trace_end_step)

    def step(self) -> None:
        do_flush = False
        with self._lock:
            self._step += 1
            if self._step == self._config.trace_end_step + 1:
                do_flush = True
        if do_flush:
            self.flush()

    def begin(self, name: str, stage: str,
              cross_thread: bool = False) -> None:
        """Mark the start of a (tensor, stage) span
        (reference: scheduled_queue.cc:105-123). begin/end normally pair
        on ONE thread (the stage's pool thread), which lets the span
        mirror into a jax.profiler.TraceAnnotation — visible in
        Perfetto/TensorBoard when a jax profiler trace is running
        (BYTEPS_JAX_PROFILER_DIR). ``cross_thread=True`` declares that
        end() will run on a DIFFERENT thread (the fused wire op: begin
        on the stage thread, end in the completion reactor) — the
        Chrome-trace event still records, but the TraceAnnotation
        mirror is skipped, since annotations stack per thread and an
        exit on another thread would unwind someone else's stack."""
        # annotations mirror whenever a profiler dir is configured —
        # independent of the Chrome-trace window, which only gates the
        # comm.json events (a profiler session spans init()->shutdown())
        mirror = bool(self._config.jax_profiler_dir) and not cross_thread
        if not (mirror or self._active()):
            return
        with self._lock:
            prev = self._open_spans.pop((name, stage), None)
        if prev is not None and prev[1] is not None:
            # double-begin without an end: close the orphan annotation
            # BEFORE entering the new one (annotations stack per thread;
            # exiting it later would unwind out of order and every
            # subsequent annotation would nest inside the orphan)
            try:
                prev[1].__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        ann = None
        if mirror:
            try:
                import jax
                ann = jax.profiler.TraceAnnotation(f"bps:{stage}:{name}")
                ann.__enter__()
            except Exception:  # noqa: BLE001 - profiler mirroring is aux
                ann = None
        with self._lock:
            self._open_spans[(name, stage)] = (self._us(), ann)

    def end(self, name: str, stage: str) -> None:
        """Record span duration (reference: core_loops.cc:69-91). The
        annotation exit is NOT gated on the trace window: a span that
        straddles trace_end_step must still close its TraceAnnotation on
        this (long-lived pool) thread or every later annotation nests
        inside the orphan forever."""
        with self._lock:
            entry = self._open_spans.pop((name, stage), None)
        if entry is None:
            return
        start, ann = entry
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        if not self._active():
            return
        with self._lock:
            self._events.append({
                "name": stage, "cat": "comm", "ph": "X",
                "ts": start, "dur": self._us() - start,
                "pid": os.getpid(), "tid": name, "args": {"tensor": name},
            })

    def counter(self, name: str, values: dict) -> None:
        """Chrome-trace counter event (``ph: "C"``): Perfetto renders
        each key of ``values`` as a stacked counter track alongside the
        comm spans — how queue depth and per-step stage aggregates from
        the metrics plane (core/metrics.py StepProfiler) appear in the
        same timeline. Gated on the trace window like span events."""
        if not self._active():
            return
        with self._lock:
            self._events.append({
                "name": name, "cat": "comm", "ph": "C",
                "ts": self._us(), "pid": os.getpid(),
                "args": dict(values),
            })

    def instant(self, name: str, stage: str) -> None:
        if not self._active():
            return
        with self._lock:
            self._events.append({
                "name": stage, "cat": "comm", "ph": "i",
                "ts": self._us(), "pid": os.getpid(), "tid": name, "s": "t",
            })

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        """Dump comm.json (reference: global.cc:448-564)."""
        with self._lock:
            if not self._events:
                return None
            out_dir = path or os.path.join(
                self._config.trace_dir, str(self._config.local_rank))
            os.makedirs(out_dir, exist_ok=True)
            out_path = os.path.join(out_dir, "comm.json")
            with open(out_path, "w") as f:
                json.dump({"traceEvents": self._events,
                           "displayTimeUnit": "ms"}, f)
            return out_path
