"""Checkpoint / restore — delegated to orbax, consistency by broadcast.

The reference has no checkpoint subsystem of its own: model/optimizer state
lives on workers and cross-worker consistency is re-established by
broadcast (SURVEY.md §5.4; reference: torch/__init__.py:261-459,
keras/__init__.py:96-123). We keep exactly that split: orbax persists the
pytrees, and ``restore(..., broadcast=True)`` broadcasts the restored
state from the root worker so every worker resumes bit-identical — the
reference's ``load_model`` + ``broadcast_parameters`` flow.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

__all__ = ["save", "save_async", "restore", "latest_step", "Checkpointer"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _step_dir(path: str, step: int) -> str:
    return os.path.join(path, f"step_{step:09d}")


def save(path: str, state: Dict[str, Any], step: int,
         keep: Optional[int] = None) -> str:
    """Save a state pytree (e.g. {'params': ..., 'opt_state': ...}) for
    ``step``. Only the root worker writes (workers hold replicated state —
    the reference's broadcast model makes rank 0 authoritative); others
    no-op. ``keep``: prune to the newest N checkpoints."""
    import byteps_tpu as bps

    if bps.rank() != 0:
        return _step_dir(path, step)
    os.makedirs(path, exist_ok=True)
    target = _step_dir(path, step)
    _checkpointer().save(target, jax.tree.map(np.asarray, state),
                         force=True)
    if keep:
        steps = sorted(all_steps(path))
        for s in steps[:-keep]:
            import shutil
            shutil.rmtree(_step_dir(path, s), ignore_errors=True)
    return target


_async_writer: Optional[concurrent.futures.ThreadPoolExecutor] = None
_async_writer_mu = threading.Lock()


def _writer() -> concurrent.futures.ThreadPoolExecutor:
    global _async_writer
    with _async_writer_mu:
        if _async_writer is None:
            # one thread: checkpoint writes are ordered, and overlapping
            # two multi-GB writes would thrash the disk anyway
            _async_writer = concurrent.futures.ThreadPoolExecutor(
                1, thread_name_prefix="bps-ckpt")
        return _async_writer


def save_async(path: str, state: Dict[str, Any], step: int,
               keep: Optional[int] = None) -> concurrent.futures.Future:
    """Like save(), but returns immediately: the state is snapshotted to
    host arrays NOW (the only device sync) and written by a background
    thread, so the train loop overlaps the disk write — the async-save
    pattern orbax's AsyncCheckpointer implements, kept dependency-light.
    The returned future resolves to the checkpoint dir; .result() (or
    Checkpointer.wait()) surfaces write errors. Non-root workers get an
    already-resolved future (save() is rank-0-only)."""
    import byteps_tpu as bps

    fut: concurrent.futures.Future = concurrent.futures.Future()
    if bps.rank() != 0:
        fut.set_result(_step_dir(path, step))
        return fut
    # np.array(..., copy=True): np.asarray would alias host-resident
    # ndarrays, racing the background write against in-place mutation by
    # the train loop (device arrays transfer, but numpy state would tear)
    snapshot = jax.tree.map(lambda x: np.array(x, copy=True), state)
    out = _writer().submit(save, path, snapshot, step, keep)

    def _log_unconsumed(f: concurrent.futures.Future) -> None:
        # a future nobody .result()s (e.g. the process exits between
        # intervals without wait()) must not swallow a write failure —
        # the executor's atexit join would discard it silently
        e = f.exception()
        if e is not None:
            from .logging import log
            log.error("async checkpoint write (step %d) failed: %s",
                      step, e)

    out.add_done_callback(_log_unconsumed)
    return out


def all_steps(path: str) -> list:
    if not os.path.isdir(path):
        return []
    out = []
    for name in os.listdir(path):
        if name.startswith("step_"):
            try:
                out.append(int(name[5:]))
            except ValueError:
                pass
    return sorted(out)


def latest_step(path: str) -> Optional[int]:
    steps = all_steps(path)
    return steps[-1] if steps else None


def _strip_ef_prev_lr(tree):
    """(tree-without-prev_lr, stripped key-paths): drop the
    error-feedback ``prev_lr`` leaf (added to CompressorStack.init_state
    in round 2) from every EF state dict — the on-disk structure of
    checkpoints written before then. Walks dicts/lists/tuples/
    namedtuples, the containers orbax round-trips. The returned paths
    let the inverse reinsert ONLY where a leaf was stripped (an
    unrelated dict that merely contains an ``error`` key must not grow
    one)."""
    paths = []

    def walk(t, path):
        if isinstance(t, dict):
            out = {}
            for k, v in t.items():
                if k == "prev_lr" and "error" in t:
                    paths.append(path)
                    continue
                out[k] = walk(v, path + (k,))
            return out
        if isinstance(t, tuple):
            vals = [walk(v, path + (i,)) for i, v in enumerate(t)]
            return type(t)(*vals) if hasattr(t, "_fields") else tuple(vals)
        if isinstance(t, list):
            return [walk(v, path + (i,)) for i, v in enumerate(t)]
        return t

    return walk(tree, ()), paths


def _insert_ef_prev_lr(tree, paths):
    """Inverse of _strip_ef_prev_lr: add a zeros(()) ``prev_lr`` at
    exactly the stripped paths (0 = "no LR seen yet", a first-rescale
    no-op — see CompressorStack.init_state)."""
    pathset = set(paths)

    def walk(t, path):
        if isinstance(t, dict):
            out = {k: walk(v, path + (k,)) for k, v in t.items()}
            if path in pathset:
                out["prev_lr"] = np.zeros((), np.float32)
            return out
        if isinstance(t, tuple):
            vals = [walk(v, path + (i,)) for i, v in enumerate(t)]
            return type(t)(*vals) if hasattr(t, "_fields") else tuple(vals)
        if isinstance(t, list):
            return [walk(v, path + (i,)) for i, v in enumerate(t)]
        return t

    return walk(tree, ())


def restore(path: str, step: Optional[int] = None,
            example: Optional[Dict[str, Any]] = None,
            broadcast: bool = True) -> Dict[str, Any]:
    """Restore the checkpoint at ``step`` (default: latest). With
    ``broadcast`` (and a multi-worker PS), the restored tree is broadcast
    from worker 0 so a stale or missing local checkpoint on other workers
    cannot fork the training state.

    save() writes on rank 0 only, so on a non-shared filesystem the other
    workers have NO local checkpoint: they must pass ``example`` (for the
    tree structure/shapes) and receive rank 0's state entirely through the
    broadcast (their zero contribution is summed away)."""
    import byteps_tpu as bps

    from ..core.state import get_state

    if step is None:
        step = latest_step(path)

    multi_worker = (get_state().ps_client is not None
                    and get_state().config.num_workers > 1)
    if broadcast and multi_worker:
        # agree on the step FIRST: without this, a fresh run (no checkpoint
        # anywhere) would raise on rank 0 while the other ranks enter the
        # state broadcast and deadlock waiting for its contribution
        flag = np.asarray(
            [step + 1 if (step is not None and bps.rank() == 0) else 0],
            np.int64)
        agreed = int(np.asarray(bps.broadcast(
            flag, root_rank=0, name="ckpt/restore_step"))[0])
        step = agreed - 1 if agreed > 0 else None
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {path} on the "
                                    f"root worker")
        local = bps.rank() == 0 or step in all_steps(path)
    else:
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
        local = True

    if local and step in all_steps(path):
        if example is not None:
            # restore INTO the example structure: orbax maps by tree path,
            # so namedtuple field order / >9 chain indices can't permute
            # (raw leaf-order reshaping would silently corrupt e.g.
            # optax.MultiSteps state, whose field names do not sort
            # alphabetically)
            item = jax.tree.map(np.asarray, example)
            try:
                state = _checkpointer().restore(_step_dir(path, step),
                                                item=item)
            except Exception:
                # round-1-era checkpoints predate the EF state's prev_lr
                # leaf: retry against the legacy structure and reinsert
                # the leaf as zeros (a first-rescale no-op)
                legacy, stripped = _strip_ef_prev_lr(item)
                if not stripped:
                    raise
                state = _checkpointer().restore(_step_dir(path, step),
                                                item=legacy)
                state = _insert_ef_prev_lr(state, stripped)
                from .logging import log
                log.info("checkpoint %s step %s: migrated legacy "
                         "error-feedback state (+%d prev_lr leaf(s))",
                         path, step, len(stripped))
        else:
            state = _checkpointer().restore(_step_dir(path, step))
    else:
        if example is None:
            raise FileNotFoundError(
                f"step {step} missing under {path} (non-root workers need "
                f"example= to join the restore broadcast without a local "
                f"checkpoint)")
        # the zeros template is ONLY valid as this worker's contribution
        # to a multi-worker restore broadcast (the root's values win);
        # without one it would be handed back as the restored state and
        # the caller would silently resume from a zeroed model
        from ..core.state import get_state
        from ..ops.push_pull import _mesh_spans_processes

        st = get_state()
        # repopulation happens via either tier: the PS broadcast
        # (client + >1 workers) or the multi-process global mesh (ICI
        # collectives, num_servers=0). A lazy PS that never connected
        # cannot repopulate — raising there is correct, the old code
        # silently returned zeros.
        spans = st.mesh is not None and _mesh_spans_processes(st.mesh)
        will_repopulate = broadcast and (
            spans or (st.ps_client is not None
                      and st.config.num_workers > 1))
        if not will_repopulate:
            raise FileNotFoundError(
                f"step {step} missing under {path} and no multi-worker "
                f"broadcast will repopulate it (broadcast={broadcast}, "
                f"workers="
                f"{st.config.num_workers if st.initialized else 1}); "
                f"refusing to return a zeroed state")
        state = jax.tree.map(lambda leaf: np.zeros_like(np.asarray(leaf)),
                             example)
    if broadcast:
        from ..jax import broadcast_parameters
        state = broadcast_parameters(state, root_rank=0)
    return state


class Checkpointer:
    """Convenience wrapper: periodic save + latest-restore.

    >>> ckpt = Checkpointer("/tmp/run1", every_steps=1000, keep=3)
    >>> ckpt.maybe_save(step, {"params": params, "opt_state": opt})
    >>> state = ckpt.restore_latest(example={"params": params,
    ...                                      "opt_state": opt})
    """

    def __init__(self, path: str, every_steps: int = 1000,
                 keep: Optional[int] = 3, async_save: bool = False):
        self.path = path
        self.every_steps = every_steps
        self.keep = keep
        self.async_save = async_save
        self._pending: Optional[concurrent.futures.Future] = None

    def maybe_save(self, step: int, state: Dict[str, Any]) -> Optional[str]:
        if step % self.every_steps:
            return None
        if self.async_save:
            # at most one write in flight: wait for (and error-check) the
            # previous one before snapshotting the next
            self.wait()
            self._pending = save_async(self.path, state, step,
                                       keep=self.keep)
            return _step_dir(self.path, step)
        return save(self.path, state, step, keep=self.keep)

    def wait(self) -> None:
        """Block until the outstanding async save (if any) has landed;
        re-raises its error (once — a failed save does not poison later
        ones). Call before exit."""
        if self._pending is not None:
            try:
                self._pending.result()
            finally:
                self._pending = None

    def restore_latest(self, example: Optional[Dict[str, Any]] = None,
                       broadcast: bool = True) -> Dict[str, Any]:
        self.wait()  # never restore a checkpoint that is mid-write
        return restore(self.path, example=example, broadcast=broadcast)

    def latest_step(self) -> Optional[int]:
        return latest_step(self.path)
