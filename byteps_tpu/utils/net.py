"""Small networking helpers shared by benchmarks, tests and launchers."""

from __future__ import annotations

import socket
import time


def wait_port(port: int, timeout: float = 60.0,
              host: str = "127.0.0.1") -> None:
    """Block until ``host:port`` ACCEPTS a connection (the
    server-came-up rendezvous every loopback-fleet harness needs —
    server processes pay a cold import before they bind). Raises
    RuntimeError at the deadline."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            with socket.create_connection((host, port), timeout=1):
                return
        except OSError:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"server on {host}:{port} never came up within "
                    f"{timeout:.0f}s")
            time.sleep(0.1)


def free_port() -> int:
    """An ephemeral loopback port (bind-probe then release). Subject to
    the usual reuse race; callers that must be robust should retry on
    bind failure."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
