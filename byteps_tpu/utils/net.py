"""Small networking helpers shared by benchmarks, tests and launchers."""

from __future__ import annotations

import socket


def free_port() -> int:
    """An ephemeral loopback port (bind-probe then release). Subject to
    the usual reuse race; callers that must be robust should retry on
    bind failure."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
