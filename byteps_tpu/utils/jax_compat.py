"""Compatibility shims for the span of jax releases this repo meets in
the wild.

The package (and its tests/examples) target the current jax surface:
``jax.shard_map`` with ``check_vma``, and the ``jax_num_cpu_devices``
config option for virtual CPU meshes. Older still-deployed releases
(<= 0.4.x) spell these ``jax.experimental.shard_map.shard_map`` with
``check_rep`` and ``--xla_force_host_platform_device_count`` in
XLA_FLAGS. One shim module keeps every call site on the modern
spelling instead of scattering try/excepts through the codebase.

``ensure()`` is idempotent and called from ``byteps_tpu/__init__`` (so
any import of the package fixes up the session) and from test/child
bootstraps that touch jax before importing the package.
"""

from __future__ import annotations

import os


def ensure() -> None:
    """Install ``jax.shard_map`` when this jax only ships the
    experimental spelling, translating ``check_vma`` to the old
    ``check_rep`` knob."""
    import jax

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # psum of a constant folds to the (static) mesh axis size
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size

    if not hasattr(jax.distributed, "is_initialized"):
        def is_initialized():
            try:
                from jax._src.distributed import global_state
                return global_state.client is not None
            except Exception:  # noqa: BLE001 - internals moved: assume no
                return False

        jax.distributed.is_initialized = is_initialized

    if hasattr(jax, "shard_map"):
        return
    import inspect

    from jax.experimental.shard_map import shard_map as _shard_map

    _has_check_rep = "check_rep" in inspect.signature(_shard_map).parameters

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None and _has_check_rep:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map


def force_cpu(n_devices: int = 8) -> None:
    """Pin jax to an ``n_devices``-wide virtual CPU mesh, whichever way
    this jax spells it. Call before the first device query; sets
    XLA_FLAGS first so a child process that has not imported jax yet
    gets the device count even without the config option. An inherited
    flag with a DIFFERENT count is rewritten, not kept — a pytest
    parent's 8-device XLA_FLAGS must not override a worker child's
    force_cpu(4) on a jax without the config option."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       f"--xla_force_host_platform_device_count"
                       f"={n_devices}", flags)
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:  # pre-0.5 jax: the XLA_FLAGS path above applies
        pass
    ensure()
