"""Gradient-compression codecs, TPU-native.

Re-implementations of the reference's four codecs
(byteps/common/compressor/impl/{onebit,topk,randomk,dithering}.cc) as
functional, jit-compatible transforms over flat fp32 vectors. Payloads are
pytrees of fixed-shape arrays (XLA needs static shapes), so:

- onebit packs sign bits into uint32 words on-device (reference packs into
  host words with OpenMP, onebit.cc:34-66);
- topk/randomk ship (indices, values) pairs of static length k;
- dithering diverges from the reference wire format by design: instead of
  Elias-delta-coded sparse indices (dithering.cc:25-80) it ships a dense
  int8 level per element + the norm scalar — variable-length bitstreams
  don't fit XLA's static-shape model, and the dense form keeps the whole
  codec on the MXU/VPU. Numerics (linear/natural partition, max/L2 norm,
  Bernoulli rounding with xorshift128+) match.

Every codec implements ``compress(x, step) -> payload`` and
``decompress(payload) -> x_hat`` for flat f32 ``x``; ``wire_bytes`` reports
payload size for telemetry/scheduling.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .rng import jnp_uniform_parallel


def _pad_to(x: jnp.ndarray, multiple: int) -> jnp.ndarray:
    pad = (-x.shape[0]) % multiple
    return jnp.pad(x, (0, pad)) if pad else x


@dataclasses.dataclass(frozen=True)
class Codec:
    """Base: identity codec."""

    size: int  # number of f32 elements of the uncompressed flat tensor

    def compress(self, x: jnp.ndarray, step: int = 0) -> Dict[str, Any]:
        return {"raw": x}

    def decompress(self, payload: Dict[str, Any]) -> jnp.ndarray:
        return payload["raw"]

    def wire_bytes(self) -> int:
        return self.size * 4


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


@dataclasses.dataclass(frozen=True)
class OnebitCodec(Codec):
    """signSGD with optional L1-mean scaling (onebit.cc:34-66).

    payload: bits uint32[~n/32], scale f32[] (1.0 when unscaled). On TPU
    the pack/unpack dispatch to the Pallas kernels
    (pallas_kernels.onebit_pack/unpack; sublane-folded word layout); the
    jnp path below is the portable reference semantics. Both layouts are
    self-inverse, so decompressed values agree bit-for-bit.
    """

    scaled: bool = True
    use_pallas: bool = True

    def _pallas_active(self) -> bool:
        """Layout choice LATCHED at the first compress/decompress/
        wire_bytes call: the Pallas and portable payloads differ in size
        (sublane-folded padding), so resolving pallas-vs-portable
        independently per call under different device contexts would
        size the pull buffer for the wrong layout — which the server's
        oversized-reply check turns into a hard per-round error."""
        got = self.__dict__.get("_pallas_latched")
        if got is None:
            got = bool(self.use_pallas and _on_tpu())
            object.__setattr__(self, "_pallas_latched", got)
        return got

    def compress(self, x: jnp.ndarray, step: int = 0) -> Dict[str, Any]:
        scale = jnp.mean(jnp.abs(x)) if self.scaled else jnp.float32(1.0)
        if self._pallas_active():
            from .pallas_kernels import onebit_pack
            bits = onebit_pack(x)
        else:
            signs = (_pad_to(x, 32) >= 0).astype(jnp.uint32)
            words = signs.reshape(-1, 32)
            weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
            bits = jnp.sum(words * weights[None, :], axis=1, dtype=jnp.uint32)
        return {"bits": bits, "scale": scale.astype(jnp.float32)}

    def decompress(self, payload: Dict[str, Any]) -> jnp.ndarray:
        bits = payload["bits"]
        if self._pallas_active():
            from .pallas_kernels import onebit_unpack
            return onebit_unpack(bits, jnp.float32(1.0), self.size) \
                * payload["scale"]
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
        signs = ((bits[:, None] & weights[None, :]) > 0).astype(jnp.float32)
        flat = (signs * 2.0 - 1.0).reshape(-1)[: self.size]
        return flat * payload["scale"]

    def wire_bytes(self) -> int:
        # report what this codec's active layout actually moves: the
        # Pallas sublane-folded payload pads n to full 256-row blocks,
        # so the portable ceil(n/32) count would under-report telemetry
        # and scheduling credit by up to a block (badly for small
        # leaves, whose minimum payload is one block)
        if self._pallas_active():
            from .pallas_kernels import _LANES, _padded_rows
            return (_padded_rows(self.size) * _LANES // 32) * 4 + 4
        return ((self.size + 31) // 32) * 4 + 4


def resolve_k(k_param: float, size: int) -> int:
    """k as absolute count (>=1) or fraction (<1), like HyperParamFinder's
    compressor_k handling (topk.cc:24-43)."""
    if k_param >= 1:
        k = int(k_param)
    else:
        k = max(1, int(size * k_param))
    return min(k, size)


@dataclasses.dataclass(frozen=True)
class TopkCodec(Codec):
    """Top-k |x| selection into (indices, values) (topk.cc:24-43); the
    reference's heap loop becomes lax.top_k, which XLA maps to the TPU
    sort unit. A hand-written Pallas selection cannot beat that dedicated
    unit, so — unlike onebit/randomk/dithering — topk intentionally has no
    Pallas kernel (SURVEY §7 "hard parts" #3 budgets for exactly this).

    ``approx=True`` instead lowers to the TPU's ApproxTopK hardware op
    (lax.approx_max_k, ~95% recall by default): it returns *approximately*
    the largest-|x| set, which is sound under error feedback (missed
    coordinates stay in the EF residual and ship next round) and is
    substantially faster at large n. Documented divergence: indices may
    differ from exact top-k; the wire format is unchanged (the server
    mirror consumes (indices, values) pairs either way)."""

    k: int = 1
    approx: bool = False

    def compress(self, x: jnp.ndarray, step: int = 0) -> Dict[str, Any]:
        if self.approx:
            _, idx = jax.lax.approx_max_k(jnp.abs(x), self.k)
        else:
            _, idx = jax.lax.top_k(jnp.abs(x), self.k)
        return {"indices": idx.astype(jnp.int32), "values": x[idx]}

    def decompress(self, payload: Dict[str, Any]) -> jnp.ndarray:
        out = jnp.zeros((self.size,), jnp.float32)
        return out.at[payload["indices"]].set(payload["values"])

    def wire_bytes(self) -> int:
        return self.k * 8


@dataclasses.dataclass(frozen=True)
class RandomkCodec(Codec):
    """k pseudo-random (index, value) pairs seeded by (seed, step) so every
    party draws the same indices (randomk.cc:24-60). Uses the counter-based
    generator (murmur3 over (i, seed, step), rng.py): O(1) depth instead of
    the O(k) sequential xorshift scan — at the reference's default k=1% of
    a 4MB partition that scan would dwarf the compress itself — and the PS
    server reuses the in-band indices, so only np/jnp parity is needed."""

    k: int = 1
    seed: int = 0
    use_pallas: bool = True

    def _indices(self, step) -> jnp.ndarray:
        # the kernel pays one pallas launch and computes full 32K-lane
        # blocks, so it only wins once k spans at least a block; small k
        # (the common 1%-of-partition case) stays on the jnp elementwise
        # path — both are bit-exact with the numpy golden
        if self.use_pallas and self.k >= 32768 and _on_tpu():
            from .pallas_kernels import randomk_indices
            from .rng import uniform_base
            return randomk_indices(
                jnp.asarray(uniform_base(self.seed, step)),
                jnp.int32(self.size), self.k)
        from .rng import jnp_index_parallel
        return jnp_index_parallel(self.seed, self.k, self.size, mix=step)

    def compress(self, x: jnp.ndarray, step: int = 0) -> Dict[str, Any]:
        idx = self._indices(step)
        return {"indices": idx, "values": x[idx]}

    def decompress(self, payload: Dict[str, Any]) -> jnp.ndarray:
        out = jnp.zeros((self.size,), jnp.float32)
        return out.at[payload["indices"]].set(payload["values"])

    def wire_bytes(self) -> int:
        return self.k * 8


@dataclasses.dataclass(frozen=True)
class DitheringCodec(Codec):
    """Stochastic s-level quantization (dithering.cc:25-80): normalize by
    max or L2 norm, map |x| onto s levels (linear or natural/power-of-two
    partition), round up with probability equal to the fractional position
    (Bernoulli via shared xorshift128+), ship dense signed int8 levels.
    """

    s: int = 127                  # levels; <=127 so a level fits int8
    partition: str = "linear"     # or "natural"
    normalize: str = "max"        # or "l2"
    seed: int = 0
    use_pallas: bool = True       # fused VPU quantize kernel on TPU

    def __post_init__(self):
        if not (1 <= self.s <= 127):
            raise ValueError(
                f"dithering s={self.s} out of range [1, 127] (levels are "
                f"carried as int8; larger s would silently wrap)")
        if self.partition not in ("linear", "natural"):
            raise ValueError(f"unknown partition {self.partition!r}")
        if self.normalize not in ("max", "l2"):
            raise ValueError(f"unknown normalize {self.normalize!r}")

    def compress(self, x: jnp.ndarray, step: int = 0) -> Dict[str, Any]:
        absx = jnp.abs(x)
        m = jnp.max(absx)
        if self.normalize == "max":
            norm = m
        else:
            # scale-invariant two-pass l2 (f32-safe for |x| near
            # float32 max, where x*x overflows to inf)
            safe_m = jnp.maximum(m, 1e-30)
            norm = safe_m * jnp.sqrt(jnp.sum(jnp.square(absx / safe_m)))
        norm = jnp.maximum(norm, 1e-30)
        if self.use_pallas and _on_tpu():
            # fused VPU pass: in-register counter RNG + quantize, one read
            # of x and one write of the levels (pallas_kernels)
            from .pallas_kernels import dithering_levels
            from .rng import uniform_base
            base = jnp.asarray(uniform_base(self.seed, step))
            levels = dithering_levels(x, norm, base, self.s, self.partition)
            return {"levels": levels, "norm": norm.astype(jnp.float32)}
        scaled = absx / norm                           # in [0, 1]
        # counter-based parallel uniforms: per-element noise needs no
        # sequential stream, and the O(n)-depth xorshift scan would dwarf
        # the gradient compute at real tensor sizes
        u = jnp_uniform_parallel(self.seed, self.size, mix=step)

        if self.partition == "linear":
            pos = scaled * self.s                      # in [0, s]
            floor = jnp.floor(pos)
            frac = pos - floor
            level = floor + (u < frac)                 # stochastic round
            # l2 norm can round below max|x|, making scaled epsilon > 1;
            # an unclamped level s+1 would wrap the int8 cast at s=127
            level = jnp.minimum(level, float(self.s))
        else:  # natural: levels at 2^-j — quantize onto powers of two
            # j = number of halvings from full scale; level value = 2^-j.
            # Stored level is j+1 (so stored 0 unambiguously means zero).
            safe = jnp.maximum(scaled, 1e-30)
            j = jnp.clip(jnp.floor(-jnp.log2(safe)), 0, 30)
            low = jnp.exp2(-j - 1)                     # lower level value
            high = jnp.exp2(-j)
            frac = (scaled - low) / (high - low)
            take_high = u < frac
            exp = jnp.where(take_high, j, j + 1)       # halvings from 1.0
            level = jnp.where(scaled < jnp.exp2(-31.0), 0.0, exp + 1.0)
            level = jnp.clip(level, 0, 126)

        levels = (jnp.sign(x) * level).astype(jnp.int8)
        return {"levels": levels, "norm": norm.astype(jnp.float32)}

    def decompress(self, payload: Dict[str, Any]) -> jnp.ndarray:
        lv = payload["levels"].astype(jnp.float32)
        if self.partition == "linear":
            mag = jnp.abs(lv) / self.s
        else:
            mag = jnp.where(lv == 0, 0.0, jnp.exp2(-(jnp.abs(lv) - 1.0)))
        return jnp.sign(lv) * mag * payload["norm"]

    def wire_bytes(self) -> int:
        return self.size + 4
