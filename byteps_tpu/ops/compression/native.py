"""ctypes wrapper over the C++ wire codec (native/ps.cc CompressorCfg).

The server has always mirrored the worker's codec in C++; this exposes the
SAME implementation to the worker host tier, replacing the numpy pack loop
on the per-step hot path (reference: the worker-side compressors are
OpenMP C++, byteps/common/compressor/impl/onebit.cc:34-66 — numpy was the
rebuild's placeholder). Wire bytes are produced by the identical code the
server parses, so worker/server bit-agreement is by construction.

Routed by ``make_host_codec`` for onebit/topk/randomk when the native
library is available (kill switch: BYTEPS_NATIVE_CODEC=0), plus
dithering in its DEFAULT config (partition=linear, normalize=max): the
max norm is computed exactly by both tiers and the level arithmetic
mirrors the numpy op order, so the stochastic rounding draws are
bit-identical. The non-default dithering configs stay numpy: l2's norm
(C++ double accumulate vs numpy f32 pairwise) and natural's exp2f/log2f
(libm-dependent) can differ by an ulp, and an ulp there can flip a level
draw — unlike the deterministic codecs, where no reduction scalar gates
a bit (the onebit scale rides the wire but never selects a sign).

Parity contract scope: FINITE inputs. On NaN gradients the tiers diverge
for dithering (numpy's max propagates NaN into the norm; C++ std::max
skips it) — the same divergence the C++ server mirror has always had
against the numpy golden. Onebit is NaN-parity-engineered (">= 0" is
false for NaN on every tier); a NaN gradient round is garbage either
way, so dithering's divergence is documented rather than mirrored.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, Optional

import numpy as np

_lib = None
_lib_lock = threading.Lock()
_LOAD_FAILED = False


def _load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the shared library; None if unavailable.
    Never raises — callers fall back to the numpy tier."""
    global _lib, _LOAD_FAILED
    if _lib is not None:
        return _lib
    if _LOAD_FAILED or os.environ.get("BYTEPS_NATIVE_CODEC", "1") == "0":
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib
        try:
            from ...native.build import build

            lib = ctypes.CDLL(build())
            lib.bps_codec_create.restype = ctypes.c_void_p
            lib.bps_codec_create.argtypes = [ctypes.c_char_p]
            lib.bps_codec_wire_bound.restype = ctypes.c_uint32
            lib.bps_codec_wire_bound.argtypes = [ctypes.c_void_p]
            lib.bps_codec_compress.restype = ctypes.c_int64
            lib.bps_codec_compress.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64]
            lib.bps_codec_decompress.restype = ctypes.c_int
            lib.bps_codec_decompress.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
                ctypes.c_void_p]
            lib.bps_codec_destroy.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:  # noqa: BLE001 - no toolchain etc.
            _LOAD_FAILED = True
            return None
    return _lib


class NativeCodec:
    """HostCodec-interface adapter over one C++ CompressorCfg instance."""

    def __init__(self, kwargs_wire: str, n: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native codec library unavailable")
        self._lib = lib
        self.n = n
        self._kwargs_wire = kwargs_wire
        self._h = lib.bps_codec_create(kwargs_wire.encode())
        if not self._h:
            raise ValueError(f"native codec rejected {kwargs_wire!r}")
        self._bound = int(lib.bps_codec_wire_bound(self._h))

    def compress(self, x: np.ndarray, step: int = 0) -> np.ndarray:
        """Wire payload as a uint8 ndarray — a buffer-protocol object,
        interchangeable with the numpy tier's bytes everywhere the wire
        is consumed (np.frombuffer / zpush) without the tobytes copy."""
        x = np.ascontiguousarray(x, np.float32)
        if x.size != self.n:
            raise ValueError(f"expected {self.n} elements, got {x.size}")
        out = np.empty(self._bound, np.uint8)
        wl = self._lib.bps_codec_compress(self._h, x.ctypes.data,
                                          out.ctypes.data, step)
        if wl < 0:
            raise RuntimeError("native compress failed")
        return out[:wl]

    def decompress(self, buf) -> np.ndarray:
        raw = np.ascontiguousarray(np.frombuffer(buf, np.uint8))
        out = np.empty(self.n, np.float32)
        rc = self._lib.bps_codec_decompress(self._h, raw.ctypes.data,
                                            len(raw), out.ctypes.data)
        if rc != 0:
            raise ValueError("native decompress: bad wire payload")
        return out

    def wire_bytes(self) -> int:
        return self._bound

    def kwargs_wire(self) -> str:
        return self._kwargs_wire

    def __del__(self):  # noqa: D105
        h, lib = getattr(self, "_h", None), getattr(self, "_lib", None)
        if h and lib is not None:
            try:
                lib.bps_codec_destroy(h)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass
            self._h = None


_NATIVE_OK = ("onebit", "topk", "randomk")


def _eligible(kwargs: Dict[str, str]) -> bool:
    name = kwargs.get("compressor")
    if name in _NATIVE_OK:
        return True
    if name == "dithering":
        # only the bit-stable default config (see module docstring)
        return (kwargs.get("partition_type", "linear") == "linear"
                and kwargs.get("normalize_type", "max") == "max")
    return False


def maybe_native(kwargs: Dict[str, str], kwargs_wire: str,
                 n: int) -> Optional[NativeCodec]:
    """A NativeCodec for this config, or None when the config or the
    environment calls for the numpy tier."""
    if not _eligible(kwargs) or _load() is None:
        return None
    try:
        return NativeCodec(kwargs_wire, n)
    except (RuntimeError, ValueError):
        return None
