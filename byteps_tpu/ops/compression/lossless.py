"""Lossless byte-plane wire codec: exponent/mantissa plane split + zlib.

The lossless tier of the adaptive codec ladder (core/codec_plane.py;
ZipCCL, arxiv 2604.27844): a float tensor's bytes are transposed into
per-byte *planes* — plane j holds byte j of every element — so the
low-entropy sign/exponent bytes (which cluster tightly for gradients)
sit contiguously and deflate far better than the interleaved stream,
while the high-entropy mantissa-noise planes cost ~nothing extra. The
entropy stage is zlib level 1: the stream is self-describing, so the
three wire producers (this numpy tier, the C++ server mirror in
native/ps.cc CompressorCfg LOSSLESS, and any future device tier) need
only produce *decodable* bytes, not identical ones — unlike the lossy
codecs there is no cross-implementation bit-parity constraint on the
wire, only on the reconstruction, which is bitwise exact by
construction (NaN payloads, -0.0, subnormals and inf round-trip
untouched).

Wire layout (little-endian, mirrored by ps.cc kLosslessHdr):

    [u32 n_elems][u8 mode][u8 nplanes][u16 reserved]
    [u32 plane_len[nplanes]][plane bytes ...]

mode 1 = deflated planes; mode 0 = raw passthrough chosen when deflate
does not pay, capping the wire at header + raw bytes — ``wire_bytes()``
is therefore a hard allocation bound like the varint dithering wire.

``plane_split``/``plane_join`` are dtype-agnostic (fp32 = 4 planes,
bf16/f16 = 2) so the property suite proves the byte-plane transform on
bf16 payloads directly; the PS wire tier (``HostLossless``) is f32 like
every other host codec (the compressed PS path upcasts, host.py).
"""

from __future__ import annotations

import dataclasses
import struct
import zlib

import numpy as np

# header: [u32 n][u8 mode][u8 nplanes][u16 rsvd] + u32 len per plane
_HDR = struct.Struct("<IBBH")
# zlib level 1: the tier trades one cheap entropy pass for wire bytes;
# gradient sign/exponent planes compress well even at the fastest level
# (ps.cc uses the same level for the server-side recompress)
_LEVEL = 1


def plane_split(raw: np.ndarray, itemsize: int) -> list:
    """Byte planes of a flat uint8 buffer of ``itemsize``-wide elements:
    plane j = byte j of every element, each C-contiguous."""
    if raw.size % itemsize:
        raise ValueError(f"buffer of {raw.size} bytes is not a whole "
                         f"number of {itemsize}-byte elements")
    mat = raw.reshape(-1, itemsize)
    return [np.ascontiguousarray(mat[:, j]) for j in range(itemsize)]


def plane_join(planes: list, itemsize: int) -> np.ndarray:
    """Inverse of :func:`plane_split`: re-interleave planes into the
    element byte stream (uint8)."""
    n = len(planes[0])
    out = np.empty((n, itemsize), np.uint8)
    for j, p in enumerate(planes):
        out[:, j] = p
    return out.reshape(-1)


def encode_planes(raw: np.ndarray, itemsize: int) -> bytes:
    """One buffer -> the self-describing byte-plane wire (see module
    docstring). ``raw``: flat uint8 view of the element bytes."""
    planes = plane_split(np.ascontiguousarray(raw, np.uint8), itemsize)
    n = len(planes[0]) if planes else 0
    packed = [zlib.compress(p.tobytes(), _LEVEL) for p in planes]
    mode = 1 if sum(len(b) for b in packed) < raw.size else 0
    if mode == 0:
        packed = [p.tobytes() for p in planes]
    head = _HDR.pack(n, mode, itemsize, 0)
    lens = struct.pack(f"<{itemsize}I", *[len(b) for b in packed])
    return head + lens + b"".join(packed)


def decode_planes(buf, itemsize: int) -> np.ndarray:
    """Wire -> flat uint8 element bytes; validates the header hard
    (wire parsers face untrusted input)."""
    buf = bytes(buf)
    if len(buf) < _HDR.size:
        raise ValueError("lossless wire: truncated header")
    n, mode, nplanes, _rsvd = _HDR.unpack_from(buf)
    if nplanes != itemsize or mode > 1:
        raise ValueError(
            f"lossless wire: bad header (mode={mode} nplanes={nplanes}, "
            f"expected {itemsize} planes)")
    off = _HDR.size + 4 * nplanes
    if len(buf) < off:
        raise ValueError("lossless wire: truncated plane table")
    lens = struct.unpack_from(f"<{nplanes}I", buf, _HDR.size)
    if off + sum(lens) != len(buf):
        raise ValueError("lossless wire: plane lengths disagree with "
                         "payload size")
    planes = []
    for ln in lens:
        chunk = buf[off:off + ln]
        if mode:
            chunk = zlib.decompress(chunk)
        if len(chunk) != n:
            raise ValueError("lossless wire: plane inflated to "
                             f"{len(chunk)} bytes, expected {n}")
        planes.append(np.frombuffer(chunk, np.uint8))
        off += ln
    if n == 0:
        return np.zeros(0, np.uint8)
    return plane_join(planes, itemsize)


@dataclasses.dataclass
class LosslessCodec:
    """Bitwise round-trip codec over raw element bytes of any width —
    the dtype-agnostic core (fp32 = 4 planes, bf16 = 2) used by the
    property suite and by HostLossless below."""

    itemsize: int = 4

    def compress_bytes(self, raw: np.ndarray) -> bytes:
        return encode_planes(raw, self.itemsize)

    def decompress_bytes(self, buf) -> np.ndarray:
        return decode_planes(buf, self.itemsize)


class HostLossless:
    """PS wire tier: the :class:`~.host.HostCodec` surface over f32
    partitions (compress(x, step) -> bytes; decompress(buf) -> f32[n]).
    ``lossless = True`` marks tasks for the scheduler's
    ``codec/lossless_bytes_*`` accounting."""

    lossless = True

    def __init__(self, n: int):
        self.n = n
        self._codec = LosslessCodec(itemsize=4)

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        x = np.ascontiguousarray(x, np.float32)
        if x.size != self.n:
            raise ValueError(f"lossless codec sized for {self.n} elems, "
                             f"got {x.size}")
        return self._codec.compress_bytes(x.view(np.uint8).reshape(-1))

    def decompress(self, buf) -> np.ndarray:
        raw = self._codec.decompress_bytes(buf)
        out = raw.view(np.float32)
        if out.size != self.n:
            raise ValueError(f"lossless wire decoded {out.size} elems, "
                             f"expected {self.n}")
        return out

    def wire_bytes(self) -> int:
        # allocation BOUND (mode-0 raw passthrough worst case), exactly
        # ps.cc's WireLen(): header + plane table + 4n raw bytes
        return _HDR.size + 4 * 4 + 4 * self.n

    def kwargs_wire(self) -> str:
        return f"compressor=lossless;n={self.n}"
