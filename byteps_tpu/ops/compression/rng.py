"""xorshift128+ RNG, bit-exact across numpy (golden), JAX (on-device) and the
C++ server.

The reference uses xorshift128+ so worker and server draw identical random
sequences for randomk and dithering (reference:
byteps/common/compressor/utils.h:69-110), and its tests replicate the C++
generator in numba-compiled Python (tests/utils.py:31-51). We keep the same
scheme. The JAX implementation represents each 64-bit lane as a (hi, lo)
uint32 pair — TPUs have no 64-bit integer units, and this also sidesteps
jax's x64 flag — while producing draws identical to the numpy golden model.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def seed_state(seed: int) -> Tuple[int, int]:
    """Derive the 128-bit state from a seed (splitmix64 twice, standard
    xorshift seeding); shared by all implementations."""
    state = []
    z = np.uint64(seed) & _M64
    with np.errstate(over="ignore"):
        for _ in range(2):
            z = (z + np.uint64(0x9E3779B97F4A7C15)) & _M64
            x = z
            x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & _M64
            x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & _M64
            x = x ^ (x >> np.uint64(31))
            state.append(int(x))
    return state[0], state[1]


def np_xorshift128p(seed: int, n: int, mix: int = 0) -> np.ndarray:
    """Golden model: n uint64 draws. ``mix`` (e.g. the training step) is
    XORed into the low lane of s0 so per-step streams differ; the jnp
    implementation applies the identical scheme, so the two stay bit-exact
    even when the step is only known inside jit."""
    s0, s1 = (np.uint64(v) for v in seed_state(seed))
    s0 = s0 ^ np.uint64(mix & 0xFFFFFFFF)
    out = np.empty(n, np.uint64)
    with np.errstate(over="ignore"):
        for i in range(n):
            x, y = s0, s1
            s0 = y
            x = x ^ ((x << np.uint64(23)) & _M64)
            s1 = (x ^ y ^ (x >> np.uint64(17)) ^ (y >> np.uint64(26))) & _M64
            out[i] = (s1 + y) & _M64
    return out


# ------------------------------------------------------------------ #
# 64-bit lanes as (hi, lo) uint32 pairs — jit/TPU friendly
# ------------------------------------------------------------------ #

def _shl(h, l, k: int):
    k = np.uint32(k)
    return ((h << k) | (l >> (np.uint32(32) - k))) , (l << k)


def _shr(h, l, k: int):
    k = np.uint32(k)
    return (h >> k), ((l >> k) | (h << (np.uint32(32) - k)))


def _add(h1, l1, h2, l2):
    lo = l1 + l2
    carry = (lo < l1).astype(jnp.uint32)
    return h1 + h2 + carry, lo


def jnp_xorshift128p(seed: int, n: int, mix=0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """n draws as (hi, lo) uint32 arrays, matching np_xorshift128p:
    hi == draw >> 32, lo == draw & 0xffffffff. ``mix`` may be a traced
    int32/uint32 scalar (e.g. the step counter inside jit)."""
    from jax import lax

    s0, s1 = seed_state(seed)

    def split(v):
        return jnp.uint32(v >> 32), jnp.uint32(v & 0xFFFFFFFF)

    def body(carry, _):
        s0h, s0l, s1h, s1l = carry
        xh, xl, yh, yl = s0h, s0l, s1h, s1l
        n0h, n0l = yh, yl
        sh, sl = _shl(xh, xl, 23)
        xh, xl = xh ^ sh, xl ^ sl
        r17h, r17l = _shr(xh, xl, 17)
        r26h, r26l = _shr(yh, yl, 26)
        n1h = xh ^ yh ^ r17h ^ r26h
        n1l = xl ^ yl ^ r17l ^ r26l
        oh, ol = _add(n1h, n1l, yh, yl)
        return (n0h, n0l, n1h, n1l), (oh, ol)

    s0h, s0l = split(s0)
    s0l = s0l ^ jnp.asarray(mix).astype(jnp.uint32)
    init = (s0h, s0l, *split(s1))
    _, (hi, lo) = lax.scan(body, init, None, length=n)
    return hi, lo


def mm3_finalize(h):
    """murmur3 finalizer over a uint32 jnp array — THE jnp definition,
    shared by jnp_uniform_parallel and the Pallas kernels (plain jnp ops,
    so Mosaic traces it directly); _np_mm3 below is the independent numpy
    golden the parity tests check both against."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


def _np_mm3(h: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = h ^ (h >> np.uint32(16))
        h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
        h = h ^ (h >> np.uint32(13))
        h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
        h = h ^ (h >> np.uint32(16))
    return h


def uniform_base(seed: int, mix=0):
    """The uint32 counter base of the parallel uniform generator:
    seed-state low word XOR mix. THE single definition — numpy golden, jnp
    path and the Pallas kernels all derive their counters from this, and
    worker/server randomk index agreement depends on them staying
    identical. ``mix`` may be a traced scalar; the return is a jnp scalar
    then."""
    s0, _ = seed_state(seed)
    low = s0 & 0xFFFFFFFF
    if isinstance(mix, (int, np.integer)):
        return np.uint32(low) ^ np.uint32(mix & 0xFFFFFFFF)
    return jnp.uint32(low) ^ jnp.asarray(mix).astype(jnp.uint32)


def np_uniform_parallel(seed: int, n: int, mix: int = 0,
                        dtype=np.float32) -> np.ndarray:
    """Counter-based parallel uniforms: murmur3 finalizer over
    (index, seed, mix). O(1) depth — unlike the sequential xorshift stream —
    so it is the right generator for per-element noise (dithering's
    Bernoulli rounding) where no cross-party stream agreement is needed,
    only np/jnp bit-parity. Golden model."""
    base = uniform_base(seed, mix)
    with np.errstate(over="ignore"):
        h = (np.arange(n, dtype=np.uint32) * np.uint32(0x9E3779B1) + base) \
            & np.uint32(0xFFFFFFFF)
    h = _np_mm3(h)
    return ((h >> np.uint32(8)).astype(np.float64) / float(1 << 24)).astype(dtype)


def jnp_uniform_parallel(seed: int, n: int, mix=0,
                         dtype=jnp.float32) -> jnp.ndarray:
    """Bit-exact jnp twin of np_uniform_parallel; ``mix`` may be traced."""
    base = jnp.asarray(uniform_base(seed, mix))
    h = mm3_finalize(jnp.arange(n, dtype=jnp.uint32)
                     * jnp.uint32(0x9E3779B1) + base)
    return ((h >> jnp.uint32(8)).astype(jnp.float32) / float(1 << 24)).astype(dtype)


def np_index_parallel(seed: int, k: int, size: int,
                      mix: int = 0) -> np.ndarray:
    """k pseudo-random indices in [0, size): the full 32-bit murmur3 hash
    modulo size. The former float-uniform derivation ((u * size) with a
    24-bit u) capped the distinct reachable indices at 2^24 — on leaves
    past 16.7M elements most coordinates were deterministically NEVER
    selected (never trained without EF; unbounded residual with EF).
    Golden model; jnp/Pallas/C++ must stay bit-identical."""
    base = uniform_base(seed, mix)
    with np.errstate(over="ignore"):
        h = (np.arange(k, dtype=np.uint32) * np.uint32(0x9E3779B1) + base) \
            & np.uint32(0xFFFFFFFF)
    h = _np_mm3(h)
    return (h % np.uint32(size)).astype(np.int32)


def jnp_index_parallel(seed: int, k: int, size, mix=0) -> jnp.ndarray:
    """Bit-exact jnp twin of np_index_parallel; ``mix``/``size`` may be
    traced."""
    base = jnp.asarray(uniform_base(seed, mix))
    h = mm3_finalize(jnp.arange(k, dtype=jnp.uint32)
                     * jnp.uint32(0x9E3779B1) + base)
    return (h % jnp.asarray(size).astype(jnp.uint32)).astype(jnp.int32)


def np_uniform(seed: int, n: int, mix: int = 0, dtype=np.float32) -> np.ndarray:
    """[0,1) floats from the top 24 bits of each golden draw."""
    bits = np_xorshift128p(seed, n, mix)
    return ((bits >> np.uint64(40)).astype(np.float64)
            / float(1 << 24)).astype(dtype)


def jnp_uniform(seed: int, n: int, mix=0, dtype=jnp.float32) -> jnp.ndarray:
    """Same values as np_uniform, computed from the (hi, lo) lanes: the top
    24 bits are hi >> 8."""
    hi, _ = jnp_xorshift128p(seed, n, mix)
    return ((hi >> np.uint32(8)).astype(jnp.float32)
            / float(1 << 24)).astype(dtype)
