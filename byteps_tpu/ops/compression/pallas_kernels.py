"""Pallas TPU kernels for the hot codec paths.

The reference's codecs are CPU OpenMP loops over host shared memory
(byteps/common/compressor/impl/*.cc); here the pack/unpack runs on the TPU's
vector unit so compressed push_pull never leaves the device (SURVEY.md §2.2
TPU note). The jnp implementations in codecs.py remain the reference
semantics (and the CPU-test path); these kernels are drop-in replacements
dispatched on TPU.

Layout: Mosaic cannot reshape the lane (last, 128-wide) dimension, so onebit
packs sign bits across the *sublane* dimension: input viewed as rows of 128
lanes; 32 consecutive rows fold into one uint32 row. Element i lives at
row i//128, lane i%128; its bit is bit (row % 32) of word
[row//32, lane]. Pack and unpack share this layout, so decompressed values
are identical to the jnp codec's (+/-scale per element) even though the
word order on the wire differs; the C++ PS mirror must use this same layout
when summing payloads natively.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_FOLD = 32                      # rows folded into one uint32 row
_BLOCK_WORD_ROWS = 8            # uint32 rows per grid step
_BLOCK_ROWS = _FOLD * _BLOCK_WORD_ROWS  # = 256 input rows per grid step


def _onebit_pack_kernel(x_ref, bits_ref):
    x = x_ref[:]                                    # (256, 128) f32
    signs = (x >= 0).astype(jnp.int32)
    grouped = signs.reshape(_BLOCK_WORD_ROWS, _FOLD, _LANES)
    # Mosaic has no unsigned reductions: accumulate in int32 (distinct
    # powers of two; the 1<<31 wraparound is benign) and bitcast after.
    weights = (jnp.int32(1) << jax.lax.broadcasted_iota(
        jnp.int32, (1, _FOLD, 1), 1))
    packed = jnp.sum(grouped * weights, axis=1, dtype=jnp.int32)
    bits_ref[:] = pltpu.bitcast(packed, jnp.uint32)


def _onebit_unpack_kernel(bits_ref, scale_ref, out_ref):
    bits = pltpu.bitcast(bits_ref[:], jnp.int32)    # (8, 128)
    expanded = bits[:, None, :] >> jax.lax.broadcasted_iota(
        jnp.int32, (1, _FOLD, 1), 1)
    on = (expanded & 1).astype(jnp.float32)         # (8, 32, 128)
    signs = on * 2.0 - 1.0
    out_ref[:] = signs.reshape(_BLOCK_ROWS, _LANES) * scale_ref[0]


def _padded_rows(n: int) -> int:
    rows = (n + _LANES - 1) // _LANES
    return (rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS * _BLOCK_ROWS


@functools.partial(jax.jit, static_argnums=(1,))
def onebit_pack(x: jnp.ndarray, interpret: bool = False):
    """Flat f32 [n] -> bits uint32[(rows//32) * 128] (scaling is the
    caller's job — see OnebitCodec).

    Sign convention matches OnebitCodec/onebit.cc:34-66; padding elements
    are 0 -> bit 1, sliced away by unpack.
    """
    n = x.shape[0]
    rows = _padded_rows(n)
    padded = jnp.zeros((rows * _LANES,), jnp.float32).at[:n].set(x)
    x2d = padded.reshape(rows, _LANES)

    bits = pl.pallas_call(
        _onebit_pack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows // _FOLD, _LANES), jnp.uint32),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_BLOCK_WORD_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x2d)
    return bits.reshape(-1)


@functools.partial(jax.jit, static_argnums=(2, 3))
def onebit_unpack(bits: jnp.ndarray, scale: jnp.ndarray, n: int,
                  interpret: bool = False) -> jnp.ndarray:
    """(bits, scale, n) -> flat f32 [n] of +/-scale (inverts onebit_pack)."""
    word_rows = bits.shape[0] // _LANES
    bits2d = bits.reshape(word_rows, _LANES)
    rows = word_rows * _FOLD
    scale_arr = jnp.full((1,), scale, jnp.float32)

    out = pl.pallas_call(
        _onebit_unpack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        grid=(word_rows // _BLOCK_WORD_ROWS,),
        in_specs=[
            pl.BlockSpec((_BLOCK_WORD_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bits2d, scale_arr)
    return out.reshape(-1)[:n]


def tpu_available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


# ------------------------------------------------------------------ #
# counter-based RNG codecs: dithering + randomk
#
# The per-element cost of these codecs is the murmur3 counter RNG plus
# the quantization arithmetic (reference: impl/dithering.cc:25-80,
# impl/randomk.cc:24-60 — OpenMP host loops). Here both fuse into one
# VPU pass: the uniform is derived in-register from the element's global
# index (rng.py np_uniform_parallel semantics, bit-exact), so compress
# reads x once and writes the levels once — no separate RNG pass or
# materialized uniforms in HBM.
# ------------------------------------------------------------------ #

_GOLDEN = 0x9E3779B1  # counter stride, must match rng.np_uniform_parallel


def _kernel_uniform(gidx_u32):
    """murmur3-finalizer uniform in [0,1) from a uint32 counter; bit-exact
    with rng.jnp_uniform_parallel because it calls the same rng helper
    (base already folded into the counter by the caller)."""
    from .rng import mm3_finalize
    h = mm3_finalize(gidx_u32)
    # Mosaic has no uint32->f32 cast; the top-24-bit value fits int32, so
    # bitcast and convert from there (exact for [0, 2^24))
    h24 = pltpu.bitcast(h >> jnp.uint32(8), jnp.int32)
    return h24.astype(jnp.float32) / float(1 << 24)


def _global_counter(base_u32, block_rows: int):
    """uint32 counter i*GOLDEN + base for each element of this grid block
    (row-major global element index)."""
    rid = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, _LANES), 0)
    lid = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, _LANES), 1)
    gidx = (jnp.uint32(pl.program_id(0)) * jnp.uint32(block_rows) + rid) \
        * jnp.uint32(_LANES) + lid
    return gidx * jnp.uint32(_GOLDEN) + base_u32


def _dither_linear_kernel(x_ref, fparams_ref, base_ref, out_ref):
    x = x_ref[:]
    norm, s = fparams_ref[0], fparams_ref[1]
    u = _kernel_uniform(_global_counter(base_ref[0], _BLOCK_ROWS))
    # identical op order to DitheringCodec.compress (linear) so levels
    # stay bit-equal: scaled = |x|/norm; pos = scaled*s; stochastic round
    pos = (jnp.abs(x) / norm) * s
    floor = jnp.floor(pos)
    level = floor + (u < (pos - floor)).astype(jnp.float32)
    level = jnp.minimum(level, s)
    out_ref[:] = (jnp.sign(x) * level).astype(jnp.int8)


def _dither_natural_kernel(x_ref, fparams_ref, base_ref, out_ref):
    x = x_ref[:]
    norm = fparams_ref[0]
    u = _kernel_uniform(_global_counter(base_ref[0], _BLOCK_ROWS))
    scaled = jnp.abs(x) / norm
    safe = jnp.maximum(scaled, 1e-30)
    j = jnp.clip(jnp.floor(-jnp.log2(safe)), 0.0, 30.0)
    low = jnp.exp2(-j - 1.0)
    high = jnp.exp2(-j)
    frac = (scaled - low) / (high - low)
    exp = jnp.where(u < frac, j, j + 1.0)
    # literal 2^-31: a scalar jnp.exp2 constant trips Mosaic's math-dialect
    # lowering (it expects a vector operand)
    level = jnp.where(scaled < jnp.float32(2.0 ** -31), 0.0, exp + 1.0)
    level = jnp.clip(level, 0.0, 126.0)
    out_ref[:] = (jnp.sign(x) * level).astype(jnp.int8)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def dithering_levels(x: jnp.ndarray, norm: jnp.ndarray, base: jnp.ndarray,
                     s: int, partition: str = "linear",
                     interpret: bool = False) -> jnp.ndarray:
    """Fused stochastic quantization: flat f32 [n] -> int8 signed levels
    [n]. ``norm`` is the (max or l2) scale computed by the caller; ``base``
    is the uint32 RNG base (seed-state low word XOR step) so the uniforms
    bit-match jnp_uniform_parallel(seed, n, mix=step)."""
    n = x.shape[0]
    rows = _padded_rows(n)
    padded = jnp.zeros((rows * _LANES,), jnp.float32).at[:n].set(x)
    x2d = padded.reshape(rows, _LANES)
    fparams = jnp.stack([norm.astype(jnp.float32),
                         jnp.float32(s)])
    base_arr = jnp.asarray(base, jnp.uint32).reshape(1)
    kernel = (_dither_linear_kernel if partition == "linear"
              else _dither_natural_kernel)

    levels = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int8),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x2d, fparams, base_arr)
    return levels.reshape(-1)[:n]


def _randomk_hash_kernel(base_ref, out_ref):
    """Raw murmur3 hash per lane (bitcast to int32 for VMEM); the caller
    takes ``% size`` in plain XLA — keeping the mod outside the kernel
    avoids relying on Mosaic uint32 remainder support while preserving
    the full 32-bit index range (a float-uniform derivation caps
    distinct indices at 2^24, wrong for size > 16.7M)."""
    from .rng import mm3_finalize

    h = mm3_finalize(_global_counter(base_ref[0], _BLOCK_ROWS))
    out_ref[:] = pltpu.bitcast(h, jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3))
def randomk_indices(base: jnp.ndarray, size: jnp.ndarray, k: int,
                    interpret: bool = False):
    """k pseudo-random indices in [0, size) from the counter RNG —
    bit-exact with RandomkCodec._indices / HostRandomk.indices. ``base``
    is the uint32 RNG base (rng.uniform_base(seed, step)); ``size`` the
    uncompressed element count."""
    rows = _padded_rows(k)
    base_arr = jnp.asarray(base, jnp.uint32).reshape(1)
    h = pl.pallas_call(
        _randomk_hash_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(base_arr)
    hu = h.reshape(-1)[:k].astype(jnp.uint32)
    return (hu % jnp.asarray(size).astype(jnp.uint32)).astype(jnp.int32)
