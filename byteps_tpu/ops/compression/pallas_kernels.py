"""Pallas TPU kernels for the hot codec paths.

The reference's codecs are CPU OpenMP loops over host shared memory
(byteps/common/compressor/impl/*.cc); here the pack/unpack runs on the TPU's
vector unit so compressed push_pull never leaves the device (SURVEY.md §2.2
TPU note). The jnp implementations in codecs.py remain the reference
semantics (and the CPU-test path); these kernels are drop-in replacements
dispatched on TPU.

Layout: Mosaic cannot reshape the lane (last, 128-wide) dimension, so onebit
packs sign bits across the *sublane* dimension: input viewed as rows of 128
lanes; 32 consecutive rows fold into one uint32 row. Element i lives at
row i//128, lane i%128; its bit is bit (row % 32) of word
[row//32, lane]. Pack and unpack share this layout, so decompressed values
are identical to the jnp codec's (+/-scale per element) even though the
word order on the wire differs; the C++ PS mirror must use this same layout
when summing payloads natively.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_FOLD = 32                      # rows folded into one uint32 row
_BLOCK_WORD_ROWS = 8            # uint32 rows per grid step
_BLOCK_ROWS = _FOLD * _BLOCK_WORD_ROWS  # = 256 input rows per grid step


def _onebit_pack_kernel(x_ref, bits_ref):
    x = x_ref[:]                                    # (256, 128) f32
    signs = (x >= 0).astype(jnp.int32)
    grouped = signs.reshape(_BLOCK_WORD_ROWS, _FOLD, _LANES)
    # Mosaic has no unsigned reductions: accumulate in int32 (distinct
    # powers of two; the 1<<31 wraparound is benign) and bitcast after.
    weights = (jnp.int32(1) << jax.lax.broadcasted_iota(
        jnp.int32, (1, _FOLD, 1), 1))
    packed = jnp.sum(grouped * weights, axis=1, dtype=jnp.int32)
    bits_ref[:] = pltpu.bitcast(packed, jnp.uint32)


def _onebit_unpack_kernel(bits_ref, scale_ref, out_ref):
    bits = pltpu.bitcast(bits_ref[:], jnp.int32)    # (8, 128)
    expanded = bits[:, None, :] >> jax.lax.broadcasted_iota(
        jnp.int32, (1, _FOLD, 1), 1)
    on = (expanded & 1).astype(jnp.float32)         # (8, 32, 128)
    signs = on * 2.0 - 1.0
    out_ref[:] = signs.reshape(_BLOCK_ROWS, _LANES) * scale_ref[0]


def _padded_rows(n: int) -> int:
    rows = (n + _LANES - 1) // _LANES
    return (rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS * _BLOCK_ROWS


@functools.partial(jax.jit, static_argnums=(1,))
def onebit_pack(x: jnp.ndarray, interpret: bool = False):
    """Flat f32 [n] -> bits uint32[(rows//32) * 128] (scaling is the
    caller's job — see OnebitCodec).

    Sign convention matches OnebitCodec/onebit.cc:34-66; padding elements
    are 0 -> bit 1, sliced away by unpack.
    """
    n = x.shape[0]
    rows = _padded_rows(n)
    padded = jnp.zeros((rows * _LANES,), jnp.float32).at[:n].set(x)
    x2d = padded.reshape(rows, _LANES)

    bits = pl.pallas_call(
        _onebit_pack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows // _FOLD, _LANES), jnp.uint32),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_BLOCK_WORD_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x2d)
    return bits.reshape(-1)


@functools.partial(jax.jit, static_argnums=(2, 3))
def onebit_unpack(bits: jnp.ndarray, scale: jnp.ndarray, n: int,
                  interpret: bool = False) -> jnp.ndarray:
    """(bits, scale, n) -> flat f32 [n] of +/-scale (inverts onebit_pack)."""
    word_rows = bits.shape[0] // _LANES
    bits2d = bits.reshape(word_rows, _LANES)
    rows = word_rows * _FOLD
    scale_arr = jnp.full((1,), scale, jnp.float32)

    out = pl.pallas_call(
        _onebit_unpack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        grid=(word_rows // _BLOCK_WORD_ROWS,),
        in_specs=[
            pl.BlockSpec((_BLOCK_WORD_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bits2d, scale_arr)
    return out.reshape(-1)[:n]


def tpu_available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False
