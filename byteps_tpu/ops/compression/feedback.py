"""Error feedback and momentum wrappers for compression codecs.

The reference stacks these decorator-style: Momentum wraps ErrorFeedback
wraps a base Compressor (compressor.h:28-52). Here the stack is a pure
function over (grad, state):

- ErrorFeedback (error_feedback.cc:22-43):
    corrected = grad + error
    payload   = codec.compress(corrected)
    error'    = corrected - codec.decompress(payload)
- Nesterov momentum (momentum.h:25-45, nesterov_momentum.cc:39-50): the
  velocity update runs *before* compression and must replace the framework
  optimizer's own momentum:
    m'   = mu * m + grad
    out  = grad + mu * m'

State lives in the optimizer state pytree (see compression_transform in
__init__.py), keeping everything functional/jit-compatible.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp

from .codecs import Codec


@dataclasses.dataclass(frozen=True)
class CompressorStack:
    """momentum -> error feedback -> base codec, any stage optional."""

    codec: Codec
    use_ef: bool = False
    momentum_mu: Optional[float] = None   # None = no momentum stage

    def init_state(self, size: int) -> Dict[str, Any]:
        st: Dict[str, Any] = {}
        if self.use_ef:
            st["error"] = jnp.zeros((size,), jnp.float32)
            # 0 = "no LR seen yet" (first rescale is a no-op); a fixed
            # key keeps the state pytree structure static under jit.
            # NOTE: added in round 2 — an optimizer-state checkpoint from
            # before then lacks this leaf; utils.checkpoint.restore()
            # migrates such checkpoints automatically (retries against
            # the legacy structure and reinserts the leaf as zeros).
            st["prev_lr"] = jnp.zeros((), jnp.float32)
        if self.momentum_mu is not None:
            st["momentum"] = jnp.zeros((size,), jnp.float32)
        return st

    def compress(self, grad: jnp.ndarray, state: Dict[str, Any],
                 step: int = 0, lr=None) -> Tuple[Dict[str, Any],
                                                  Dict[str, Any]]:
        """(payload, new_state). ``grad`` flat f32.

        ``lr``: current learning rate. When given (and EF is on), the
        carried residual is rescaled by prev_lr/cur_lr before reuse — a
        residual is "gradient still owed", and under a changed LR the
        owed *parameter delta* is what must be conserved (the reference's
        VanillaErrorFeedbackCompressor reads pre_lr/cur_lr from the
        mmap'd lr.s file the trainer writes each step,
        vanilla_error_feedback.cc:44-67, mxnet/__init__.py:326-331; here
        the LR flows as an explicit argument instead of a file
        side-channel). Omit lr for the constant-LR case (scale 1).
        """
        new_state = dict(state)
        x = grad
        if self.momentum_mu is not None:
            mu = self.momentum_mu
            m = mu * state["momentum"] + x
            new_state["momentum"] = m
            x = x + mu * m
        if self.use_ef:
            error = state["error"]
            if lr is not None:
                cur = jnp.asarray(lr, jnp.float32)
                prev = state["prev_lr"]
                # skip the rescale entirely at the boundaries: prev==0
                # means "no LR seen yet"; cur==0 (a schedule touching
                # zero, e.g. warm restarts) must not destroy the
                # residual — keep it, and keep prev so the next nonzero
                # LR rescales from the last real one
                ok = (prev != 0) & (cur != 0)
                scale = jnp.where(ok, prev / jnp.where(cur == 0, 1.0, cur),
                                  1.0)
                error = error * scale
                new_state["prev_lr"] = jnp.where(cur == 0, prev, cur)
            x = x + error
            payload = self.codec.compress(x, step)
            new_state["error"] = x - self.codec.decompress(payload)
        else:
            payload = self.codec.compress(x, step)
        return payload, new_state

    def decompress(self, payload: Dict[str, Any]) -> jnp.ndarray:
        return self.codec.decompress(payload)
