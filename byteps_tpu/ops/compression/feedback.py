"""Error feedback and momentum wrappers for compression codecs.

The reference stacks these decorator-style: Momentum wraps ErrorFeedback
wraps a base Compressor (compressor.h:28-52). Here the stack is a pure
function over (grad, state):

- ErrorFeedback (error_feedback.cc:22-43):
    corrected = grad + error
    payload   = codec.compress(corrected)
    error'    = corrected - codec.decompress(payload)
- Nesterov momentum (momentum.h:25-45, nesterov_momentum.cc:39-50): the
  velocity update runs *before* compression and must replace the framework
  optimizer's own momentum:
    m'   = mu * m + grad
    out  = grad + mu * m'

State lives in the optimizer state pytree (see compression_transform in
__init__.py), keeping everything functional/jit-compatible.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp

from .codecs import Codec


@dataclasses.dataclass(frozen=True)
class CompressorStack:
    """momentum -> error feedback -> base codec, any stage optional."""

    codec: Codec
    use_ef: bool = False
    momentum_mu: Optional[float] = None   # None = no momentum stage

    def init_state(self, size: int) -> Dict[str, Any]:
        st: Dict[str, Any] = {}
        if self.use_ef:
            st["error"] = jnp.zeros((size,), jnp.float32)
        if self.momentum_mu is not None:
            st["momentum"] = jnp.zeros((size,), jnp.float32)
        return st

    def compress(self, grad: jnp.ndarray, state: Dict[str, Any],
                 step: int = 0) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """(payload, new_state). ``grad`` flat f32."""
        new_state = dict(state)
        x = grad
        if self.momentum_mu is not None:
            mu = self.momentum_mu
            m = mu * state["momentum"] + x
            new_state["momentum"] = m
            x = x + mu * m
        if self.use_ef:
            x = x + state["error"]
            payload = self.codec.compress(x, step)
            new_state["error"] = x - self.codec.decompress(payload)
        else:
            payload = self.codec.compress(x, step)
        return payload, new_state

    def decompress(self, payload: Dict[str, Any]) -> jnp.ndarray:
        return self.codec.decompress(payload)
