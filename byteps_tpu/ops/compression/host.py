"""Host-side (numpy) wire codecs for the compressed DCN PS path.

The in-jit codecs (codecs.py) keep payloads as arrays for collectives; the
PS path needs flat byte strings on the wire and a server that can
decompress / sum / recompress (reference: server-side compressor mirror,
server.cc:92-118,228-257). This module defines THE wire format — shared by
three parties (signs/levels/indices bit-for-bit; reduction-derived scalars
like the onebit scale and the dithering l2 norm may differ by an ulp across
implementations, since summation order differs — tests compare those with
rtol=1e-6):

- this numpy implementation (worker host path + golden model for tests),
- the portable jnp codecs in codecs.py (on-device compress; the Pallas
  sublane-folded onebit layout is NOT wire format — PS codecs always use
  the portable layout),
- the C++ server (native/ps.cc CompressorCfg::{Compress,Decompress}).

Wire layouts (little-endian):
- onebit:    uint32 bits[ceil(n/32)], then f32 scale
- topk:      int32 idx[k], then f32 val[k]
- randomk:   int32 idx[k], then f32 val[k] (idx = 32-bit counter-murmur3
             hash mod n, ``np_index_parallel``, seeded by (seed, step)
             so worker and server agree)
- dithering: int8 levels[n], then f32 norm

Error feedback (vanilla) and momentum (nesterov) run worker-side only, as
in the reference (the server skips momentum, compressor_registry.cc:39-56).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from ...utils.logging import log
from .codecs import resolve_k
from .rng import np_uniform_parallel


class HostCodec:
    """Base: compress(x, step) -> bytes; decompress(buf) -> f32[n]."""

    n: int

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        raise NotImplementedError

    def decompress(self, buf: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def wire_bytes(self) -> int:
        raise NotImplementedError

    def kwargs_wire(self) -> str:
        """Serialized config for the server (parsed by ps.cc); mirrors the
        reference's in-band kwargs push (operations.cc:396-408)."""
        raise NotImplementedError


@dataclasses.dataclass
class HostOnebit(HostCodec):
    n: int
    scaled: bool = True

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        x = np.ascontiguousarray(x, np.float32)
        scale = np.float32(np.mean(np.abs(x))) if self.scaled \
            else np.float32(1.0)
        pad = (-self.n) % 32
        signs = np.empty(self.n + pad, np.uint8)
        np.greater_equal(x, 0, out=signs[: self.n])
        signs[self.n:] = 1  # zero-pad compresses as +1 (codecs.py parity)
        # packbits(bitorder='little') is byte-identical to the u32-LE
        # "bit i of word w = element w*32+i" wire layout (LE word bytes
        # ARE the ascending bit groups) and runs at C memory speed —
        # the explicit weights-multiply fold was 3x slower
        bits = np.packbits(signs, bitorder="little")
        return bits.tobytes() + scale.tobytes()

    def decompress(self, buf) -> np.ndarray:
        raw = np.frombuffer(buf, np.uint8)
        scale = raw[-4:].view(np.float32)[0]
        signs = np.unpackbits(raw[:-4], bitorder="little",
                              count=self.n)
        # 2-entry LUT gather: 3x faster than np.where with scalar
        # operands at multi-MB sizes
        return np.array([-scale, scale], np.float32)[signs]

    def wire_bytes(self) -> int:
        return ((self.n + 31) // 32) * 4 + 4

    def kwargs_wire(self) -> str:
        return (f"compressor=onebit;n={self.n};"
                f"scaling={1 if self.scaled else 0}")


@dataclasses.dataclass
class HostTopk(HostCodec):
    n: int
    k: int

    @staticmethod
    def select(x: np.ndarray, k: int) -> np.ndarray:
        """Top-k by (|x| desc, index asc) — the comparator the C++ server
        uses, deterministic under ties."""
        order = np.lexsort((np.arange(x.shape[0]), -np.abs(x)))
        return np.sort(order[:k]).astype(np.int32)  # ascending index order

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        x = np.ascontiguousarray(x, np.float32)
        idx = self.select(x, self.k)
        return idx.tobytes() + x[idx].astype(np.float32).tobytes()

    def decompress(self, buf) -> np.ndarray:
        raw = np.frombuffer(buf, np.uint8)
        idx = raw[: 4 * self.k].view(np.int32)
        val = raw[4 * self.k:].view(np.float32)
        out = np.zeros(self.n, np.float32)
        out[idx] = val
        return out

    def wire_bytes(self) -> int:
        return self.k * 8

    def kwargs_wire(self) -> str:
        return f"compressor=topk;n={self.n};k={self.k}"


@dataclasses.dataclass
class HostRandomk(HostCodec):
    n: int
    k: int
    seed: int = 0

    def indices(self, step: int) -> np.ndarray:
        # counter-based generator (parity with RandomkCodec._indices):
        # full-32-bit hash modulo n — see rng.np_index_parallel for why
        # the float-uniform form was wrong past n = 2^24
        from .rng import np_index_parallel
        return np_index_parallel(self.seed, self.k, self.n, mix=step)

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        x = np.ascontiguousarray(x, np.float32)
        idx = self.indices(step)
        return idx.tobytes() + x[idx].astype(np.float32).tobytes()

    def decompress(self, buf) -> np.ndarray:
        raw = np.frombuffer(buf, np.uint8)
        idx = raw[: 4 * self.k].view(np.int32)
        val = raw[4 * self.k:].view(np.float32)
        out = np.zeros(self.n, np.float32)
        out[idx] = val
        return out

    def wire_bytes(self) -> int:
        return self.k * 8

    def kwargs_wire(self) -> str:
        return f"compressor=randomk;n={self.n};k={self.k};seed={self.seed}"


def _uniform_fast(seed: int, n: int, mix: int) -> np.ndarray:
    """Bit-identical to rng.np_uniform_parallel (the golden model; a test
    asserts equality) with in-place passes: the counter/murmur chain and
    the [0,1) conversion allocate 2 arrays instead of ~8 — on the 4MB-
    partition hot path the temp churn was most of the compress time.
    (f32 divide by 2^24 is exact for 24-bit ints, so skipping the golden's
    f64 intermediate cannot change the result.)"""
    from .rng import uniform_base

    h = np.arange(n, dtype=np.uint32)
    t = np.empty(n, np.uint32)
    with np.errstate(over="ignore"):
        h *= np.uint32(0x9E3779B1)
        h += uniform_base(seed, mix)
        np.right_shift(h, 16, out=t); h ^= t
        h *= np.uint32(0x85EBCA6B)
        np.right_shift(h, 13, out=t); h ^= t
        h *= np.uint32(0xC2B2AE35)
        np.right_shift(h, 16, out=t); h ^= t
        h >>= 8
    u = h.astype(np.float32)
    u /= np.float32(1 << 24)
    return u


def _varint_encode(vals: np.ndarray) -> np.ndarray:
    """LEB128 bytes for nonnegative int64 values — vectorized (per-BYTE
    python loop, <=5 iterations, each pass full-width numpy)."""
    vals = np.asarray(vals, np.int64)
    if vals.size == 0:
        return np.zeros(0, np.uint8)
    nb = np.ones(vals.shape, np.int64)
    v = vals >> 7
    while v.any():
        nb += v > 0
        v >>= 7
    ends = np.cumsum(nb)
    out = np.zeros(int(ends[-1]), np.uint8)
    starts = ends - nb
    for j in range(int(nb.max())):
        sel = nb > j
        byte = (vals[sel] >> (7 * j)) & 0x7F
        cont = np.where(j < nb[sel] - 1, 0x80, 0)
        out[starts[sel] + j] = (byte | cont).astype(np.uint8)
    return out


def _varint_decode(buf_u8: np.ndarray, count: int):
    """(values int64[count], bytes_consumed) — vectorized LEB128 decode
    of the first ``count`` varints in ``buf_u8``."""
    if count == 0:
        return np.zeros(0, np.int64), 0
    term = (buf_u8 & 0x80) == 0
    ends = np.flatnonzero(term)
    if len(ends) < count:
        raise ValueError("truncated varint stream")
    last = int(ends[count - 1])
    b = buf_u8[: last + 1].astype(np.int64)
    e = ends[:count]
    starts = np.concatenate(([0], e[:-1] + 1))
    gid = np.zeros(last + 1, np.int64)
    gid[starts[1:]] = 1
    gid = np.cumsum(gid)
    # cap matches the C++ decoder (shift > 35 rejected): values stay
    # < 2^42, so a cumsum of <= 2^31 of them cannot overflow int64 and
    # wrap an index negative past the bounds checks
    shift = (np.arange(last + 1) - starts[gid]) * 7
    if int(shift.max(initial=0)) > 35:
        raise ValueError("varint too long")
    vals = np.zeros(count, np.int64)
    np.add.at(vals, gid, (b & 0x7F) << shift)
    return vals, last + 1


@dataclasses.dataclass
class HostDithering(HostCodec):
    n: int
    s: int = 127
    partition: str = "linear"
    normalize: str = "max"
    seed: int = 0

    def __post_init__(self):
        # same bound as DitheringCodec and the C++ parser (ps.cc): a
        # level must fit signed int8; s=255 (plausible under the
        # reference's compressor_k convention) would silently wrap the
        # int8 cast and flip signs on the wire while the server rejects
        # the same kwargs — fail fast and symmetrically instead
        if not 1 <= self.s <= 127:
            raise ValueError(
                f"dithering levels s={self.s} out of range [1, 127] "
                f"(levels ship as signed int8 on the wire)")
    # "varint": delta+LEB128-coded nonzero indices + int8 levels on the
    # wire — the reference's coded sparse dithering format
    # (impl/dithering.cc:25-80, compressor/utils.h BitWriter), byte-
    # aligned here. Wire bytes ~ 2 x nnz instead of n: at low s most
    # levels quantize to zero and the wire shrinks accordingly. The wire
    # is then VARIABLE-LENGTH (wire_bytes() is the allocation bound);
    # only the host/C++ tier supports it (the on-device payload stays
    # dense int8 — XLA needs static shapes).
    index_coding: str = "dense"

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        x = np.ascontiguousarray(x, np.float32)
        absx = np.abs(x)
        m = absx.max(initial=np.float32(0))
        if self.normalize == "max":
            norm = m
        else:
            # scale-invariant two-pass l2: |x| up to float32 max would
            # overflow x*x to inf (and decompress to 0*inf = NaN)
            safe_m = np.float32(max(m, 1e-30))
            norm = safe_m * np.float32(
                np.sqrt(np.sum(np.square(absx / safe_m))))
        norm = np.float32(max(norm, 1e-30))
        u = _uniform_fast(self.seed, self.n, step)
        if self.partition == "linear":
            # in-place chain, same op ORDER as the jnp codec (rounding
            # parity): scaled = |x|/norm; pos = scaled*s; stochastic round
            pos = absx            # reuse: absx is dead after norm
            pos /= norm
            pos *= np.float32(self.s)
            floor = np.floor(pos)
            pos -= floor          # pos is now frac
            level = floor
            np.add(level, u < pos, out=level, casting="unsafe")
            # l2 norm can round below max|x| -> scaled > 1 -> level s+1
            # would wrap the int8 cast at s=127
            np.minimum(level, np.float32(self.s), out=level)
        else:
            scaled = (absx / norm).astype(np.float32)
            safe = np.maximum(scaled, np.float32(1e-30))
            j = np.clip(np.floor(-np.log2(safe)), 0, 30).astype(np.float32)
            low = np.exp2(-j - 1).astype(np.float32)
            high = np.exp2(-j).astype(np.float32)
            frac = (scaled - low) / (high - low)
            exp = np.where(u < frac, j, j + 1)
            level = np.where(scaled < np.exp2(np.float32(-31.0)),
                             np.float32(0.0), exp + 1.0)
            level = np.clip(level, 0, 126)
        levels = (np.sign(x) * level).astype(np.int8)
        if self.index_coding == "varint":
            nz = np.flatnonzero(levels)
            gaps = np.empty(len(nz), np.int64)
            if len(nz):
                gaps[0] = nz[0] + 1  # implicit start index -1
                gaps[1:] = np.diff(nz)
            gb = _varint_encode(gaps)
            return (np.uint32(len(nz)).tobytes() + gb.tobytes()
                    + levels[nz].tobytes() + np.float32(norm).tobytes())
        return levels.tobytes() + np.float32(norm).tobytes()

    def _dense_levels(self, buf) -> tuple:
        """(int8 levels[n], norm) from either wire form."""
        raw = np.frombuffer(buf, np.uint8)
        if self.index_coding != "varint":
            return raw[: self.n].view(np.int8), \
                raw[self.n: self.n + 4].view(np.float32)[0]
        nnz = int(raw[:4].copy().view(np.uint32)[0])
        if nnz > self.n:
            raise ValueError(f"varint dithering wire: nnz {nnz} > n")
        gaps, used = _varint_decode(raw[4: len(raw) - 4 - nnz], nnz)
        if used != len(raw) - 8 - nnz:
            raise ValueError("varint dithering wire: trailing bytes")
        idx = np.cumsum(gaps) - 1
        if len(idx) and (gaps.min() < 1 or gaps.max() > self.n
                         or idx[-1] >= self.n):
            raise ValueError("varint dithering wire: bad indices")
        lv = np.zeros(self.n, np.int8)
        lv[idx] = raw[4 + used: 4 + used + nnz].view(np.int8)
        return lv, raw[-4:].copy().view(np.float32)[0]

    def decompress(self, buf) -> np.ndarray:
        lv, norm = self._dense_levels(buf)
        lv = lv.astype(np.float32)
        if self.partition == "linear":
            mag = np.abs(lv) / np.float32(self.s)
        else:
            mag = np.where(lv == 0, np.float32(0.0),
                           np.exp2(-(np.abs(lv) - 1.0)).astype(np.float32))
        return (np.sign(lv) * mag * norm).astype(np.float32)

    def wire_bytes(self) -> int:
        # varint: allocation BOUND (worst case all-nonzero + multi-byte
        # gap slack); actual wires are shorter — matches ps.cc WireLen()
        if self.index_coding == "varint":
            return 2 * self.n + self.n // 64 + 16
        return self.n + 4

    def kwargs_wire(self) -> str:
        extra = ";index_coding=varint" if self.index_coding == "varint" \
            else ""
        return (f"compressor=dithering;n={self.n};s={self.s};"
                f"partition_type={self.partition};"
                f"normalize_type={self.normalize};seed={self.seed}{extra}")


class HostErrorFeedback:
    """Vanilla EF wrapper (error_feedback.cc:22-43): corrected = grad +
    error; payload = compress(corrected); error = corrected -
    decompress(payload). State persists across steps per tensor/partition.
    """

    def __init__(self, codec: HostCodec):
        self.codec = codec
        self.error = np.zeros(codec.n, np.float32)

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        corrected = x.astype(np.float32) + self.error
        buf = self.codec.compress(corrected, step)
        # the reference fuses this as FastUpdateError (onebit.cc:113-140);
        # in numpy the "fused" form is the same unpack+gather+subtract
        # passes, so the plain decompose keeps one wire parser
        self.error = corrected - self.codec.decompress(
            np.frombuffer(buf, np.uint8))
        return buf

    def decompress(self, buf) -> np.ndarray:
        return self.codec.decompress(buf)

    def wire_bytes(self) -> int:
        return self.codec.wire_bytes()

    def kwargs_wire(self) -> str:
        return self.codec.kwargs_wire()


class HostNesterovMomentum:
    """Worker-side nesterov momentum pre-pass (momentum.h:25-45): m = mu*m
    + g; compress(g + mu*m). Must replace framework momentum."""

    def __init__(self, inner, mu: float = 0.9):
        self.inner = inner
        self.mu = np.float32(mu)
        self.m = np.zeros(inner.codec.n if isinstance(inner, HostErrorFeedback)
                          else inner.n, np.float32)

    def compress(self, x: np.ndarray, step: int = 0) -> bytes:
        self.m = self.mu * self.m + x.astype(np.float32)
        return self.inner.compress(x + self.mu * self.m, step)

    def decompress(self, buf) -> np.ndarray:
        return self.inner.decompress(buf)

    def wire_bytes(self) -> int:
        return self.inner.wire_bytes()

    def kwargs_wire(self) -> str:
        return self.inner.kwargs_wire()


_warned_approx: list = []  # once-per-process dedup for the approx warning


def make_host_codec(kwargs: Dict[str, str], n: int):
    """Registry: kwargs dict -> (momentum ->) (EF ->) codec stack, same
    lookup order as the reference (compressor_registry.cc:39-56) and same
    parameter names as ops.compression.make_compressor."""
    from . import parse_bool_kwarg

    name = kwargs.get("compressor")
    if name == "lossless":
        # byte-plane + entropy tier (lossless.py): bitwise round-trip,
        # so EF/momentum stacking is pointless (the error is always 0)
        # but harmless; the numpy+zlib tier IS the host implementation —
        # the wire is self-describing, so no native bit-parity tier is
        # needed (module-top contract does not apply)
        from .lossless import HostLossless
        codec: HostCodec = HostLossless(n=n)
    elif name == "onebit":
        codec = HostOnebit(
            n=n, scaled=parse_bool_kwarg(kwargs, "scaling", "true"))
    elif name == "topk":
        if parse_bool_kwarg(kwargs, "approx") and not _warned_approx:
            # ApproxTopK is a TPU hardware op; the host (numpy) tier runs
            # the exact selection. Warn (once — this runs per partition)
            # instead of silently dropping the kwarg so a user following
            # the docs knows which tier the knob applies to.
            _warned_approx.append(True)
            log.warning("topk approx=1 applies to the in-jit TPU tier "
                        "only; the host/PS codec uses exact selection")
        codec = HostTopk(n=n, k=resolve_k(float(kwargs.get("k", 0.01)), n))
    elif name == "randomk":
        codec = HostRandomk(n=n, k=resolve_k(float(kwargs.get("k", 0.01)), n),
                            seed=int(kwargs.get("seed", 0)))
    elif name == "dithering":
        coding = kwargs.get("index_coding", "dense")
        if coding not in ("dense", "varint"):
            raise ValueError(f"unknown index_coding {coding!r}")
        codec = HostDithering(
            # level count: "s" with fallback to "k" — the reference
            # passes dithering's levels as compressor_k
            # (dithering.cc:31), so adapter attribute bags arrive as
            # "k"; the server inherits the resolved value via
            # kwargs_wire either way
            n=n, s=int(kwargs.get("s", kwargs.get("k", 127))),
            partition=kwargs.get("partition_type", "linear"),
            normalize=kwargs.get("normalize_type", "max"),
            seed=int(kwargs.get("seed", 0)), index_coding=coding)
    else:
        raise ValueError(f"unknown compressor {name!r}")
    # hot-path acceleration: the deterministic codecs route through the
    # C++ implementation the server already mirrors (native.py; kill
    # switch BYTEPS_NATIVE_CODEC=0) — signs/indices/values bit-identical
    # to the numpy golden, reduction-derived scalars (the onebit scale)
    # within an ulp (module-top contract); numpy stays the golden model
    # and the fallback
    from .native import maybe_native

    native = maybe_native(kwargs, codec.kwargs_wire(), n)
    if native is not None:
        codec = native
    stack = codec
    from . import parse_ef_kwarg
    if parse_ef_kwarg(kwargs):
        stack = HostErrorFeedback(stack)
    from . import parse_momentum_kwarg
    if parse_momentum_kwarg(kwargs):
        if not isinstance(stack, HostErrorFeedback):
            raise ValueError("momentum requires ef=vanilla (reference "
                             "stacking order, compressor.h:28-52)")
        stack = HostNesterovMomentum(stack,
                                     mu=float(kwargs.get("momentum_mu", 0.9)))
    return stack
