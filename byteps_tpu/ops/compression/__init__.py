"""Gradient compression for byteps_tpu.

Public surface:

- ``make_compressor(kwargs, size)``: string-kwargs registry mirroring the
  reference's CompressorRegistry (compressor_registry.cc:39-56). Keys follow
  the reference's python-side parameter names (byteps/mxnet/__init__.py:236-317):
  ``compressor`` (onebit|topk|randomk|dithering), ``ef`` (vanilla),
  ``momentum`` (nesterov), ``k``, ``scaling``, ``seed``, ``s`` (dithering
  levels), ``partition_type`` (linear|natural), ``normalize_type`` (max|l2),
  ``momentum_mu``.
- ``compressed_psum_tree(grads, states, stacks, axis, step)``: the
  compressed allreduce — each replica compresses its shard-local gradient,
  payloads all_gather over the mesh axis (this is the bandwidth win: k<<n
  or 1 bit/elem on the wire instead of 4 bytes/elem), every replica
  decompresses and sums. Mirrors the reference dataflow COMPRESS -> PUSH ->
  server sum of decompressed -> PULL -> DECOMPRESS (core_loops.cc:498-648,
  server.cc:92-118), collapsed into collectives.
- ``compression_transform(...)``: optax transformation carrying EF/momentum
  state, composed by byteps_tpu.jax.distributed_optimizer.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ...config import DEFAULT_MIN_COMPRESS_BYTES
from .codecs import (
    Codec, DitheringCodec, OnebitCodec, RandomkCodec, TopkCodec, resolve_k,
)
from .feedback import CompressorStack

__all__ = [
    "Codec", "OnebitCodec", "TopkCodec", "RandomkCodec", "DitheringCodec",
    "CompressorStack", "make_compressor", "compressed_psum_tree",
    "compression_transform", "default_stacks", "NO_COMPRESS",
]


_REGISTRY = {}


def parse_bool_kwarg(kwargs: Dict[str, str], name: str,
                     default: str = "false") -> bool:
    """Shared string-truthiness rule for codec kwargs — one definition so
    the worker registry, the host registry, and the wire stay in
    lockstep."""
    return str(kwargs.get(name, default)).lower() in ("1", "true", "yes")


def parse_ef_kwarg(kwargs) -> bool:
    """ONE truthiness rule for the ``ef`` kwarg across every tier
    (device/collective, host/PS, server wire): the reference type string
    "vanilla" or any boolean-true spelling enables vanilla error
    feedback. Tier-divergent parsing silently dropped EF when a config
    moved from the collective tier to the PS tier."""
    v = str(kwargs.get("ef", "")).lower()
    if v in ("vanilla", "true", "1", "yes"):
        return True
    if v in ("", "0", "false", "no", "none", "off"):
        return False
    # a typo ('vanila') must not silently drop EF — the exact failure
    # mode this helper exists to prevent
    raise ValueError(f"unknown ef type {kwargs.get('ef')!r}; "
                     f"use 'vanilla' (or a boolean spelling)")


def parse_momentum_kwarg(kwargs) -> bool:
    """ONE rule for the ``momentum`` kwarg across tiers (same rationale
    as parse_ef_kwarg): 'nesterov' enables it, falsy spellings disable,
    anything else fails fast."""
    mom = str(kwargs.get("momentum", "")).lower()
    if mom in ("nesterov",):
        return True
    if mom in ("", "none", "0", "false", "no", "off"):
        return False
    raise ValueError(f"unknown momentum type "
                     f"{kwargs.get('momentum')!r}; use 'nesterov'")


def register_codec(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


@register_codec("onebit")
def _make_onebit(kwargs: Dict[str, str], size: int) -> Codec:
    return OnebitCodec(size=size,
                       scaled=parse_bool_kwarg(kwargs, "scaling", "true"))


@register_codec("topk")
def _make_topk(kwargs: Dict[str, str], size: int) -> Codec:
    k = resolve_k(float(kwargs.get("k", 0.01)), size)
    return TopkCodec(size=size, k=k,
                     approx=parse_bool_kwarg(kwargs, "approx"))


@register_codec("randomk")
def _make_randomk(kwargs: Dict[str, str], size: int) -> Codec:
    k = resolve_k(float(kwargs.get("k", 0.01)), size)
    return RandomkCodec(size=size, k=k, seed=int(kwargs.get("seed", 0)))


@register_codec("dithering")
def _make_dithering(kwargs: Dict[str, str], size: int) -> Codec:
    return DitheringCodec(
        size=size,
        # "s" with "k" fallback: the reference passes dithering's level
        # count as compressor_k (dithering.cc:31)
        s=int(kwargs.get("s", kwargs.get("k", 127))),
        partition=kwargs.get("partition_type", "linear"),
        normalize=kwargs.get("normalize_type", "max"),
        seed=int(kwargs.get("seed", 0)),
    )


def make_compressor(kwargs: Dict[str, str], size: int) -> CompressorStack:
    """Build the full momentum->EF->codec stack from string kwargs
    (reference lookup order, compressor_registry.cc:39-56)."""
    name = kwargs.get("compressor")
    if name not in _REGISTRY:
        raise ValueError(f"unknown compressor {name!r}; "
                         f"have {sorted(_REGISTRY)}")
    codec = _REGISTRY[name](kwargs, size)
    use_ef = parse_ef_kwarg(kwargs)
    mu = None
    if parse_momentum_kwarg(kwargs):
        if not use_ef:
            # same contract as the host tier (make_host_codec) and the
            # reference stacking order (compressor.h:28-52: Momentum
            # wraps ErrorFeedback wraps the codec) — a tier-divergent
            # rule would silently change training semantics when a
            # config moves between the collective and PS paths
            raise ValueError("momentum requires ef=vanilla (reference "
                             "stacking order, compressor.h:28-52)")
        mu = float(kwargs.get("momentum_mu", 0.9))
    return CompressorStack(codec=codec, use_ef=use_ef, momentum_mu=mu)


# ------------------------------------------------------------------ #
# compressed cross-replica reduction
# ------------------------------------------------------------------ #

class _NoCompress:
    """Sentinel for 'leave this leaf uncompressed'. (None would vanish from
    jax pytrees — None is an empty subtree, not a leaf.)"""

    def __repr__(self):
        return "NO_COMPRESS"


NO_COMPRESS = _NoCompress()


def _is_stack_leaf(x):
    return isinstance(x, (CompressorStack, _NoCompress))


def compressed_psum_tree(grads: Any, states: Any, stacks: Any,
                         axis: str, step, average: bool = True, lr=None):
    """Compress each leaf, all_gather payloads over ``axis``, sum the
    decompressed replicas. Returns (reduced_grads, new_states).

    ``stacks``: pytree of CompressorStack aligned with grads leaves
    (NO_COMPRESS leaf = plain psum). ``states``: matching pytree of state
    dicts. Call inside shard_map with ``axis`` bound. ``lr``: current
    learning rate, for the EF residual rescale under LR schedules
    (feedback.CompressorStack.compress).
    """
    n = jax.lax.axis_size(axis)

    def reduce_leaf(g, st, stack):
        if not isinstance(stack, CompressorStack):
            summed = jax.lax.psum(g, axis_name=axis)
            return (summed / n if average else summed), st
        shape = g.shape
        flat = g.reshape(-1).astype(jnp.float32)
        payload, new_st = stack.compress(flat, st, step, lr=lr)
        gathered = jax.lax.all_gather(payload, axis_name=axis)  # leading n
        dec = jax.vmap(stack.decompress)(gathered)
        total = jnp.sum(dec, axis=0)
        if average:
            total = total / n
        return total.reshape(shape).astype(g.dtype), new_st

    flat_g, treedef = jax.tree.flatten(grads)
    flat_st = treedef.flatten_up_to(states)
    flat_stacks = treedef.flatten_up_to(stacks)
    out = [reduce_leaf(g, st, sk)
           for g, st, sk in zip(flat_g, flat_st, flat_stacks)]
    new_grads = treedef.unflatten([o[0] for o in out])
    new_states = treedef.unflatten([o[1] for o in out])
    return new_grads, new_states


def _resolve_min_compress_bytes(v: Optional[int]) -> int:
    """None -> BYTEPS_MIN_COMPRESS_BYTES from the live config (global.cc:43),
    falling back to the compiled-in default."""
    if v is not None:
        return v
    try:
        from ...core.state import get_state
        state = get_state()
        if state.initialized:
            return state.config.min_compress_bytes
    except Exception:  # noqa: BLE001
        pass
    return DEFAULT_MIN_COMPRESS_BYTES


def default_stacks(params: Any, kwargs: Dict[str, str],
                   min_compress_bytes: Optional[int] = None) -> Any:
    """Per-leaf CompressorStack pytree: leaves smaller than
    ``min_compress_bytes`` stay uncompressed (reference:
    BYTEPS_MIN_COMPRESS_BYTES, operations.cc:361-364)."""
    min_compress_bytes = _resolve_min_compress_bytes(min_compress_bytes)

    def for_leaf(p):
        nbytes = int(np.prod(p.shape)) * 4
        if nbytes < min_compress_bytes:
            return NO_COMPRESS
        return make_compressor(kwargs, int(np.prod(p.shape)))

    return jax.tree.map(for_leaf, params)


def compression_transform(params_example: Any, kwargs: Dict[str, str],
                          axis: str = "dp", average: bool = True,
                          min_compress_bytes: Optional[int] = None,
                          lr_schedule=None):
    """optax GradientTransformation performing compressed cross-replica
    reduction with EF/momentum state. Compose before the base optimizer:

        tx = optax.chain(compression_transform(params, kw), optax.adam(...))

    (byteps_tpu.jax.distributed_optimizer does this wiring when given a
    ``compression`` kwargs dict.) Must run inside shard_map with ``axis``
    bound.

    ``lr_schedule``: optional step -> lr callable (typically the same
    optax schedule the base optimizer uses). When given, the EF residual
    is rescaled by prev_lr/cur_lr across LR changes
    (CompressorStack.compress; the reference's lr.s mechanism).
    """
    stacks = default_stacks(params_example, kwargs, min_compress_bytes)

    def init_fn(params):
        def st(p, stack):
            if not isinstance(stack, CompressorStack):
                return {}
            return stack.init_state(int(np.prod(p.shape)))
        states = jax.tree.map(st, params, stacks, is_leaf=_is_stack_leaf)
        return {"compress": states, "step": jnp.zeros((), jnp.int32)}

    def update_fn(grads, state, params=None):
        del params
        lr = lr_schedule(state["step"]) if lr_schedule is not None else None
        reduced, new_states = compressed_psum_tree(
            grads, state["compress"], stacks, axis, state["step"],
            average=average, lr=lr)
        return reduced, {"compress": new_states,
                         "step": state["step"] + 1}

    return optax.GradientTransformation(init_fn, update_fn)
