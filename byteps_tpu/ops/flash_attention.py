"""Flash attention for long sequences (S >= ~4k).

The dense attention in models/llama.py materializes [B, H, S, S] scores;
XLA fuses the softmax well enough that at S=1024 on v5e it beats a
hand-written kernel (measured, docs/performance.md "rejected" table).
The quadratic HBM term wins at longer S, so long-context runs get:

- ``blockwise_attention`` — jnp ``lax.scan`` over KV blocks with the
  streaming-softmax fold (the same math as ring attention's per-step
  fold, parallel/ring_attention.py:35-52, with the ring replaced by a
  local block loop). Differentiable by construction (XLA AD through the
  scan; jax.checkpoint per block bounds the residency at
  O(S * block_k)), runs on any backend — the portable reference
  semantics and the autodiff path.
- ``flash_attention`` — Pallas TPU forward kernel (one [block_q, hd]
  output tile per grid step, online softmax across the K grid, causal
  blocks skipped) with a ``jax.custom_vjp`` whose backward recomputes
  through ``blockwise_attention`` — fwd pays zero S^2 HBM, bwd trades
  FLOPs for memory exactly like the remat the model already runs.
  Falls back to ``blockwise_attention`` off-TPU.

Green-field component (the reference has no attention kernels at all —
it is a communication library; SURVEY §5.7 long-context is TPU-side
design). Interface matches models.llama ``attn_impl``:
q [B,S,H,D], k/v [B,S,Hkv,D] (GQA), causal, scale 1/sqrt(D).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30

# the streaming-softmax fold is THE subtle math here — one definition,
# shared with the ring (same shape contract; ring_attention.py:35-52)
from ..parallel.ring_attention import _block_attn_accum as _fold  # noqa: E402,E501


def blockwise_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = True, block_k: int = 512,
                        remat: bool = True) -> jnp.ndarray:
    """Exact attention streaming over KV blocks: peak residency
    O(S * block_k) instead of O(S^2). q [B,S,H,D], k/v [B,S,Hkv,D]."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    groups = H // Hkv
    block_k = min(block_k, S)
    if S % block_k:
        raise ValueError(f"S={S} not divisible by block_k={block_k}")
    nk = S // block_k
    scale = 1.0 / np.sqrt(D)
    q32 = q.astype(jnp.float32)
    # [nk, B, bk, Hkv, D] so scan carries one block per step. KV stay in
    # COMPACT Hkv heads and original dtype here: a whole-sequence GQA
    # repeat (+fp32 cast) before the scan would multiply KV residency by
    # (H/Hkv)*(32/16) in HBM — on the backward-recompute path this module
    # exists to keep small. The per-block expand happens in body (same
    # arrangement as ring_attention.body).
    ks = k.reshape(B, nk, block_k, Hkv, D).transpose(1, 0, 2, 3, 4)
    vs = v.reshape(B, nk, block_k, Hkv, D).transpose(1, 0, 2, 3, 4)
    qpos = jnp.arange(S)
    kpos_blk = jnp.arange(block_k)

    def body(carry, blk):
        m, l, o = carry
        j, kb, vb = blk
        kb = kb.astype(jnp.float32)
        vb = vb.astype(jnp.float32)
        if groups > 1:
            kb = jnp.repeat(kb, groups, axis=2)
            vb = jnp.repeat(vb, groups, axis=2)
        if causal:
            mask = qpos[:, None] >= (j * block_k + kpos_blk)[None, :]
        else:
            mask = None
        m, l, o = _fold(q32, kb, vb, mask, m, l, o, scale)
        return (m, l, o), None

    fold_fn = body
    if remat:
        fold_fn = jax.checkpoint(body)

    m0 = jnp.full((B, H, S), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    o0 = jnp.zeros((B, S, H, D), jnp.float32)
    (m, l, o), _ = jax.lax.scan(
        fold_fn, (m0, l0, o0), (jnp.arange(nk), ks, vs))
    l = jnp.maximum(l, 1e-30)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


# --------------------------------------------------------------------- #
# Pallas forward kernel
# --------------------------------------------------------------------- #


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                      *, block_q: int, block_k: int, nk: int, scale: float,
                      causal: bool):
    """Grid (B, H, nq, nk) — innermost nk sequential ("arbitrary"):
    scratch carries the online softmax state across k blocks for one
    [block_q, D] output tile."""
    import jax.experimental.pallas as pl

    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: block j contributes only when its first key position is
    # <= the tile's last query position (j >= 0 == always, kept traced)
    live = (j * block_k <= i * block_q + block_q - 1) if causal \
        else (j >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)       # [bq, D]
        kb = k_ref[0, 0].astype(jnp.float32)      # [bk, D]
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_ref[:, :1]                     # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    Hkv = k.shape[2]
    groups = H // Hkv
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"S={S} not divisible by blocks "
                         f"({block_q}, {block_k})")
    nq, nk = S // block_q, S // block_k
    scale = 1.0 / np.sqrt(D)

    # [B,H,S,D] layout: one (b, h, tile) per grid step
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, nk=nk,
        scale=scale, causal=causal)

    out = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j, g=groups: (b, h // g, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
        ],
        # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams; accept
        # both so the kernel builds against either line
        compiler_params=getattr(
            pltpu, "CompilerParams",
            getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)  # back to [B,S,H,D]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512):
    """Pallas flash attention forward (TPU), blockwise-recompute
    backward. Off-TPU (tests, CPU mesh) the forward also runs the
    portable blockwise path, so behavior is uniform."""
    if jax.default_backend() == "tpu":
        return _flash_fwd(q, k, v, causal, block_q, block_k)
    return blockwise_attention(q, k, v, causal=causal, block_k=block_k)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k):
    out = flash_attention(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    # recompute through the differentiable blockwise path: same fold
    # math, so gradients are exact for the same function
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, block_k=block_k), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def make_flash_attn(causal: bool = True, block_q: int = 512,
                    block_k: int = 512, pallas: Optional[bool] = None):
    """Bind as a models.llama ``attn_impl``. ``pallas=False`` forces the
    jnp blockwise path even on TPU (A/B-ing the kernel)."""

    def impl(q, k, v):
        if pallas is False:
            return blockwise_attention(q, k, v, causal=causal,
                                       block_k=block_k)
        return flash_attention(q, k, v, causal, block_q, block_k)

    return impl
