"""push_pull: gradient summation over the device mesh.

This is the TPU-native core of the framework. The reference implements
push_pull as a 12-stage host-thread pipeline: NCCL ReduceScatter inside the
machine, ZPush/ZPull to parameter servers between machines, NCCL AllGather
back out (reference: byteps/common/core_loops.cc:190-268,538-618). On TPU the
intra-slice part compiles into the XLA program:

- ``psum_tree``            — one-shot allreduce (lax.psum over the dp axis)
- ``reduce_scatter_tree``  — each device ends up owning 1/N of every gradient
  (the analogue of the reference's "each GPU owns 1/local_size of every
  partition" layout, core_loops.cc:216-268)
- ``all_gather_tree``      — rebuild full params from shards (BROADCAST stage)

These are meant to be called *inside* ``shard_map`` / ``pjit`` where the mesh
axis name is bound; XLA then schedules the collectives asynchronously and
overlaps them with compute — which is exactly the pipelining the reference
builds by hand with priority queues and stage threads.

The eager, Horovod-style ``push_pull(x)`` entry point (one call per tensor,
used by the adapter API and tests) wraps the same collectives in a cached
jitted shard_map over the global mesh.

Cross-slice (DCN) aggregation goes through byteps_tpu.server instead — see
that module; this one is pure ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..core.state import get_state
from ..core.types import DataType
from ..parallel.mesh import DP_AXIS


# ---------------------------------------------------------------------- #
# in-jit collectives (call inside shard_map/pjit)
# ---------------------------------------------------------------------- #

def psum_tree(tree: Any, axis: str = DP_AXIS, average: bool = True) -> Any:
    """Sum (or mean) every leaf across ``axis``. The REDUCE+PUSH+PULL+
    BROADCAST pipeline collapsed into one XLA allreduce. Integer leaves keep
    their dtype under averaging (truncating, like the reference's post-hoc
    ``div_(size)`` on int tensors, torch/ops.cc:78-90)."""
    summed = jax.lax.psum(tree, axis_name=axis)
    if average:
        n = jax.lax.axis_size(axis)

        def avg(g):
            if jnp.issubdtype(g.dtype, jnp.integer):
                # lax.div truncates toward zero like the reference's C++
                # div_(size) — floor division would skew every negative
                # element by one
                return jax.lax.div(g, jnp.asarray(n, g.dtype))
            return g / n

        summed = jax.tree.map(avg, summed)
    return summed


def pmean_tree(tree: Any, axis: str = DP_AXIS) -> Any:
    return psum_tree(tree, axis, average=True)


def _scatter_leaf(g: jnp.ndarray, axis: str, average: bool) -> jnp.ndarray:
    """ReduceScatter one leaf along its leading dim; pads to make the leading
    dim divisible by the axis size (the reference pads partitions to page
    multiples for the same reason, global.cc:140-144)."""
    n = jax.lax.axis_size(axis)
    flat = g.reshape(-1)
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    out = jax.lax.psum_scatter(flat.reshape(n, -1), axis_name=axis,
                               scatter_dimension=0, tiled=False)
    if average:
        if jnp.issubdtype(out.dtype, jnp.integer):
            # keep int dtype + truncating semantics, matching psum_tree
            # (true division would silently promote shards to float and
            # make the scatter/gather pair disagree with the allreduce
            # path on int tensors)
            out = jax.lax.div(out, jnp.asarray(n, out.dtype))
        else:
            out = out / n
    return out


def shard_layout(size: int, num_shards: int) -> tuple:
    """THE shard sizing rule for the locality-sharded export path:
    ``(shard_len, pad)`` such that ``shard_len * num_shards ==
    size + pad`` — identical to the padding ``_scatter_leaf`` applies
    inside the compiled program, so the host-side import plan
    (per-shard key sizes, H2D shapes, trim) can never disagree with the
    device-side reduce-scatter layout."""
    shard_len = (size + num_shards - 1) // num_shards
    return shard_len, shard_len * num_shards - size


def scatter_leaf(g: jnp.ndarray, axis: str = DP_AXIS,
                 average: bool = True) -> jnp.ndarray:
    """Public single-leaf ReduceScatter (the locality-sharded export
    tap reduce-scatters individual eligible leaves while the rest of
    the tree rides one psum)."""
    return _scatter_leaf(g, axis, average)


def reduce_scatter_tree(tree: Any, axis: str = DP_AXIS,
                        average: bool = True) -> Any:
    """ReduceScatter every leaf: afterwards each device holds a flat 1/N shard
    of the summed gradient. Pairs with ``all_gather_tree`` and enables
    sharded (ZeRO-1 style) optimizer updates, the TPU upgrade of the
    reference's owns-1/N-of-each-partition layout."""
    return jax.tree.map(lambda g: _scatter_leaf(g, axis, average), tree)


def all_gather_tree(shard_tree: Any, shapes: Any, axis: str = DP_AXIS) -> Any:
    """Inverse of reduce_scatter_tree: gather flat shards and restore original
    leaf shapes (the ICI_BCAST stage)."""

    def gather(shard, orig):
        full = jax.lax.all_gather(shard, axis_name=axis, axis=0, tiled=False)
        size = int(np.prod(orig.shape)) if orig.shape else 1
        return full.reshape(-1)[:size].reshape(orig.shape).astype(orig.dtype)

    return jax.tree.map(gather, shard_tree, shapes)


# ---------------------------------------------------------------------- #
# eager Horovod-style API
# ---------------------------------------------------------------------- #

@functools.lru_cache(maxsize=64)
def _mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh contains devices of more than one process
    (global-mesh multi-process mode, parallel/distributed.py). Cached —
    it's a pure function of the mesh and sits on the eager hot path."""
    if jax.process_count() == 1:
        return False
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _local_stack(tensor, mesh: Mesh, axis: str, stacked: bool, what: str):
    """Assemble a process-spanning global array from this process's local
    contribution: with ``stacked`` the input carries one slice per LOCAL
    device; otherwise the local value is replicated onto the local devices.
    Only the flat all-``axis`` mesh is supported eagerly — structured
    layouts use the in-jit collectives directly."""
    if tuple(mesh.axis_names) != (axis,):
        raise ValueError(
            f"multi-process eager {what} supports only a flat ('{axis}',) "
            f"mesh, got {mesh.axis_names}")
    n_local = sum(1 for d in mesh.devices.flat
                  if d.process_index == jax.process_index())
    xl = np.asarray(tensor)
    if stacked:
        if xl.ndim == 0 or xl.shape[0] != n_local:
            raise ValueError(
                f"stacked {what} expects leading dim {n_local} (local "
                f"devices on '{axis}'), got shape {xl.shape}")
    else:
        xl = np.broadcast_to(xl, (n_local,) + xl.shape)
    from ..parallel.distributed import global_batch
    return global_batch(mesh, np.ascontiguousarray(xl), axis=axis)


@functools.lru_cache(maxsize=512)
def _cached_push_pull(mesh: Mesh, shape, dtype, average: bool, axis: str):
    """Build and cache a jitted shard_map that sums a (n_dev, *shape) stacked
    input over ``axis`` and returns the replicated (*shape) result."""

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=P(axis), out_specs=P())
    def _pp(x):
        # in_specs=P(axis) with leading dim == axis size -> local block (1, *s)
        return psum_tree(x.reshape(x.shape[1:]), axis=axis, average=average)

    return jax.jit(_pp)


@functools.lru_cache(maxsize=512)
def _cached_push_pull_replicated(mesh: Mesh, shape, dtype, average: bool,
                                 axis: str):
    """Unstacked variant: the input is the replicated value every device
    contributes (in_specs=P()), so the eager path never materializes an
    n_devices-times-larger stacked copy just to reshard it."""

    @functools.partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P())
    def _pp(x):
        return psum_tree(x, axis=axis, average=average)

    return jax.jit(_pp)


def push_pull(tensor, name: Optional[str] = None, average: bool = True,
              axis: str = DP_AXIS, priority: Optional[int] = None,
              stacked: bool = False):
    """Horovod-compatible eager push_pull.

    With ``stacked=True``, ``tensor`` carries one slice per mesh device on
    the leading dim (shape ``(n_devices, *s)``) — the single-controller
    analogue of "each worker contributes its own value". With the default
    ``stacked=False``, ``tensor`` (shape ``(*s)``) is the value every device
    contributes. Either way returns the sum (mean when ``average``) of shape
    ``(*s)``, replicated — the contract of the reference's framework-level
    ``byteps.push_pull`` (reference: byteps/torch/__init__.py:139,
    ops.py:157-174). The flag is explicit because shape inference here is a
    silent-corruption hazard (a replicated tensor whose dim 0 happens to
    equal the mesh size).
    """
    state = get_state()
    if not state.initialized:
        raise RuntimeError("byteps_tpu.init() must be called before push_pull")
    mesh = state.mesh
    n = mesh.shape.get(axis, 1)

    replicated = False
    if _mesh_spans_processes(mesh):
        # Global-mesh multi-process mode: this process contributes values
        # for its own devices; the global array is assembled across
        # processes (each worker feeds its minibatch) and the collective
        # rides ICI/DCN via XLA.
        x = _local_stack(tensor, mesh, axis, stacked, "push_pull")
    else:
        x = jnp.asarray(tensor)
        if stacked:
            if x.ndim == 0 or x.shape[0] != n:
                raise ValueError(
                    f"stacked push_pull expects leading dim {n} (mesh "
                    f"'{axis}' size), got shape {x.shape}")
        else:
            # the replicated value feeds a P()-in_specs shard_map
            # directly — no n_devices-times stacked copy is built
            replicated = True

    out_shape = tuple(x.shape) if replicated else tuple(x.shape[1:])
    if int(np.prod(out_shape)) == 0:
        # zero-element tensors carry no data: skip the collectives and
        # the PS tier entirely (init_tensor rejects zero-size
        # declarations, and the sum of nothing is nothing)
        return jnp.zeros(out_shape, x.dtype)

    if name is not None:
        state.registry.init_tensor(
            name, int(np.prod(out_shape)) * x.dtype.itemsize,
            DataType.from_np(x.dtype))
        from ..utils.logging import debug_sample
        # pass the raw array: debug_sample only materializes (np.asarray →
        # device sync + D2H) after its needle check, keeping the hot
        # collective path free of forced transfers when sampling is off
        debug_sample(state.config, name, "INPUT", tensor)
    if replicated:
        fn = _cached_push_pull_replicated(mesh, out_shape, str(x.dtype),
                                          average, axis)
    else:
        fn = _cached_push_pull(mesh, out_shape, str(x.dtype), average, axis)
    out = fn(x)
    state.telemetry.record(out.nbytes * n)

    if state.ps_client is not None:
        # distributed tier: ICI-reduced value round-trips through the DCN
        # PS for cross-worker summation (REDUCE -> PUSH -> PULL ->
        # BROADCAST, docs/architecture.md "General Workflow")
        if name is None:
            raise ValueError(
                "push_pull over the PS requires a tensor name (stable keys "
                "must match across workers; operations.cc:420-427)")
        from ..server.client import ps_round_trip
        host = np.asarray(out).reshape(-1)
        out = jnp.asarray(
            ps_round_trip(state, name, host, average,
                          priority=priority).reshape(out.shape))

    if name is not None:
        from ..utils.logging import debug_sample
        debug_sample(state.config, name, "OUTPUT", out)
    if state.tracer is not None and name is not None:
        state.tracer.instant(name, "push_pull")
    return out


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              axis: str = DP_AXIS, stacked: bool = False):
    """Broadcast the root device's value to all devices.

    ``stacked=True``: ``tensor`` is ``(n_devices, *s)`` per-device values and
    the root's slice wins. ``stacked=False`` (default): ``tensor`` is the
    local value (already replicated under single-controller JAX); the
    collective still runs, asserting device agreement and keeping parity
    with the multi-process path. Implemented the way the reference
    implements broadcast_parameters — zero the non-root contributions, then
    push_pull(sum) (reference: byteps/torch/__init__.py:261-293).
    """
    state = get_state()
    if not state.initialized:
        raise RuntimeError("byteps_tpu.init() must be called before broadcast")
    mesh = state.mesh
    n = mesh.shape.get(axis, 1)
    if _mesh_spans_processes(mesh):
        # same local-stack contract as multi-process push_pull; root_rank
        # indexes the GLOBAL device order on the axis
        x = _local_stack(tensor, mesh, axis, stacked, "broadcast")
        out = _cached_broadcast(mesh, root_rank % n, axis)(x)
    else:
        x = jnp.asarray(tensor)
        if stacked:
            if x.ndim == 0 or x.shape[0] != n:
                raise ValueError(
                    f"stacked broadcast expects leading dim {n} (mesh "
                    f"'{axis}' size), got shape {x.shape}")
            out = _cached_broadcast(mesh, root_rank % n, axis)(x)
        else:
            # replicated input: no n-times stacked copy (see push_pull)
            out = _cached_broadcast_replicated(mesh, root_rank % n, axis)(x)

    if state.ps_client is not None and state.config.num_workers > 1:
        # cross-worker tier: the reference's broadcast IS zero-non-root +
        # push_pull(sum) (torch/__init__.py:261-293). root_rank is global:
        # worker root_rank // n holds the source copy.
        if name is None:
            raise ValueError(
                "broadcast over the PS requires a tensor name")
        from ..server.client import ps_round_trip
        root_worker = root_rank // n
        host = np.asarray(out).reshape(-1)
        if state.config.worker_id != root_worker:
            host = np.zeros_like(host)
        out = jnp.asarray(
            ps_round_trip(state, "bcast/" + name, host,
                          average=False).reshape(out.shape))
    return out


@functools.lru_cache(maxsize=64)
def _cached_broadcast(mesh: Mesh, root_rank: int, axis: str):
    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P())
    def _bcast(v):
        local = v.reshape(v.shape[1:])
        idx = jax.lax.axis_index(axis)
        contrib = jnp.where(idx == root_rank, local, jnp.zeros_like(local))
        return jax.lax.psum(contrib, axis_name=axis)

    return jax.jit(_bcast)


@functools.lru_cache(maxsize=64)
def _cached_broadcast_replicated(mesh: Mesh, root_rank: int, axis: str):
    """Unstacked variant (replicated input, in_specs=P()): the collective
    still runs — asserting device agreement and keeping parity with the
    stacked path — without building an n-times stacked copy first."""

    @functools.partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P())
    def _bcast(v):
        idx = jax.lax.axis_index(axis)
        contrib = jnp.where(idx == root_rank, v, jnp.zeros_like(v))
        return jax.lax.psum(contrib, axis_name=axis)

    return jax.jit(_bcast)
