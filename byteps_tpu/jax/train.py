"""Sharded train-step construction.

The reference's training loop shape — backward, per-tensor push_pull hooks,
optimizer step on the worker (reference: byteps/torch/__init__.py:142-216,
docs/architecture.md "General Workflow") — becomes here a single compiled
function: shard_map over the mesh, batch sharded on ``dp``, gradients
cross-replica-summed by the distributed optimizer, update applied inside the
same program so XLA overlaps the gradient collectives with remaining
backward compute (the pipelining BytePS builds with host threads).

Two flavors:

- ``make_train_step``: replicated params/optimizer state, psum allreduce.
- ``make_zero_train_step``: ReduceScatter gradients, keep optimizer state
  sharded 1/N per device, AllGather updated params — the TPU upgrade of the
  reference's "each GPU owns 1/local_size of every partition" hierarchical
  layout (core_loops.cc:216-268) that also cuts optimizer memory by N.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.push_pull import psum_tree, reduce_scatter_tree, all_gather_tree
from ..parallel.mesh import DP_AXIS


def make_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DP_AXIS,
    grads_transform: Optional[Callable] = None,
    donate: bool = True,
    extra_batch_axes: Tuple[str, ...] = (),
    opt_specs: Any = None,
):
    """Build a jitted SPMD train step.

    ``loss_fn(params, batch) -> scalar`` computed on the local batch shard;
    ``tx`` should be ``byteps_tpu.jax.distributed_optimizer(...)`` so the
    gradient push_pull happens inside its update (or pass a plain optax tx
    plus ``grads_transform=lambda g: psum_tree(g, axis)``).

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``.
    Batch leaves are sharded on their leading dim over ``axis`` (+
    ``extra_batch_axes``, e.g. ("sp",) to also shard sequence).

    ``opt_specs``: PartitionSpec pytree for the optimizer state; REQUIRED
    (via byteps_tpu.jax.init_opt_state) when ``tx`` carries per-replica
    compression state (EF/momentum) — those leaves are device-varying and
    must be declared sharded, not replicated.
    """
    batch_spec = P((axis,) + tuple(extra_batch_axes)) \
        if extra_batch_axes else P(axis)
    if opt_specs is None:
        opt_specs = P()

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        if grads_transform is not None:
            grads = grads_transform(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.pmean(loss, axis)
        return params, opt_state, loss

    smapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), opt_specs, batch_spec),
        out_specs=(P(), opt_specs, P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    jitted = jax.jit(smapped, donate_argnums=donate_argnums)
    return _with_tracer_tick(jitted)


def _with_tracer_tick(jitted):
    """Tick the Chrome-trace step counter per training step (the reference
    counts steps to window tracing between BYTEPS_TRACE_START/END_STEP,
    global.cc:113-124)."""
    import functools as _functools

    from ..core.state import get_state

    @_functools.wraps(jitted)
    def stepper(*args, **kw):
        tracer = get_state().tracer
        if tracer is not None:
            tracer.step()
        return jitted(*args, **kw)

    # keep access to the underlying jitted fn (e.g. for AOT lowering)
    stepper.jitted = jitted
    return stepper


def _zero_state_specs(params, tx: optax.GradientTransformation, mesh: Mesh,
                      axis: str):
    """Opt-state partition specs for the ZeRO layout: array leaves are flat
    1/N shards -> P(axis); scalar leaves (e.g. adam's count) replicate."""
    import numpy as np

    n = mesh.shape[axis]

    def shard_shape(p):
        size = int(np.prod(p.shape)) if p.shape else 1
        padded = size + (-size % n)
        return jax.ShapeDtypeStruct((padded // n,), p.dtype)

    shard_params = jax.tree.map(shard_shape, params)
    opt_shapes = jax.eval_shape(tx.init, shard_params)
    specs = jax.tree.map(lambda s: P() if s.ndim == 0 else P(axis), opt_shapes)
    return specs


def make_zero_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    params_example: Any,
    axis: str = DP_AXIS,
    donate: bool = True,
):
    """ZeRO-1-style step: optimizer state lives sharded (flat 1/N per
    device); gradients ReduceScatter instead of allreduce; params AllGather
    after the shard update. Cuts optimizer memory by N and replaces the
    allreduce with RS+AG, each half the bytes.

    Use ``init_zero_state(params, tx, mesh, axis)`` for the initial optimizer
    state. Params stay replicated between steps. ``params_example`` (a pytree
    of arrays or ShapeDtypeStructs) fixes the optimizer-state structure.
    """
    opt_specs = _zero_state_specs(params_example, tx, mesh, axis)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grad_shards = reduce_scatter_tree(grads, axis=axis, average=True)
        param_shards = reduce_scatter_tree(params, axis=axis, average=True)
        updates, opt_state = tx.update(grad_shards, opt_state, param_shards)
        param_shards = optax.apply_updates(param_shards, updates)
        params = all_gather_tree(param_shards, params, axis=axis)
        loss = jax.lax.pmean(loss, axis)
        return params, opt_state, loss

    smapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), opt_specs, P(axis)),
        out_specs=(P(), opt_specs, P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    return _with_tracer_tick(jax.jit(smapped, donate_argnums=donate_argnums))


_COMP_POOL = None
_rowsparse_warned: set = set()  # names warned about dense fallback


def _comp_pool():
    """Shared tensor-level fan-out pool for compressed push_pull. Must be
    distinct from the client's partition pool (a tensor task blocks on
    partition tasks — sharing one pool could deadlock) and shared across
    step functions so rebuilding a step never accumulates executors."""
    global _COMP_POOL
    if _COMP_POOL is None:
        import concurrent.futures
        _COMP_POOL = concurrent.futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="bps-comp")
    return _COMP_POOL


def _route_rowsparse(name: str, leaf, state, rowsparse_params) -> bool:
    """One routing predicate for BOTH compression tiers: a leaf matching
    ``rowsparse_params`` rides the row-sparse wire only when it is 2D
    and a scheduler is running; mismatches warn once and fall back to
    the tier's dense/compressed path."""
    if not (rowsparse_params and any(s in name for s in rowsparse_params)):
        return False
    if getattr(leaf, "ndim", None) == 2 and state.scheduler is not None:
        return True
    if name not in _rowsparse_warned:
        from ..utils.logging import log
        _rowsparse_warned.add(name)
        log.warning(
            "rowsparse_params matched %r but the gradient is not 2D "
            "(shape %s) or no scheduler is running — using the dense "
            "path", name, getattr(leaf, "shape", None))
    return False


def _device_compressed_round(state, client, comp_state, compression,
                             min_compress_bytes, rowsparse_params, names,
                             leaves, treedef):
    """One gradient round on the device-compressed tier: leaves matching
    ``rowsparse_params`` ride the host row-sparse path (the row payload
    needs the dense host rows anyway); everything else compresses inside
    XLA and crosses device->host wire-sized
    (device_compression.DeviceCompressor)."""
    import numpy as np

    from .device_compression import DeviceCompressor

    if comp_state["client"] is not client or comp_state["device"] is None:
        mcb = min_compress_bytes
        if mcb is None:
            mcb = getattr(state.config, "min_compress_bytes", 0)
        comp_state["device"] = DeviceCompressor(
            client, state.config.num_workers, compression, mcb)
        comp_state["client"] = client
        comp_state["registry"] = None  # host tier rebuilt on demand
    dc = comp_state["device"]

    sparse = {}
    dev_idx = []
    for i, (name, leaf) in enumerate(zip(names, leaves)):
        if _route_rowsparse(name, leaf, state, rowsparse_params):
            sparse[i] = None
        else:
            dev_idx.append(i)
    from .. import _rowsparse_submit
    for i in sparse:
        h = np.asarray(leaves[i]).astype(np.float32, copy=False)
        handle = state.handles.allocate(names[i])
        _rowsparse_submit(state, names[i], h, True, handle)
        sparse[i] = (handle, leaves[i].dtype)
    results = [None] * len(leaves)
    if dev_idx:
        out = dc.push_pull_leaves(state, [names[i] for i in dev_idx],
                                  [leaves[i] for i in dev_idx])
        for i, o in zip(dev_idx, out):
            results[i] = o
    for i, (handle, dt) in sparse.items():
        results[i] = np.asarray(
            state.handles.wait_and_clear(handle.id)).astype(dt, copy=False)
    return treedef.unflatten(results)


def make_ps_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DP_AXIS,
    compression: Optional[dict] = None,
    min_compress_bytes: Optional[int] = None,
    rowsparse_params: Optional[Tuple[str, ...]] = None,
    device_compress: Optional[bool] = None,
):
    """Two-phase train step for the DCN PS path — the reference's actual
    architecture (docs/architecture.md "General Workflow"): the compiled
    program reduces gradients over the local slice (ICI psum == the NCCL
    ReduceScatter tier), gradients exit to host, the PS client push_pulls
    each declared tensor across workers in priority order (the PUSH/PULL
    stages over DCN), and a second compiled program applies the optimizer
    update on the worker (servers only sum).

    ``compression``: string-kwargs dict for the codec registry (e.g.
    ``{"compressor": "onebit", "ef": "vanilla"}``) — gradients then ride
    the wire compressed with the C++ server decompress/sum/recompress
    mirror (reference: BASELINE config 4 path; server.cc:92-118). EF and
    momentum state live worker-side per tensor. ``min_compress_bytes``
    gates small tensors onto the dense path (BYTEPS_MIN_COMPRESS_BYTES).

    ``device_compress`` (default on whenever ``compression`` is set and
    the scheduler is running): run the momentum->EF->codec stack inside
    the compiled step (jax/device_compression.py), so the device->host
    hop carries the wire-sized payload — SURVEY §7's "the D2H moves
    *compressed* bytes" — instead of dense f32 that is then compressed
    in numpy; the pull reply is decompressed back on device. EF state
    lives on device and, like the host path's, resets on
    suspend/resume. Set False to force the host-numpy codec tier.

    ``rowsparse_params``: substrings of gradient names (e.g.
    ``("embed",)``) whose 2D gradients travel row-sparse — only nonzero
    rows on the push wire (bps.push_pull_rowsparse; embedding gradients
    are mostly zero rows). Takes precedence over ``compression`` for the
    matching leaves.

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``;
    reads the PS client + registry from the global state at call time, so
    it composes with suspend/resume.
    """
    import numpy as np

    from ..core.state import get_state

    # registry is keyed to the client that created it: suspend/resume
    # replaces state.ps_client, and a cached registry would then push on a
    # destroyed native handle with a stale worker count
    comp_state = {"registry": None, "client": None, "device": None}

    def local_grads(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = psum_tree(grads, axis=axis, average=True)
        loss = jax.lax.pmean(loss, axis)
        return loss, grads

    grad_fn = jax.jit(jax.shard_map(
        local_grads, mesh=mesh, in_specs=(P(), P(axis)),
        out_specs=(P(), P()), check_vma=False))

    def apply_updates_fn(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    apply_fn = jax.jit(apply_updates_fn, donate_argnums=(0, 1))

    def step(params, opt_state, batch):
        state = get_state()
        client = state.ps_client
        loss, grads = grad_fn(params, batch)
        if client is not None:
            paths, treedef = jax.tree_util.tree_flatten_with_path(grads)
            names, leaves = [], []
            for path, leaf in paths:
                names.append("grad/" + "/".join(
                    str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path))
                leaves.append(leaf)
            use_device = (compression is not None
                          and device_compress is not False
                          and state.scheduler is not None)
            if use_device:
                grads = _device_compressed_round(
                    state, client, comp_state, compression,
                    min_compress_bytes, rowsparse_params, names, leaves,
                    treedef)
                params, opt_state = apply_fn(params, opt_state, grads)
                return params, opt_state, loss
            # host tier below: dense D2H, codecs in numpy.
            # start ALL D2H copies now; each np.asarray below then only
            # waits for ITS leaf, so the transfer of leaf k+1 rides the
            # bus while leaf k is already in PUSH — the reference's
            # per-partition COPYD2H/PUSH overlap (core_loops.cc:378-443)
            # done with device_get futures instead of a D2H stage thread.
            for leaf in leaves:
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            reg = None
            mcb = min_compress_bytes
            if mcb is None:
                mcb = getattr(state.config, "min_compress_bytes", 0)
            if compression is not None:
                if comp_state["client"] is not client:
                    from ..server.compressed import CompressedRegistry
                    comp_state["registry"] = CompressedRegistry(
                        client, state.config.num_workers, compression, mcb)
                    comp_state["client"] = client
                reg = comp_state["registry"]
            # one submit-as-ready loop for all three transports: dense or
            # compressed partitions enter the priority-scheduled pipeline
            # (compressed ones through COMPRESS/DECOMPRESS stages,
            # operations.cc:199-204); the no-scheduler fallbacks overlap
            # on a pool / run blocking.
            import byteps_tpu as bps

            # Persistent host staging (core/arena.py, the reference's
            # cpubuff discipline): result slots and fused-bucket concat
            # slots check out of the arena instead of np.empty per step;
            # every lease is released only after the imports below
            # complete (or abandoned on error — correctness never
            # depends on a slot surviving).
            arena = state.arena
            leases: list = []

            def checkout(key, nbytes, dtype):
                lease = arena.checkout(key, nbytes)
                leases.append(lease)
                return lease.array(dtype)

            def submit_sparse(name, h2d, out_dtype):
                from .. import _rowsparse_submit
                handle = state.handles.allocate(name)
                obuf = checkout(f"{name}:out", h2d.size * 4, np.float32)
                _rowsparse_submit(state, name,
                                  h2d.astype(np.float32, copy=False),
                                  True, handle, out=obuf)
                return (lambda: state.handles.wait_and_clear(
                    handle.id).astype(out_dtype, copy=False)), handle

            def submit(name, flat):
                """Returns (finish, notifier): ``finish()`` yields the
                reduced array (non-blocking once ``notifier`` — a Handle
                or Future with add_done_callback, or None for an already
                complete result — has fired)."""
                if reg is not None:
                    flat = flat.astype(np.float32, copy=False)
                    if state.scheduler is not None:
                        obuf = checkout(f"{name}:out", flat.nbytes,
                                        np.float32)
                        hd = reg.push_pull_async(state, name, flat, True,
                                                 out=obuf)
                        return (lambda: bps.synchronize(hd),
                                state.handles.get(hd))
                    fut = _comp_pool().submit(
                        reg.push_pull, state, name, flat, True)
                    return fut.result, fut
                if state.scheduler is not None:
                    obuf = checkout(f"{name}:out", flat.nbytes, flat.dtype)
                    hd = bps.push_pull_async(flat, name, average=True,
                                             out=obuf)
                    return (lambda: bps.synchronize(hd),
                            state.handles.get(hd))
                from ..server.client import ps_round_trip
                obuf = checkout(f"{name}:out", flat.nbytes, flat.dtype)
                res = ps_round_trip(state, name, flat, average=True,
                                    out=obuf)
                return (lambda: res), None

            # Bucket fusion (BYTEPS_FUSION_BYTES; the group-push cure):
            # per-key cost (scheduler admission, handle, two syscall
            # round-trips, server queue hop) is flat, so sub-threshold
            # leaves — biases, norms, small projections — fuse into one
            # concatenated key per dtype run and are sliced back after
            # the round. The bucket name is a content-stable digest of
            # (member names, sizes): every worker flattens the same tree
            # in the same order, so all workers aggregate the same
            # bucket; a changed model topology changes the digest and
            # cleanly declares a new key. Codec granularity for a fused
            # bucket is the bucket (matching the reference, where the
            # codec unit is the partition, not the layer).
            #
            # Interaction rules:
            # - bucket cap <= partition_bytes: a bucket must stay ONE
            #   key, or the partitioner re-splits it and re-adds the
            #   round trip fusion exists to remove;
            # - with compression on and min_compress_bytes > 0, only
            #   sub-mcb leaves fuse and the bucket stays < mcb, so
            #   tensors the gate kept full-precision (biases, norms)
            #   are NOT quantized via the fused key (mcb == 0 means the
            #   user asked for everything compressed — buckets too).
            fusion = getattr(state.config, "fusion_bytes", 0)
            bucket_cap = min(4 << 20,
                             getattr(state.config, "partition_bytes",
                                     4 << 20))
            if reg is not None and mcb > 0:
                fusion = min(fusion, mcb)
                bucket_cap = min(bucket_cap, mcb - 1)
            waiters = []   # (slot_or_slots, finisher, notifier)
            bucket: list = []  # [(slot, name, flat_f-contig host array)]
            bucket_bytes = 0

            def flush_bucket():
                nonlocal bucket, bucket_bytes
                if not bucket:
                    return
                if len(bucket) == 1:
                    slot, name, h = bucket[0]
                    waiters.append((slot, *submit(name, h.reshape(-1))))
                else:
                    import hashlib
                    digest = hashlib.sha1(";".join(
                        f"{n}:{h.size}" for _, n, h in bucket)
                        .encode()).hexdigest()[:12]
                    # concatenate into the bucket's PERSISTENT arena
                    # slot (np.concatenate would allocate the fused
                    # buffer fresh every step). With compression on the
                    # wire is f32, so fill as f32 and skip the astype
                    # copy submit() would otherwise make.
                    bdt = np.dtype(np.float32) if reg is not None \
                        else bucket[0][2].dtype
                    total = sum(h.size for _, _, h in bucket)
                    fused = checkout(f"fused/{digest}:in",
                                     total * bdt.itemsize, bdt)
                    off = 0
                    for _, _, h in bucket:
                        fused[off:off + h.size] = h.reshape(-1)
                        off += h.size
                    slots = [s for s, _, _ in bucket]
                    sizes = [h.size for _, _, h in bucket]
                    w, notifier = submit(f"fused/{digest}", fused)

                    def finish(w=w, sizes=sizes):
                        out = w()
                        outs = np.split(out, np.cumsum(sizes)[:-1])
                        return outs

                    waiters.append((slots, finish, notifier))
                bucket, bucket_bytes = [], 0

            imported: list = [None] * len(names)
            try:
                for i, (name, leaf) in enumerate(zip(names, leaves)):
                    h = np.asarray(leaf)  # ready-or-wait for THIS leaf
                    if _route_rowsparse(name, h, state, rowsparse_params):
                        flush_bucket()
                        # non-f32 grads upcast for the wire, cast back
                        waiters.append((i, *submit_sparse(name, h,
                                                          h.dtype)))
                    elif h.nbytes < fusion:
                        if bucket and (bucket[0][2].dtype != h.dtype
                                       or bucket_bytes + h.nbytes
                                       > bucket_cap):
                            flush_bucket()
                        bucket.append((i, name, h))
                        bucket_bytes += h.nbytes
                    else:
                        flush_bucket()
                        waiters.append((i, *submit(name, h.reshape(-1))))
                flush_bucket()
                shapes = [np.shape(leaf) for leaf in leaves]
                # Completion-ordered IMPORT drain: instead of draining
                # every waiter in submission order and only then letting
                # apply_fn upload the whole tree, issue the async H2D
                # device_put for each leaf THE MOMENT its pull lands —
                # XLA overlaps the import of tensor k with the DCN PULL
                # of tensor k+1, the mirror of the copy_to_host_async
                # EXPORT overlap above (reference: COPYH2D as its own
                # pipeline stage, core_loops.cc:620-648).
                import queue as _queue

                ready: "_queue.Queue" = _queue.Queue()
                for wi, (_, _, notifier) in enumerate(waiters):
                    if notifier is None:
                        ready.put(wi)
                    else:
                        notifier.add_done_callback(
                            lambda *_a, wi=wi: ready.put(wi))
                for _ in range(len(waiters)):
                    slot, finish, _ = waiters[ready.get()]
                    if isinstance(slot, list):
                        for s, piece in zip(slot, finish()):
                            imported[s] = jax.device_put(
                                piece.reshape(shapes[s]))
                    else:
                        imported[slot] = jax.device_put(
                            finish().reshape(shapes[slot]))
                # wait for the H2D transfers only (apply_fn needs them
                # anyway) so the arena slots are provably idle before
                # they are released for the next round
                jax.block_until_ready([x for x in imported
                                       if x is not None])
            except BaseException:
                # a failed round (submission OR drain) may leave pulls
                # mid-flight into these slots: abandon (drop from the
                # table) instead of recycling them under a late writer.
                # The not-yet-drained sibling handles must not pin their
                # gradient-sized result buffers in the handle table for
                # the life of the process either (the same leak class
                # the TF graph tier discards against).
                for lease in leases:
                    lease.abandon()
                for _, _, notifier in waiters:
                    if hasattr(notifier, "id"):
                        state.handles.discard(notifier.id)
                raise
            for lease in leases:
                lease.release()
            grads = treedef.unflatten(imported)
        params, opt_state = apply_fn(params, opt_state, grads)
        return params, opt_state, loss

    # tick the Chrome-trace step counter: the PUSH/PULL/COMPRESS spans the
    # scheduler records are windowed by step (BYTEPS_TRACE_START/END_STEP)
    return _with_tracer_tick(step)


def make_async_ps_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DP_AXIS,
):
    """Asynchronous data-parallel train step (the reference's
    BYTEPS_ENABLE_ASYNC mode, torch/__init__.py:188-216, server.cc:315-319):
    each worker updates its params locally, pushes the weight DELTA to the
    PS — which folds it into the authoritative weights with no aggregation
    barrier — and pulls the current weights back. Workers never wait for
    each other; staleness is the accepted tradeoff.

    The server must run with BYTEPS_ENABLE_ASYNC=1. On the first step each
    worker init-pushes its initial weights (first arrival seeds the
    authoritative copy — start workers from identical or broadcast params).

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``.
    Without a PS configured, degrades to plain local (single-worker) SGD.
    """
    import numpy as np

    from ..core.state import get_state
    from ..server.client import get_or_init_ctx

    def local_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = psum_tree(grads, axis=axis, average=True)
        updates, opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        delta = jax.tree.map(jnp.subtract, new_params, params)
        loss = jax.lax.pmean(loss, axis)
        return loss, delta, opt_state

    local_fn = jax.jit(jax.shard_map(
        local_step, mesh=mesh, in_specs=(P(), P(), P(axis)),
        out_specs=(P(), P(), P()), check_vma=False))

    # seeding is keyed to the client that received it: suspend/resume
    # replaces state.ps_client with fresh (unseeded) servers, and a stale
    # `seeded` set would skip init_weights — the pull would then return
    # bare deltas and silently destroy the model (the sync paths carry
    # the same client-keyed guard on their compression registry)
    seed_state = {"client": None, "names": set()}

    def step(params, opt_state, batch):
        state = get_state()
        client = state.ps_client
        loss, delta, opt_state = local_fn(params, opt_state, batch)
        if client is None:
            params = jax.tree.map(jnp.add, params, delta)
            return params, opt_state, loss
        if seed_state["client"] is not client:
            seed_state["client"] = client
            seed_state["names"] = set()
        seeded = seed_state["names"]
        paths, treedef = jax.tree_util.tree_flatten_with_path(params)
        deltas = jax.tree.leaves(delta)
        leaves = []
        for (path, leaf), d in zip(paths, deltas):
            name = "asyncw/" + "/".join(
                str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
            host_w = np.asarray(leaf).reshape(-1)
            ctx = get_or_init_ctx(state, name, host_w)
            if name not in seeded:
                client.init_weights(ctx, host_w)
                seeded.add(name)
            leaves.append((ctx, leaf, np.asarray(d).reshape(-1)))

        # overlap the per-leaf round trips (they'd otherwise serialize the
        # step on sum-of-RTTs) on the shared tensor-level pool — NOT
        # client._pool (these calls block on client-pool futures and
        # would deadlock it), and not a per-step executor (spawn/join of
        # 16 threads every step on the hot path)
        def one(item):
            ctx, leaf, d = item
            out = client.push_delta_pull_weights(ctx, d)
            state.telemetry.record(out.nbytes * 2)
            return jnp.asarray(out.reshape(leaf.shape))

        pulled = list(_comp_pool().map(one, leaves))
        params = treedef.unflatten(pulled)
        return params, opt_state, loss

    return _with_tracer_tick(step)


def init_zero_state(params, tx: optax.GradientTransformation, mesh: Mesh,
                    axis: str = DP_AXIS):
    """Initialize optimizer state over flat 1/N param shards (matches
    make_zero_train_step's layout)."""
    opt_specs = _zero_state_specs(params, tx, mesh, axis)

    def init(params_):
        shards = reduce_scatter_tree(params_, axis=axis, average=True)
        return tx.init(shards)

    return jax.jit(jax.shard_map(
        init, mesh=mesh, in_specs=(P(),), out_specs=opt_specs,
        check_vma=False))(params)
