"""Sharded train-step construction.

The reference's training loop shape — backward, per-tensor push_pull hooks,
optimizer step on the worker (reference: byteps/torch/__init__.py:142-216,
docs/architecture.md "General Workflow") — becomes here a single compiled
function: shard_map over the mesh, batch sharded on ``dp``, gradients
cross-replica-summed by the distributed optimizer, update applied inside the
same program so XLA overlaps the gradient collectives with remaining
backward compute (the pipelining BytePS builds with host threads).

Two flavors:

- ``make_train_step``: replicated params/optimizer state, psum allreduce.
- ``make_zero_train_step``: ReduceScatter gradients, keep optimizer state
  sharded 1/N per device, AllGather updated params — the TPU upgrade of the
  reference's "each GPU owns 1/local_size of every partition" hierarchical
  layout (core_loops.cc:216-268) that also cuts optimizer memory by N.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.push_pull import psum_tree, reduce_scatter_tree, all_gather_tree
from ..parallel.mesh import DP_AXIS


def make_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DP_AXIS,
    grads_transform: Optional[Callable] = None,
    donate: bool = True,
    extra_batch_axes: Tuple[str, ...] = (),
    opt_specs: Any = None,
):
    """Build a jitted SPMD train step.

    ``loss_fn(params, batch) -> scalar`` computed on the local batch shard;
    ``tx`` should be ``byteps_tpu.jax.distributed_optimizer(...)`` so the
    gradient push_pull happens inside its update (or pass a plain optax tx
    plus ``grads_transform=lambda g: psum_tree(g, axis)``).

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``.
    Batch leaves are sharded on their leading dim over ``axis`` (+
    ``extra_batch_axes``, e.g. ("sp",) to also shard sequence).

    ``opt_specs``: PartitionSpec pytree for the optimizer state; REQUIRED
    (via byteps_tpu.jax.init_opt_state) when ``tx`` carries per-replica
    compression state (EF/momentum) — those leaves are device-varying and
    must be declared sharded, not replicated.
    """
    batch_spec = P((axis,) + tuple(extra_batch_axes)) \
        if extra_batch_axes else P(axis)
    if opt_specs is None:
        opt_specs = P()

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        if grads_transform is not None:
            grads = grads_transform(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.pmean(loss, axis)
        return params, opt_state, loss

    smapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), opt_specs, batch_spec),
        out_specs=(P(), opt_specs, P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    jitted = jax.jit(smapped, donate_argnums=donate_argnums)
    return _with_tracer_tick(jitted)


def _with_tracer_tick(jitted):
    """Tick the Chrome-trace step counter per training step (the reference
    counts steps to window tracing between BYTEPS_TRACE_START/END_STEP,
    global.cc:113-124)."""
    import functools as _functools

    from ..core.state import get_state

    @_functools.wraps(jitted)
    def stepper(*args, **kw):
        tracer = get_state().tracer
        if tracer is not None:
            tracer.step()
        return jitted(*args, **kw)

    # keep access to the underlying jitted fn (e.g. for AOT lowering)
    stepper.jitted = jitted
    return stepper


def _zero_state_specs(params, tx: optax.GradientTransformation, mesh: Mesh,
                      axis: str):
    """Opt-state partition specs for the ZeRO layout: array leaves are flat
    1/N shards -> P(axis); scalar leaves (e.g. adam's count) replicate."""
    import numpy as np

    n = mesh.shape[axis]

    def shard_shape(p):
        size = int(np.prod(p.shape)) if p.shape else 1
        padded = size + (-size % n)
        return jax.ShapeDtypeStruct((padded // n,), p.dtype)

    shard_params = jax.tree.map(shard_shape, params)
    opt_shapes = jax.eval_shape(tx.init, shard_params)
    specs = jax.tree.map(lambda s: P() if s.ndim == 0 else P(axis), opt_shapes)
    return specs


def make_zero_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    params_example: Any,
    axis: str = DP_AXIS,
    donate: bool = True,
):
    """ZeRO-1-style step: optimizer state lives sharded (flat 1/N per
    device); gradients ReduceScatter instead of allreduce; params AllGather
    after the shard update. Cuts optimizer memory by N and replaces the
    allreduce with RS+AG, each half the bytes.

    Use ``init_zero_state(params, tx, mesh, axis)`` for the initial optimizer
    state. Params stay replicated between steps. ``params_example`` (a pytree
    of arrays or ShapeDtypeStructs) fixes the optimizer-state structure.
    """
    opt_specs = _zero_state_specs(params_example, tx, mesh, axis)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grad_shards = reduce_scatter_tree(grads, axis=axis, average=True)
        param_shards = reduce_scatter_tree(params, axis=axis, average=True)
        updates, opt_state = tx.update(grad_shards, opt_state, param_shards)
        param_shards = optax.apply_updates(param_shards, updates)
        params = all_gather_tree(param_shards, params, axis=axis)
        loss = jax.lax.pmean(loss, axis)
        return params, opt_state, loss

    smapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), opt_specs, P(axis)),
        out_specs=(P(), opt_specs, P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    return _with_tracer_tick(jax.jit(smapped, donate_argnums=donate_argnums))


_COMP_POOL = None
_EXPORT_POOL = None
_rowsparse_warned: set = set()  # names warned about dense fallback
_stream_build_warned: list = []  # once-only streamed-export build warning
_chaos_nan_fired: set = set()   # BYTEPS_CHAOS_NAN_LEAF specs consumed


def _chaos_nan_poison(spec: str, name: str, flat, step_no: int):
    """``BYTEPS_CHAOS_NAN_LEAF="<substr>[@<step>]"``: poison the first
    matching leaf's push with one NaN at/after ``<step>`` (default 3),
    ONCE per process per spec value — the chaos harness for the
    training-health plane's detect → flight-event → guard causality
    (core/health.py, tests/test_health.py). Returns the payload to
    push (a poisoned copy, or ``flat`` untouched)."""
    sub, _, at = spec.partition("@")
    try:
        at_step = int(at) if at else 3
    except ValueError:
        at_step = 3
    if spec in _chaos_nan_fired or step_no < at_step \
            or not sub or sub not in name:
        return flat
    _chaos_nan_fired.add(spec)
    poisoned = np.array(flat, copy=True)
    poisoned.reshape(-1)[0] = np.nan
    from ..core import flight
    flight.record("chaos_nan_injected",
                  detail=f"{name} step={step_no} spec={spec}")
    from ..utils.logging import log
    log.warning("CHAOS: injected NaN into %r push at step %d "
                "(BYTEPS_CHAOS_NAN_LEAF=%s)", name, step_no, spec)
    return poisoned


def _export_pool():
    """The stream-export ROUTER worker. The io_callback tap itself only
    enqueues here: a callback arg is a lazy jax.Array whose
    materialization needs the very executor running the tapped program
    — touching it on the callback (= device) thread self-deadlocks the
    step at the next collective. This thread materializes and submits
    whole-leaf exports OFF the device threads (a single worker also
    means whole-leaf ingests run in fire order, so production-order
    priority assignment is measured from the real schedule); per-device
    SHARD fires (BYTEPS_LOCAL_SHARD_EXPORT) are only routed here — the
    router resolves the tiny step/device scalars and hands the heavy
    shard materialization to that device's own worker
    (``_shard_export_pool``), so the 1/N shards of different devices
    materialize and submit in parallel."""
    global _EXPORT_POOL
    if _EXPORT_POOL is None:
        import concurrent.futures
        _EXPORT_POOL = concurrent.futures.ThreadPoolExecutor(
            1, thread_name_prefix="bps-export")
    return _EXPORT_POOL


# per-LOCAL-DEVICE shard-export workers (BYTEPS_LOCAL_SHARD_EXPORT):
# device k's reduce-scatter shard is materialized and submitted by
# worker k — one thread per device keeps each device's fires in order
# (the per-shard analogue of the single router's FIFO guarantee) while
# devices proceed independently, parallelizing the D2H export across
# the local slice exactly as BytePS's per-GPU copy threads do
_SHARD_POOLS: Dict[int, Any] = {}
_SHARD_INGESTS: Dict[int, int] = {}  # per-device ingest totals (gauges)


def _shard_export_pool(dev: int):
    pool = _SHARD_POOLS.get(dev)
    if pool is None:
        import concurrent.futures
        pool = _SHARD_POOLS[dev] = concurrent.futures.ThreadPoolExecutor(
            1, thread_name_prefix=f"bps-export-d{dev}")
    return pool


_RELEASE_POOL = None


def _release_pool():
    """Deferred arena-release worker, deliberately SEPARATE from the
    export worker: its tasks block on import readiness, and queueing
    them on the export FIFO would stall the next round's streamed
    ingests (and the error path's quiesce sentinel) behind the previous
    round's import tail."""
    global _RELEASE_POOL
    if _RELEASE_POOL is None:
        import concurrent.futures
        _RELEASE_POOL = concurrent.futures.ThreadPoolExecutor(
            1, thread_name_prefix="bps-release")
    return _RELEASE_POOL


def _disable_stream(stream_state: dict, msg: str, *args) -> None:
    """Latch the streamed-export fallback for this step closure and warn
    once per process — shared by the build-failure, dispatch-failure and
    taps-never-fired paths so the latch semantics cannot drift."""
    stream_state["disabled"] = True
    stream_state["fn"] = None
    if not _stream_build_warned:
        from ..utils.logging import log
        _stream_build_warned.append(True)
        log.warning(msg, *args)


class _StreamRound:
    """One PS train step's streamed-export state (BYTEPS_STREAM_EXPORT).

    The io_callback taps planted on each eligible gradient leaf inside
    the compiled backward fire while XLA is still producing later
    gradients; each fire is enqueued (never executed — see
    ``_export_pool``) to the export worker, whose ingest:

    - drops stale fires from an earlier round via the step tag threaded
      through the program, and dedups (shard_map fires the tap once per
      mesh device — the post-psum value is identical on every device,
      so the first fire wins);
    - materializes the payload as a host view whose base keeps the
      buffer alive through the asynchronous PUSH stage (no staging
      copy), with the round's result slot leased from the arena under
      the export tag;
    - submits it straight into the PipelineScheduler at
      production-order priority (scheduler.production_priority), so
      "last layer first" is measured, not assumed;
    - publishes the waiter for the step's completion-ordered drain.

    The main thread ``claim``s each eligible leaf: normally that just
    collects the ingest's waiter; if it hasn't fired within the
    timeout (callbacks broken at runtime), the leaf is claimed for the
    post-jit fallback loop and a late ingest is ignored — double
    submit is impossible by construction.
    """

    def __init__(self, tag: int, names, submit_streamed, mark_first_push,
                 shard_plan: Optional[dict] = None, submit_shard=None):
        self.tag = tag
        self._names = names
        self._submit = submit_streamed  # (name, flat) -> (finish, notifier)
        self._submit_shard = submit_shard  # (i, dev, flat) -> waiter
        self._mark = mark_first_push
        # leaf index -> num shards expected (BYTEPS_LOCAL_SHARD_EXPORT);
        # a planned leaf fires once per local device with ITS shard
        self._shard_plan: dict = shard_plan or {}
        self._mu = threading.Lock()
        self._events: Dict[int, threading.Event] = {}
        self._waiters: Dict[int, tuple] = {}
        self._shard_waiters: Dict[int, dict] = {}
        self._shard_left: Dict[int, int] = {}
        self._shard_started: set = set()
        self._errors: Dict[int, BaseException] = {}
        self._claimed: set = set()
        self._done: set = set()   # whole leaves done + (i, dev) shard fires
        self.streamed = 0
        self.shard_leaves = 0  # leaves exported as per-device shards
        self.broken = False  # a final claim timed out: callbacks dead
        self.dead = False    # cancelled: late ingests must no-op

    def expect(self, i: int) -> None:
        self._events[i] = threading.Event()
        n = self._shard_plan.get(i)
        if n is not None:
            self._shard_left[i] = n
            self._shard_waiters[i] = {}

    def on_fire(self, i: int, step_no: int, dev: int, arr) -> None:
        """One tap fire — runs on the export ROUTER; must never raise.
        Whole leaves dedup per leaf (every device fires the identical
        post-psum value; first wins) and materialize inline. Shard
        leaves dedup per (leaf, device) — every device's fire carries a
        DIFFERENT shard — and hand the materialization to that device's
        own worker so the shards export in parallel."""
        if self.dead or step_no != self.tag:
            return  # cancelled round / stale fire from an earlier round
        ev = self._events.get(i)
        if ev is None:
            return
        if i in self._shard_plan:
            with self._mu:
                if (i, dev) in self._done or i in self._claimed:
                    return
                self._done.add((i, dev))
                self._shard_started.add(i)
            _shard_export_pool(dev).submit(self._ingest_shard, i, dev, arr)
            return
        with self._mu:
            if i in self._done or i in self._claimed:
                return
            self._done.add(i)
        try:
            host = np.asarray(arr)  # materialize off the device threads
            if self.dead:  # cancelled while materializing: no submit
                return
            self._mark()
            w = self._submit(self._names[i], host.reshape(-1))
            with self._mu:
                self._waiters[i] = w
            self.streamed += 1
        except BaseException as e:  # noqa: BLE001 - surfaced via claim()
            self._errors[i] = e
        finally:
            ev.set()

    def _ingest_shard(self, i: int, dev: int, arr) -> None:
        """Device ``dev``'s shard of leaf ``i`` — runs on that device's
        export worker; free to block on XLA, must never raise. The
        leaf's event fires when its LAST shard submission lands, so
        ``claim`` sees either the complete per-shard waiter set or an
        error."""
        ev = self._events.get(i)
        try:
            host = np.asarray(arr)  # materialize this device's shard
            if self.dead:  # cancelled while materializing: no submit
                return
            self._mark()
            w = self._submit_shard(i, dev, host.reshape(-1))
            with self._mu:
                self._shard_waiters[i][dev] = w
                self._shard_left[i] -= 1
                fire = self._shard_left[i] == 0
                if fire:
                    # counters mutate under the lock: final shards of
                    # two leaves can complete concurrently on different
                    # per-device workers, and an unlocked += loses
                    # increments the export telemetry (and the shard
                    # A/B proof) reads
                    self.streamed += 1
                    self.shard_leaves += 1
            if fire:
                ev.set()
        except BaseException as e:  # noqa: BLE001 - surfaced via claim()
            self._errors[i] = e
            if ev is not None:
                ev.set()

    def cancel(self) -> None:
        """Error-path quiesce: mark the round dead (any ingest that
        starts from now no-ops) and drain the export workers — the
        router FIRST (it is the only dispatcher into the per-device
        shard pools, so once its sentinel runs no new shard ingests can
        appear), then every per-device pool — so an ingest already in
        flight, which may be checking out an arena lease and allocating
        a handle, finishes BEFORE the caller's abandon/discard cleanup
        runs. Without this, a late submit after cleanup leaks a
        permanently-busy slot and a gradient-sized handle entry (and,
        on the dispatch-fallback path, hands a stale-pull-targeted
        lease to the live round)."""
        self.dead = True
        pools = [_export_pool()]
        pools.extend(_shard_export_pool(d) for d in sorted(_SHARD_POOLS))
        for pool in pools:
            try:
                pool.submit(lambda: None).result(timeout=120)
            except Exception:  # noqa: BLE001 - quiesce is best-effort
                from ..utils.logging import log
                log.warning(
                    "stream-export worker did not quiesce in time; "
                    "a late ingest may leak one staging slot")

    def claim(self, i: int, timeout: float, final: bool):
        """Collect leaf ``i``'s waiter — a ``(finish, notifier)`` tuple
        for whole leaves, ``("shards", [(dev, waiter), ...])`` for
        shard-planned leaves — or None when the ingest hasn't fired
        within ``timeout``. ``final=False`` just peeks (the loop then
        blocks on the leaf itself, surfacing a compute error promptly
        instead of stalling here); ``final=True`` claims the leaf for
        the synchronous fallback on timeout — a late ingest is then
        ignored — and latches ``broken`` so the round's remaining
        leaves skip straight to the fallback. A shard leaf whose round
        PARTIALLY started is never claimed for fallback: some of its
        shard keys are already on the wire, and a whole-leaf resubmit
        would desynchronize this worker's key set from its peers' — the
        claim blocks for the in-flight submissions instead."""
        if self.broken:
            timeout = 0.0
        ev = self._events[i]
        if not ev.wait(timeout):
            if not final:
                return None
            with self._mu:
                started = (i in self._done
                           or i in self._shard_started)
                if not started:
                    self._claimed.add(i)
                    self.broken = True
                    return None
            ev.wait()  # fire won the race; submission completes shortly
        err = self._errors.get(i)
        if err is not None:
            raise err
        if i in self._shard_plan:
            with self._mu:
                return ("shards",
                        sorted(self._shard_waiters[i].items()))
        return self._waiters[i]

    def any_submitted(self) -> bool:
        """True when ANY submission reached the scheduler — including a
        PARTIAL shard round (some of a leaf's shard keys on the wire,
        the leaf not yet counted in ``streamed``). Read after
        ``cancel()`` (the quiesce guarantees no ingest is mid-submit):
        the dispatch-failure handler must not retry the round when
        anything was pushed, or the resubmitted keys would double-push
        and positionally shift every later aggregation."""
        with self._mu:
            return bool(self._waiters) or any(
                ws for ws in self._shard_waiters.values())

    def handles(self):
        """Handles of every streamed submission, whole-leaf and
        per-shard alike (error-path discard)."""
        with self._mu:
            hs = [n for _, n in self._waiters.values()
                  if hasattr(n, "id")]
            for ws in self._shard_waiters.values():
                hs.extend(n for _, n in ws.values() if hasattr(n, "id"))
            return hs


def _comp_pool():
    """Shared tensor-level fan-out pool for compressed push_pull. Must be
    distinct from the client's partition pool (a tensor task blocks on
    partition tasks — sharing one pool could deadlock) and shared across
    step functions so rebuilding a step never accumulates executors."""
    global _COMP_POOL
    if _COMP_POOL is None:
        import concurrent.futures
        _COMP_POOL = concurrent.futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="bps-comp")
    return _COMP_POOL


def _route_rowsparse(name: str, leaf, state, rowsparse_params) -> bool:
    """One routing predicate for BOTH compression tiers: a leaf matching
    ``rowsparse_params`` rides the row-sparse wire only when it is 2D
    and a scheduler is running; mismatches warn once and fall back to
    the tier's dense/compressed path."""
    if not (rowsparse_params and any(s in name for s in rowsparse_params)):
        return False
    if getattr(leaf, "ndim", None) == 2 and state.scheduler is not None:
        return True
    if name not in _rowsparse_warned:
        from ..utils.logging import log
        _rowsparse_warned.add(name)
        log.warning(
            "rowsparse_params matched %r but the gradient is not 2D "
            "(shape %s) or no scheduler is running — using the dense "
            "path", name, getattr(leaf, "shape", None))
    return False


def _device_compressed_round(state, client, comp_state, compression,
                             min_compress_bytes, rowsparse_params, names,
                             leaves, treedef):
    """One gradient round on the device-compressed tier: leaves matching
    ``rowsparse_params`` ride the host row-sparse path (the row payload
    needs the dense host rows anyway); everything else compresses inside
    XLA and crosses device->host wire-sized
    (device_compression.DeviceCompressor)."""
    import numpy as np

    from .device_compression import DeviceCompressor

    if comp_state["client"] is not client or comp_state["device"] is None:
        mcb = min_compress_bytes
        if mcb is None:
            mcb = getattr(state.config, "min_compress_bytes", 0)
        comp_state["device"] = DeviceCompressor(
            client, state.config.num_workers, compression, mcb)
        comp_state["client"] = client
        comp_state["registry"] = None  # host tier rebuilt on demand
    dc = comp_state["device"]

    sparse = {}
    dev_idx = []
    for i, (name, leaf) in enumerate(zip(names, leaves)):
        if _route_rowsparse(name, leaf, state, rowsparse_params):
            sparse[i] = None
        else:
            dev_idx.append(i)
    from .. import _rowsparse_submit
    for i in sparse:
        h = np.asarray(leaves[i]).astype(np.float32, copy=False)
        handle = state.handles.allocate(names[i])
        _rowsparse_submit(state, names[i], h, True, handle)
        sparse[i] = (handle, leaves[i].dtype)
    results = [None] * len(leaves)
    if dev_idx:
        out = dc.push_pull_leaves(state, [names[i] for i in dev_idx],
                                  [leaves[i] for i in dev_idx])
        for i, o in zip(dev_idx, out):
            results[i] = o
    for i, (handle, dt) in sparse.items():
        results[i] = np.asarray(
            state.handles.wait_and_clear(handle.id)).astype(dt, copy=False)
    return treedef.unflatten(results)


def make_ps_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DP_AXIS,
    compression: Optional[dict] = None,
    min_compress_bytes: Optional[int] = None,
    rowsparse_params: Optional[Tuple[str, ...]] = None,
    device_compress: Optional[bool] = None,
    stream_export: Optional[bool] = None,
    sharded_apply: Optional[bool] = None,
    local_shard_export: Optional[bool] = None,
):
    """Three-stage COMPUTE → PUSH → UPDATE train step for the DCN PS
    path — the reference's actual architecture (docs/architecture.md
    "General Workflow") with BOTH of its pipeline overlaps: the compiled
    program reduces gradients over the local slice (ICI psum == the NCCL
    ReduceScatter tier); gradients exit to host AS XLA PRODUCES THEM
    (streamed export: the last layers enter PUSH while earlier layers
    are still in backprop); the PS client push_pulls each declared
    tensor across workers in priority order (the PUSH/PULL stages over
    DCN); and the optimizer update is applied per leaf from the
    completion-ordered drain, so UPDATE(k) overlaps PULL(k+1) (servers
    only sum — the update stays on the worker).

    ``stream_export`` (BYTEPS_STREAM_EXPORT, default on when a scheduler
    is running): tap each eligible gradient leaf inside the compiled
    backward with jax.experimental.io_callback and hand it straight to
    the scheduler — time-to-first-push drops from "after the whole
    backward" to "after the first gradient". Each key's priority is
    pinned from its measured first-export ordinal
    (scheduler.production_priority): production order, not flatten
    order, decides service order. Leaves that are bucket-fused
    (sub-BYTEPS_FUSION_BYTES), rowsparse-routed or device-compressed,
    and builds where callbacks are unavailable, fall back cleanly to
    the post-jit copy_to_host_async loop — numerics identical.

    ``sharded_apply`` (BYTEPS_SHARDED_APPLY, default on): split the
    monolithic apply jit into per-leaf donated partial updates
    (jax.optim.make_sharded_apply) issued the moment each pull lands.
    Transforms that are not per-leaf separable (global-norm clipping)
    are detected at build time and keep the fused apply; the fused path
    is also the arena-release barrier owner, so with sharding on the
    lease release defers to the next step's start instead of a
    block_until_ready at the end of this one. Failure contract: per-leaf
    updates donate INCREMENTALLY during the drain, so a PS error
    mid-round leaves params/opt_state partially invalidated on backends
    that honor donation — treat a raised step like the donated fused
    apply's mid-apply failure and restart from a checkpoint rather than
    retrying with the same trees.

    ``local_shard_export`` (BYTEPS_LOCAL_SHARD_EXPORT, default on;
    requires streaming): the hierarchical exchange —
    reduce-scatter → push shard → update shard → all-gather. Eligible
    leaves are reduce-SCATTERED instead of psum'd, so each local
    device taps and exports only its own flat 1/local_size shard
    (per-device export workers parallelize the D2H); each shard rides
    its own PS key, spread across servers by the registry's
    load-balanced assignment; the completion-ordered drain imports
    shard k back into the device that owns it (1/local_size H2D per
    device instead of the full aggregated leaf to every device), runs
    the optimizer update on the shard alone (jax/optim.py
    make_shard_apply; shard-separability verified by probe), and a
    jitted all-gather rebuilds the replicated params and state.
    Per-device D2H/H2D and per-key wire bytes divide by local_size.
    Leaves below BYTEPS_SHARD_MIN_BYTES, leaves whose padding would
    exceed 1/8 of their size, rowsparse/host-compressed/bucket-fused
    leaves, multi-axis meshes and single-device meshes fall back to
    the whole-leaf path — numerics bitwise identical either way.

    ``compression``: string-kwargs dict for the codec registry (e.g.
    ``{"compressor": "onebit", "ef": "vanilla"}``) — gradients then ride
    the wire compressed with the C++ server decompress/sum/recompress
    mirror (reference: BASELINE config 4 path; server.cc:92-118). EF and
    momentum state live worker-side per tensor. ``min_compress_bytes``
    gates small tensors onto the dense path (BYTEPS_MIN_COMPRESS_BYTES).

    ``device_compress`` (default on whenever ``compression`` is set and
    the scheduler is running): run the momentum->EF->codec stack inside
    the compiled step (jax/device_compression.py), so the device->host
    hop carries the wire-sized payload — SURVEY §7's "the D2H moves
    *compressed* bytes" — instead of dense f32 that is then compressed
    in numpy; the pull reply is decompressed back on device. EF state
    lives on device and, like the host path's, resets on
    suspend/resume. Set False to force the host-numpy codec tier.

    ``rowsparse_params``: substrings of gradient names (e.g.
    ``("embed",)``) whose 2D gradients travel row-sparse — only nonzero
    rows on the push wire (bps.push_pull_rowsparse; embedding gradients
    are mostly zero rows). Takes precedence over ``compression`` for the
    matching leaves.

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``;
    reads the PS client + registry from the global state at call time, so
    it composes with suspend/resume.
    """
    import numpy as np

    from ..core.state import get_state

    import time as _time

    # registry is keyed to the client that created it: suspend/resume
    # replaces state.ps_client, and a cached registry would then push on a
    # destroyed native handle with a stale worker count
    comp_state = {"registry": None, "client": None, "device": None}
    # streamed-export machinery (one compiled tapped backward, rebuilt
    # when the gradient tree or eligibility changes; "disabled" latches
    # a build/dispatch failure so a broken callback path costs one
    # warning, not one attempt per step)
    stream_state: dict = {"fn": None, "key": None, "disabled": False,
                          "tag": 0, "holder": {"round": None},
                          # locality-shard plan (BYTEPS_LOCAL_SHARD_EXPORT):
                          # leaf index -> sizing/names, the declared shard
                          # subrange names (freed when the plan changes),
                          # and the cached P(axis) sharding for imports
                          "shard_info": {}, "shard_names": set(),
                          "nsharding": None}
    # sharded-apply build cache (keyed by params+opt_state structure;
    # sa None = transform not separable -> fused apply; ssa None =
    # not SHARD-separable -> gather gradients, full-leaf apply)
    sa_state: dict = {"sa": None, "key": None, "ssa": None,
                      "ssa_key": None, "gather": None}
    # deferred arena releases from sharded rounds: (leases, imported)
    pending: list = []
    # cross-barrier pipelining state (BYTEPS_CROSS_BARRIER): "carry" is
    # the previous step's still-in-flight tail — per-leaf waiters plus
    # the exact (param, param_parts, shared) base their stale apply
    # must chain from; "over" maps leaf index -> (new_param,
    # new_pparts) produced by a carried apply, consumed as the base of
    # that leaf's NEXT apply (or folded in by ``flush``); "par" is the
    # step parity that keeps two live rounds of one key on disjoint
    # arena slots. All touched from the step thread only.
    xb_state: dict = {"carry": None, "over": {}, "par": 0, "seq": 0}

    def local_grads(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = psum_tree(grads, axis=axis, average=True)
        loss = jax.lax.pmean(loss, axis)
        return loss, grads

    grad_fn = jax.jit(jax.shard_map(
        local_grads, mesh=mesh, in_specs=(P(), P(axis)),
        out_specs=(P(), P()), check_vma=False))

    def _build_streamed_fn(eligible, shard_set=(), n_leaves=0):
        """The tapped backward: identical math to ``grad_fn`` plus an
        io_callback on each eligible gradient leaf INSIDE the
        shard_mapped body — XLA schedules each tap right after its
        leaf's collective, so the callback fires while later gradients
        are still being produced (measured: first fire at ~1/3 of the
        backward wall). The step tag rides through the program so a
        late duplicate fire can never be mistaken for the next round's
        export.

        Leaves in ``shard_set`` (BYTEPS_LOCAL_SHARD_EXPORT) ride
        ``reduce_scatter`` instead of the psum: each device's tap then
        carries only ITS flat 1/local_size shard (the device index
        rides alongside), the program returns those leaves
        P(axis)-sharded, and only 1/local_size of the leaf ever crosses
        device->host per device — BytePS's hierarchical "the
        intra-machine reduce puts 1/local_size on the wire". The
        remaining leaves keep the exact whole-leaf path (one psum over
        their subtree, replicated output), so disabling sharding per
        leaf is bitwise-invisible."""
        from jax.experimental import io_callback

        from ..ops.push_pull import scatter_leaf

        holder = stream_state["holder"]
        shard_set = frozenset(shard_set)

        def _ingest(i, step_arr, dev_arr, arr):
            # round resolved at INGEST time: a stale fire then fails
            # the tag check instead of resurrecting a finished round.
            # int() here materializes only the two scalars — the heavy
            # payload is materialized by whichever worker the round
            # routes it to (router for whole leaves, per-device worker
            # for shards)
            rnd = holder["round"]
            if rnd is not None:
                rnd.on_fire(i, int(step_arr), int(dev_arr), arr)

        def _tap(i, step_arr, dev_arr, arr):
            # device thread: enqueue ONLY (see _export_pool — touching
            # the lazy callback args here would self-deadlock)
            _export_pool().submit(_ingest, i, step_arr, dev_arr, arr)

        def streamed_local(step_tag, params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            leaves = jax.tree.leaves(grads)
            # ONE psum over the whole-leaf subtree (identical reduction
            # grouping to the untapped grad_fn's full-tree psum), RS
            # per shard leaf
            whole_idx = [i for i in range(len(leaves))
                         if i not in shard_set]
            whole = psum_tree([leaves[i] for i in whole_idx],
                              axis=axis, average=True)
            whole_map = dict(zip(whole_idx, whole))
            idx = jax.lax.axis_index(axis)
            outs = []
            for i in range(len(leaves)):
                if i in shard_set:
                    sh = scatter_leaf(leaves[i], axis=axis, average=True)
                    io_callback(functools.partial(_tap, i), None,
                                step_tag, idx, sh, ordered=False)
                    outs.append(sh)
                else:
                    g = whole_map[i]
                    if i in eligible:
                        io_callback(functools.partial(_tap, i), None,
                                    step_tag, idx, g, ordered=False)
                    outs.append(g)
            loss = jax.lax.pmean(loss, axis)
            return loss, tuple(outs)

        out_leaf_specs = tuple(
            P(axis) if i in shard_set else P()
            for i in range(n_leaves))
        return jax.jit(jax.shard_map(
            streamed_local, mesh=mesh, in_specs=(P(), P(), P(axis)),
            out_specs=(P(), out_leaf_specs), check_vma=False))

    def apply_updates_fn(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    apply_fn = jax.jit(apply_updates_fn, donate_argnums=(0, 1))

    def step(params, opt_state, batch):
        state = get_state()
        client = state.ps_client
        # drain the previous sharded round's deferred arena releases
        # FIRST: the imported arrays' readiness proves the host staging
        # was consumed (their H2D completed), and releasing before this
        # round's checkouts keeps the steady state conflict-free — the
        # old end-of-step block_until_ready barrier, moved off the
        # critical path (by now the wait is ~zero)
        if pending:
            try:
                for pl, arrs in pending:
                    try:
                        jax.block_until_ready([a for a in arrs
                                               if a is not None])
                    except Exception:  # noqa: BLE001 - failed imports
                        # surfaced step N's async failure here at step
                        # N+1's start: never recycle the slots, and
                        # never re-raise the SAME failure on every
                        # later call of this closure
                        for lease in pl:
                            lease.abandon()
                        continue
                    for lease in pl:
                        lease.release()
            finally:
                del pending[:]
        if client is None:
            loss, grads = grad_fn(params, batch)
            params, opt_state = apply_fn(params, opt_state, grads)
            return params, opt_state, loss
        # per-step pipeline profile (core/metrics.py): the scheduler's
        # stage threads feed samples into this builder; end_step below
        # closes it into the StepReport ring (+ stall diagnosis when
        # BYTEPS_STALL_DIAG=1). None when metrics are off.
        prof = state.profiler.begin_step()
        # names/shapes come from the params tree (value_and_grad gives
        # gradients the identical structure), so the whole export plan
        # exists BEFORE the backward is dispatched — the streamed taps
        # need somewhere to land
        paths, treedef = jax.tree_util.tree_flatten_with_path(params)
        names, p_leaves = [], []
        for path, leaf in paths:
            names.append("grad/" + "/".join(
                str(getattr(k, "key", getattr(k, "idx", k)))
                for k in path))
            p_leaves.append(leaf)
        # ---- step efficiency ledger (core/ledger.py): register this
        # plan's cost model ONCE per gradient-tree shape — XLA cost
        # analysis of the compiled grad + apply units (lowering only:
        # nothing executes, donated args stay live) plus the plan's
        # ideal exchange bytes (each leaf crosses the wire once each
        # way), so end_step prices every step in MFU / roofline /
        # wire-efficiency terms. A backend without a cost model
        # registers the wire sizes alone (MFU stays None, never 0).
        ledger = getattr(state, "ledger", None)
        if ledger is not None and ledger.enabled:
            cost_key = (treedef, tuple(
                (tuple(np.shape(pl)), str(getattr(pl, "dtype", "")))
                for pl in p_leaves))
            # keyed on the LEDGER INSTANCE too: suspend/resume replaces
            # state.ledger, and a plan-key-only cache would leave the
            # fresh ledger with no cost model (post-resume MFU None)
            if (stream_state.get("cost_key") != cost_key
                    or stream_state.get("cost_ledger") is not ledger):
                stream_state["cost_key"] = cost_key
                stream_state["cost_ledger"] = ledger
                from ..core import ledger as ledger_mod
                flops = acc_bytes = None
                for part in (ledger_mod.jit_cost(grad_fn, params, batch),
                             ledger_mod.jit_cost(apply_fn, params,
                                                 opt_state, params)):
                    if part:
                        if part.get("flops"):
                            flops = (flops or 0.0) + part["flops"]
                        if part.get("bytes_accessed"):
                            acc_bytes = (acc_bytes or 0.0) \
                                + part["bytes_accessed"]
                ledger.register_step_cost(
                    flops=flops, bytes_accessed=acc_bytes,
                    ideal_wire_bytes=2 * sum(
                        int(getattr(pl, "nbytes", 0))
                        for pl in p_leaves),
                    source="xla" if flops else "none")
        use_device = (compression is not None
                      and device_compress is not False
                      and state.scheduler is not None)
        if use_device:
            loss, grads = grad_fn(params, batch)
            grads = _device_compressed_round(
                state, client, comp_state, compression,
                min_compress_bytes, rowsparse_params, names,
                jax.tree.leaves(grads), treedef)
            if prof is not None:
                # device tier: the round is monolithic (compute + wire
                # inside one helper), so compute_ms covers through the
                # round and the apply is the tail; overlap_frac must
                # price as None — export_done lands AFTER the wire
                # here, so spans would fabricate "perfect overlap"
                prof.monolithic = True
                prof.mark("export_done")
                prof.mark("drain_done")
            params, opt_state = apply_fn(params, opt_state, grads)
            state.profiler.end_step(prof, fallback=len(names))
            return params, opt_state, loss
        # ---- training-health collection (core/health.py,
        # BYTEPS_HEALTH): per-leaf gradient statistics accumulate off
        # the drain as each pulled aggregate lands; the param-norm
        # program (one tiny jit, len(names) floats D2H) feeds the
        # update-to-param ratios. Host tier only — the
        # device-compressed round never materializes the aggregate
        # host-side, so its health fields stay None, never a wrong 0.
        hplane = getattr(state, "health", None)
        # prof gates too: the detector/guard run from end_step's
        # observer hook, so without an open step report the collection
        # would be cost with no consumer (HealthPlane also refuses to
        # arm under BYTEPS_METRICS=0 — this is the per-step mirror)
        hc = hplane.begin_collect(len(names)) \
            if hplane is not None and prof is not None else None
        if hc is not None:
            pnorm_key = stream_state.get("pnorm_key")
            # identity-or-equality: PyTreeDef.__ne__ rejects None
            if pnorm_key is None or pnorm_key != treedef:
                def _pnorms(leaves):
                    return jnp.sqrt(jnp.asarray(
                        [jnp.sum(jnp.square(x.astype(jnp.float32)))
                         for x in leaves]))
                stream_state["pnorm_fn"] = jax.jit(_pnorms)
                stream_state["pnorm_key"] = treedef
            try:
                hc.param_norms_dev = stream_state["pnorm_fn"](
                    list(p_leaves))
            except Exception:  # noqa: BLE001 - ratios degrade to None
                hc.param_norms_dev = None
        # chaos harness: BYTEPS_CHAOS_NAN_LEAF poisons one matching
        # leaf's push mid-run (see _chaos_nan_poison)
        chaos_nan = os.environ.get("BYTEPS_CHAOS_NAN_LEAF") or None
        # ---- host tier: dense D2H (streamed where possible), codecs
        # in numpy ----
        reg = None
        mcb = min_compress_bytes
        if mcb is None:
            mcb = getattr(state.config, "min_compress_bytes", 0)
        if compression is not None:
            if comp_state["client"] is not client:
                from ..server.compressed import CompressedRegistry
                comp_state["registry"] = CompressedRegistry(
                    client, state.config.num_workers, compression, mcb)
                comp_state["client"] = client
            reg = comp_state["registry"]
        # one submit-as-ready loop for all three transports: dense or
        # compressed partitions enter the priority-scheduled pipeline
        # (compressed ones through COMPRESS/DECOMPRESS stages,
        # operations.cc:199-204); the no-scheduler fallbacks overlap
        # on a pool / run blocking.
        import byteps_tpu as bps

        # Persistent host staging (core/arena.py, the reference's
        # cpubuff discipline): result slots, fused-bucket concat slots
        # and streamed-export result slots check out of the arena instead
        # of np.empty per step; every lease is released only after the
        # imports below complete (or abandoned on error — correctness
        # never depends on a slot surviving).
        arena = state.arena
        leases: list = []

        def checkout(key, nbytes, dtype, tag=None):
            lease = arena.checkout(key, nbytes, tag=tag)
            leases.append(lease)
            return lease.array(dtype)

        # export-plane instruments (registered every round so they are
        # present in the snapshot even when no leaf shards — the docs
        # schema guard runs a dense whole-leaf step): whole-leaf
        # exports are one device's replicated buffer crossing D2H, so
        # they account to device 0; shard exports account to the
        # device that owns the shard. The shard A/B's hard proof is
        # the ratio between these per-device counters.
        metrics = state.metrics
        exp_shard_ctr = metrics.counter("export/shard_bytes")
        exp_whole_ctr = metrics.counter("export/whole_bytes")
        exp_dev0_ctr = metrics.counter("export/device_bytes/0")
        metrics.gauge("export/shard_workers").set(len(_SHARD_POOLS))
        metrics.gauge("export/worker_ingests/0").set(
            _SHARD_INGESTS.get(0, 0))
        ag_hist = metrics.histogram("step/allgather_us")

        # time-to-first-push: wall from the backward's dispatch to the
        # first submission entering the scheduler, whichever thread
        # gets there first (telemetry: export_ttfp_ms)
        round_t0 = _time.perf_counter()
        first_push = [None]
        fp_mu = threading.Lock()

        def mark_first_push():
            with fp_mu:
                if first_push[0] is None:
                    first_push[0] = _time.perf_counter() - round_t0

        def submit_sparse(name, h2d, out_dtype):
            from .. import _rowsparse_submit
            mark_first_push()
            handle = state.handles.allocate(name)
            obuf = checkout(f"{name}:out", h2d.size * 4, np.float32)
            _rowsparse_submit(state, name,
                              h2d.astype(np.float32, copy=False),
                              True, handle, out=obuf)
            return (lambda: state.handles.wait_and_clear(
                handle.id).astype(out_dtype, copy=False)), handle

        def submit(name, flat, priority=None, tag=None):
            """Returns (finish, notifier): ``finish()`` yields the
            reduced array (non-blocking once ``notifier`` — a Handle
            or Future with add_done_callback, or None for an already
            complete result — has fired)."""
            if chaos_nan is not None:
                flat = _chaos_nan_poison(
                    chaos_nan, name, flat,
                    prof.step if prof is not None else 0)
            mark_first_push()
            if reg is not None:
                flat = flat.astype(np.float32, copy=False)
                if state.scheduler is not None:
                    obuf = checkout(f"{name}:out", flat.nbytes,
                                    np.float32, tag=tag)
                    hd = reg.push_pull_async(state, name, flat, True,
                                             priority=priority, out=obuf)
                    return (lambda: bps.synchronize(hd),
                            state.handles.get(hd))
                fut = _comp_pool().submit(
                    reg.push_pull, state, name, flat, True)
                return fut.result, fut
            if state.scheduler is not None:
                # carry-eligible keys alternate arena slots by step
                # parity: with cross-barrier staleness the step-k slot
                # can still be awaiting its pull when step k+1 checks
                # the same key out, and a conflicting checkout would
                # fall back to a fresh allocation every step
                okey = (f"{name}:out~x{xb_par}"
                        if name in xb_carry_names else f"{name}:out")
                obuf = checkout(okey, flat.nbytes, flat.dtype, tag=tag)
                hd = bps.push_pull_async(flat, name, average=True,
                                         priority=priority, out=obuf)
                return (lambda: bps.synchronize(hd),
                        state.handles.get(hd))
            from ..server.client import ps_round_trip
            obuf = checkout(f"{name}:out", flat.nbytes, flat.dtype)
            res = ps_round_trip(state, name, flat, average=True,
                                out=obuf)
            return (lambda: res), None

        def submit_streamed(name, flat):
            """Tap-side submit (runs on the export worker) at
            production-order priority. ``flat`` is the materialized
            host view of the callback's array — its base keeps the
            buffer alive through the PUSH stage, so no staging copy is
            needed; the arena lease here is the EXPORT round's result
            slot (tag="export" in the arena counters)."""
            from ..server.client import get_or_init_ctx
            if reg is not None:
                # upcast BEFORE declaring: the compressed wire is f32,
                # and initializing the ctx from a non-f32 view would
                # re-partition it every round against the registry's
                # f32 sizing — recreating the CompressedTensor and
                # silently resetting its EF/momentum codec state
                flat = flat.astype(np.float32, copy=False)
            ctx = get_or_init_ctx(state, name, flat)
            pr = state.scheduler.production_priority(ctx)
            exp_whole_ctr.inc(flat.nbytes)
            exp_dev0_ctr.inc(flat.nbytes)
            return submit(name, flat, priority=pr, tag="export")

        def submit_shard(i, dev, flat):
            """Shard-side submit (runs on device ``dev``'s export
            worker): device ``dev``'s 1/local_size shard of leaf ``i``
            rides its own subrange key at the PARENT leaf's
            production-order priority (all shards of one leaf are one
            production event), with its own per-shard arena result
            slot (tag="shard" in the arena counters)."""
            from ..server.client import get_or_init_ctx
            info = stream_state["shard_info"][i]
            ctx = get_or_init_ctx(state, info["names"][dev], flat)
            pr = state.scheduler.production_priority(
                ctx, parent=info["parent"])
            exp_shard_ctr.inc(flat.nbytes)
            metrics.counter(f"export/device_bytes/{dev}").inc(flat.nbytes)
            _SHARD_INGESTS[dev] = _SHARD_INGESTS.get(dev, 0) + 1
            metrics.gauge(f"export/worker_ingests/{dev}").set(
                _SHARD_INGESTS[dev])
            return submit(info["names"][dev], flat, priority=pr,
                          tag="shard")

        def submit_shard_fallback(i, k, flat_piece):
            """Post-jit shard submit (drain thread): a shard-planned
            leaf whose taps never fired — or whose whole round runs on
            the untapped grad_fn — STILL pushes its per-shard keys, so
            this worker's key set never diverges from peers whose taps
            are healthy (a whole-leaf submit here would stall every
            worker's aggregation on both key sets). One device did the
            whole D2H (accounted to device 0); wire and import stay
            per-shard."""
            from ..server.client import get_or_init_ctx
            info = stream_state["shard_info"][i]
            ctx = get_or_init_ctx(state, info["names"][k], flat_piece)
            pr = state.scheduler.production_priority(
                ctx, parent=info["parent"])
            exp_shard_ctr.inc(flat_piece.nbytes)
            exp_dev0_ctr.inc(flat_piece.nbytes)
            return submit(info["names"][k], flat_piece, priority=pr,
                          tag="shard")

        # Bucket fusion (BYTEPS_FUSION_BYTES; the group-push cure):
        # per-key cost (scheduler admission, handle, two syscall
        # round-trips, server queue hop) is flat, so sub-threshold
        # leaves — biases, norms, small projections — fuse into one
        # concatenated key per dtype run and are sliced back after
        # the round. The bucket name is a content-stable digest of
        # (member names, sizes): every worker flattens the same tree
        # in the same order, so all workers aggregate the same
        # bucket; a changed model topology changes the digest and
        # cleanly declares a new key. Codec granularity for a fused
        # bucket is the bucket (matching the reference, where the
        # codec unit is the partition, not the layer).
        #
        # Interaction rules:
        # - bucket cap <= partition_bytes: a bucket must stay ONE
        #   key, or the partitioner re-splits it and re-adds the
        #   round trip fusion exists to remove;
        # - with compression on and min_compress_bytes > 0, only
        #   sub-mcb leaves fuse and the bucket stays < mcb, so
        #   tensors the gate kept full-precision (biases, norms)
        #   are NOT quantized via the fused key (mcb == 0 means the
        #   user asked for everything compressed — buckets too);
        # - sub-fusion leaves never stream: a bucket is a cross-leaf
        #   artifact, and its members must all be on host before the
        #   concat — exactly what the post-jit loop provides.
        fusion = getattr(state.config, "fusion_bytes", 0)
        bucket_cap = min(4 << 20,
                         getattr(state.config, "partition_bytes",
                                 4 << 20))
        if reg is not None and mcb > 0:
            fusion = min(fusion, mcb)
            bucket_cap = min(bucket_cap, mcb - 1)
        waiters = []   # (slot_or_slots, finisher, notifier)
        bucket: list = []  # [(slot, name, flat_f-contig host array)]
        bucket_bytes = 0

        def flush_bucket():
            nonlocal bucket, bucket_bytes
            if not bucket:
                return
            if len(bucket) == 1:
                slot, name, h = bucket[0]
                waiters.append((slot, *submit(name, h.reshape(-1))))
            else:
                import hashlib
                digest = hashlib.sha1(";".join(
                    f"{n}:{h.size}" for _, n, h in bucket)
                    .encode()).hexdigest()[:12]
                # concatenate into the bucket's PERSISTENT arena
                # slot (np.concatenate would allocate the fused
                # buffer fresh every step). With compression on the
                # wire is f32, so fill as f32 and skip the astype
                # copy submit() would otherwise make.
                bdt = np.dtype(np.float32) if reg is not None \
                    else bucket[0][2].dtype
                total = sum(h.size for _, _, h in bucket)
                fused = checkout(f"fused/{digest}:in",
                                 total * bdt.itemsize, bdt)
                off = 0
                for _, _, h in bucket:
                    fused[off:off + h.size] = h.reshape(-1)
                    off += h.size
                slots = [s for s, _, _ in bucket]
                sizes = [h.size for _, _, h in bucket]
                w, notifier = submit(f"fused/{digest}", fused)

                def finish(w=w, sizes=sizes):
                    out = w()
                    outs = np.split(out, np.cumsum(sizes)[:-1])
                    return outs

                waiters.append((slots, finish, notifier))
            bucket, bucket_bytes = [], 0

        # ---- streamed-export eligibility + tapped-backward build ----
        # A leaf streams when it rides its own dense/host-compressed
        # key: rowsparse routing needs the host 2D view, and
        # sub-fusion leaves belong to a bucket (see above). The tapped
        # jit is rebuilt only when the tree/eligibility changes.
        stream_cfg = stream_export if stream_export is not None \
            else getattr(state.config, "stream_export", True)
        stream_on = (stream_cfg and state.scheduler is not None
                     and not stream_state["disabled"])
        # ``stream_avail`` is the DETERMINISTIC gate (config + topology
        # — identical on every worker); ``stream_on`` additionally
        # folds in this process's runtime latch (broken callbacks).
        # The locality-shard PLAN below must key off stream_avail, not
        # stream_on: the set of PS keys a worker pushes has to be a
        # pure function of deterministic inputs, or one worker's
        # runtime fallback would desynchronize the key sets and stall
        # every peer's aggregation.
        stream_avail = (stream_cfg and state.scheduler is not None)
        eligible: tuple = ()
        if stream_avail:
            el = []
            for i, (name, leaf) in enumerate(zip(names, p_leaves)):
                if rowsparse_params and any(s in name
                                            for s in rowsparse_params):
                    continue
                nb = getattr(leaf, "nbytes", 0)
                if nb == 0 or nb < fusion:
                    continue
                el.append(i)
            eligible = tuple(el)
        stream_on = stream_on and bool(eligible)
        # ---- locality-shard plan (BYTEPS_LOCAL_SHARD_EXPORT): which
        # eligible leaves reduce-scatter so each local device exports
        # only its own 1/local_size shard. Host-compressed rounds keep
        # whole-leaf keys (the codec unit is the declared key — a
        # per-shard codec would reset EF/momentum state per device),
        # multi-axis and single-device meshes have no locality axis to
        # shard over, and leaves below the size/pad thresholds are not
        # worth local_size extra key round-trips. All of these gates
        # are deterministic across workers; a leaf in the plan rides
        # its shard keys on EVERY path, streamed or fallback.
        shard_cfg = local_shard_export if local_shard_export is not None \
            else getattr(state.config, "local_shard_export", True)
        n_shard = 0
        if (shard_cfg and stream_avail and reg is None
                and len(mesh.axis_names) == 1):
            n_shard = int(mesh.shape.get(axis, 1))
        shard_set: tuple = ()
        if n_shard > 1:
            from ..ops.push_pull import shard_layout
            smin = max(fusion, getattr(state.config, "shard_min_bytes",
                                       65536))
            ss = []
            for i in eligible:
                leaf = p_leaves[i]
                if leaf.nbytes < smin:
                    continue
                size = int(np.prod(leaf.shape)) if leaf.shape else 1
                _, pad = shard_layout(size, n_shard)
                if pad * 8 > size:
                    continue  # padding beyond 1/8: not worth the wire
                ss.append(i)
            shard_set = tuple(ss)
        plan_key = (treedef, eligible, shard_set, n_shard)
        if stream_avail and stream_state["key"] != plan_key:
            # declare the per-shard subrange keys FIRST, in flatten
            # order — every worker flattens the same tree, so the
            # shard declared_keys agree across workers (tap-order
            # declaration would race per-device workers); the parent
            # name is declared too, as the production-order anchor all
            # of a leaf's shards share. This runs even when the tap
            # build below fails or is latched off: the fallback paths
            # still push the SHARD keys.
            from ..core.types import DataType
            from ..ops.push_pull import shard_layout
            info: Dict[int, dict] = {}
            declared: set = set()
            for i in shard_set:
                leaf = p_leaves[i]
                size = int(np.prod(leaf.shape)) if leaf.shape else 1
                slen, _ = shard_layout(size, n_shard)
                dt = np.dtype(leaf.dtype)
                ctxs = state.registry.declare_shards(
                    names[i], slen * dt.itemsize, n_shard,
                    DataType.from_np(dt))
                info[i] = {
                    "n": n_shard, "shard_len": slen, "size": size,
                    "dtype": dt,
                    "names": [c.name for c in ctxs],
                    "parent": state.registry.declare(
                        names[i], DataType.from_np(dt)),
                }
                declared.update(c.name for c in ctxs)
            # shard-subrange free: retire stale keys' server-load
            # accounting when the plan changes (leaf resized, knob
            # flipped, mesh changed) — dead keys must not skew
            # later least-loaded assignments
            for stale in stream_state["shard_names"] - declared:
                state.registry.free(stale)
            stream_state["shard_info"] = info
            stream_state["shard_names"] = declared
            if shard_set:
                from jax.sharding import NamedSharding
                stream_state["nsharding"] = NamedSharding(mesh, P(axis))
            if stream_on:
                try:
                    stream_state["fn"] = _build_streamed_fn(
                        eligible, shard_set, len(names))
                except Exception as e:  # noqa: BLE001 - clean fallback
                    stream_on = False
                    _disable_stream(
                        stream_state,
                        "streamed gradient export unavailable (%s); "
                        "falling back to post-jit export", e)
            stream_state["key"] = plan_key
        stream_on = stream_on and stream_state["fn"] is not None

        # ---- sharded-apply build (cached per tree structure) ----
        sharded_cfg = sharded_apply if sharded_apply is not None \
            else getattr(state.config, "sharded_apply", True)
        sa = None
        if sharded_cfg:
            skey = (treedef, jax.tree.structure(opt_state))
            if sa_state["key"] != skey:
                from .optim import make_sharded_apply
                sa_state["sa"] = make_sharded_apply(tx, params, opt_state)
                sa_state["key"] = skey
            sa = sa_state["sa"]  # None -> not separable -> fused apply
        # shard-mapped apply for shard-exported leaves: update runs on
        # the 1/local_size shard each device just imported, then the
        # gather jit rebuilds replicated params/state. ssa None (not
        # shard-separable, e.g. block-norm scaling) -> the drain
        # gathers the gradient instead and applies full-leaf.
        ssa = None
        if shard_set and sa is not None:
            ssa_key = (sa_state["key"], n_shard)
            if sa_state["ssa_key"] != ssa_key:
                from .optim import make_shard_apply
                sa_state["ssa"] = make_shard_apply(
                    tx, params, opt_state, mesh, axis, n_shard, base=sa)
                sa_state["ssa_key"] = ssa_key
            ssa = sa_state["ssa"]
        if shard_set and sa_state["gather"] is None:
            from .optim import LeafGather
            sa_state["gather"] = LeafGather(mesh, axis)

        # ---- cross-barrier bounded staleness (BYTEPS_CROSS_BARRIER /
        # BYTEPS_STALENESS, the PR 16 tentpole): instead of barriering
        # on the full drain, the step releases once the front-of-model
        # leaves (a flatten-order prefix — what the next forward reads
        # first) have imported; the tail leaves' PULL→H2D→UPDATE is
        # carried across the step boundary and drained after the NEXT
        # step's export, overlapping its compute. Carry-eligible leaves
        # are the plain dense whole-leaf keys only: bucket members,
        # shard subranges, rowsparse and host-compressed keys keep the
        # synchronous drain (their codec/assembly state is not
        # round-windowed). Requires the per-leaf sharded apply (the
        # carried update is a single-leaf chain) and the scheduler's
        # staleness credit (window > 0 implies fused pushpull, whose
        # replies are round-stamped server-side).
        xb_window = getattr(state.scheduler, "xb_window", 0) \
            if state.scheduler is not None else 0
        xb_on = bool(xb_window > 0 and sa is not None and reg is None)
        xb_over = xb_state["over"]
        # step ordinal for staleness-lag attribution: the carry records
        # the seq it was created at, the drain reports how many step
        # boundaries the tail actually crossed (1 at steady state)
        xb_state["seq"] += 1
        xb_carry_set: set = set()
        if xb_on:
            xb_state["par"] ^= 1
            shard_planned = set(shard_set)
            rel_n = max(1, (len(names) + 1) // 2)
            for i, nm in enumerate(names):
                if i < rel_n:
                    continue
                nb = getattr(p_leaves[i], "nbytes", 0)
                if nb == 0 or nb < fusion or i in shard_planned:
                    continue
                if rowsparse_params and any(s in nm
                                            for s in rowsparse_params):
                    continue
                xb_carry_set.add(i)
        xb_carry_names = {names[i] for i in xb_carry_set}
        xb_par = xb_state["par"]

        # ---- dispatch the backward (tapped when streaming) ----
        round_obj = None
        loss = grads = None
        if stream_on:
            stream_state["tag"] += 1
            round_obj = _StreamRound(
                stream_state["tag"], names, submit_streamed,
                mark_first_push,
                shard_plan={i: n_shard for i in shard_set},
                submit_shard=submit_shard)
            for i in eligible:
                round_obj.expect(i)
            stream_state["holder"]["round"] = round_obj
            try:
                loss, grads = stream_state["fn"](
                    jnp.int32(stream_state["tag"]), params, batch)
            except Exception as e:  # noqa: BLE001 - compile/dispatch
                # failure of the TAPPED build only: quiesce the export
                # worker, clean up whatever the partial round
                # submitted, and latch the fallback
                stream_state["holder"]["round"] = None
                round_obj.cancel()
                streamed_any = round_obj.any_submitted()
                for h in round_obj.handles():
                    state.handles.discard(h.id)
                for lease in leases:
                    lease.abandon()
                del leases[:]
                round_obj = None
                _disable_stream(
                    stream_state,
                    "streamed gradient export failed at dispatch "
                    "(%s); falling back to post-jit export", e)
                if streamed_any:
                    # pushes for this round are already on the wire:
                    # resubmitting the same keys in the fallback would
                    # double-push them — the server counts pushes
                    # positionally per worker per key, so that would
                    # silently shift every later round's aggregation
                    # (the corruption class _pin_priority guards).
                    # Fail THIS round instead; the next step runs
                    # cleanly on the plain jit.
                    raise
                # nothing left the worker (e.g. pure compile failure):
                # retry this step on the plain jit — a genuine compute
                # error will surface there on its own terms
        if grads is None:
            loss, grads = grad_fn(params, batch)
        g_leaves = jax.tree.leaves(grads)
        streamed_set = set(eligible) if round_obj is not None else set()
        # start the D2H copies for the non-streamed leaves now; each
        # np.asarray below then only waits for ITS leaf, so the
        # transfer of leaf k+1 rides the bus while leaf k is already
        # in PUSH — the reference's per-partition COPYD2H/PUSH overlap
        # (core_loops.cc:378-443). Streamed leaves already crossed in
        # their tap.
        for i, leaf in enumerate(g_leaves):
            if i not in streamed_set and hasattr(leaf,
                                                 "copy_to_host_async"):
                leaf.copy_to_host_async()

        imported: list = [None] * len(names)
        new_params: list = [None] * len(names)
        apply_parts: list = [None] * len(names)
        # per-leaf shard import state (BYTEPS_LOCAL_SHARD_EXPORT):
        # shard k of leaf i lands on the device that owns it the moment
        # its pull completes; when the last shard of a leaf lands, the
        # shards assemble into one P(axis)-sharded array and the
        # shard update + all-gather dispatch
        # the PLAN decides shard-key participation — with or without a
        # live streamed round — so every path (streamed taps, broken-tap
        # fallback, untapped grad_fn retry) pushes the same key set as
        # every other worker
        active_shard = stream_state["shard_info"] if shard_set else {}
        shard_parts: Dict[int, list] = {}
        shard_left: Dict[int, int] = {}
        axis_devs = list(mesh.devices.flat)
        try:
            for i, (name, leaf) in enumerate(zip(names, g_leaves)):
                if i in streamed_set:
                    # peek first; on a miss, block on the leaf ITSELF —
                    # a compute error then surfaces immediately instead
                    # of stalling a long claim — and give the ingest
                    # one more beat (it fires by program end unless the
                    # callback path is truly dead, which the final
                    # claim latches via round.broken)
                    w = round_obj.claim(i, timeout=5.0, final=False)
                    if w is None:
                        # ready-or-raise WITHOUT materializing: a
                        # D2H here would assemble the full (for shard
                        # leaves: cross-device) value only to discard
                        # it when the claim then succeeds
                        jax.block_until_ready(leaf)
                        w = round_obj.claim(i, timeout=30.0, final=True)
                    if w is not None:
                        if (isinstance(w, tuple) and len(w) == 2
                                and w[0] == "shards"):
                            shard_parts[i] = [None] * active_shard[i]["n"]
                            shard_left[i] = active_shard[i]["n"]
                            for dev, (fin, notif) in w[1]:
                                waiters.append((("shard", i, dev),
                                                fin, notif))
                        else:
                            waiters.append((i, *w))
                        continue
                    # claimed for fallback: export synchronously below
                h = np.asarray(leaf)  # ready-or-wait for THIS leaf
                if i in active_shard:
                    # shard-planned leaf on a fallback path (taps dead,
                    # or the whole round on the untapped grad_fn): keep
                    # the SHARD keys — slice the host copy into the
                    # same padded subranges the taps would have pushed.
                    # From the tapped program the value is already the
                    # reduce-scattered flat (concat of shards == padded
                    # summed flat, bitwise); from grad_fn it is the
                    # full psum'd leaf and pads here.
                    info = active_shard[i]
                    flat = h.reshape(-1)
                    total = info["n"] * info["shard_len"]
                    if flat.size != total:
                        flat = np.pad(flat, (0, total - flat.size))
                    flush_bucket()
                    shard_parts[i] = [None] * info["n"]
                    shard_left[i] = info["n"]
                    slen = info["shard_len"]
                    for k in range(info["n"]):
                        w = submit_shard_fallback(
                            i, k, flat[k * slen:(k + 1) * slen])
                        waiters.append((("shard", i, k), *w))
                    continue
                exp_whole_ctr.inc(h.nbytes)
                exp_dev0_ctr.inc(h.nbytes)
                if _route_rowsparse(name, h, state, rowsparse_params):
                    flush_bucket()
                    # non-f32 grads upcast for the wire, cast back
                    waiters.append((i, *submit_sparse(name, h,
                                                      h.dtype)))
                elif h.nbytes < fusion:
                    if bucket and (bucket[0][2].dtype != h.dtype
                                   or bucket_bytes + h.nbytes
                                   > bucket_cap):
                        flush_bucket()
                    bucket.append((i, name, h))
                    bucket_bytes += h.nbytes
                else:
                    flush_bucket()
                    waiters.append((i, *submit(name, h.reshape(-1))))
            flush_bucket()
            if prof is not None:
                # every leaf is now off the device and submitted (each
                # np.asarray above blocked on ITS leaf): the compute +
                # export wall of this step's report
                prof.mark("export_done")
            # ---- carried drain (BYTEPS_CROSS_BARRIER): the PREVIOUS
            # step's tail rounds land here, AFTER this step's backward
            # has been dispatched and its exports submitted — their
            # PULL wait overlaps this step's compute, which is the
            # whole point. Each carried apply chains from the exact
            # base captured at carry time (never the live opt_state,
            # which has moved on) via the non-donating apply_with, and
            # its result becomes this step's base for the same leaf
            # (``xb_over``). Health stats tap into THIS step's
            # collector: one tap per leaf per step at steady state, so
            # the per-round detectors see divergence within one step.
            prev_carry = xb_state["carry"]
            xb_state["carry"] = None
            xb_drained = 0
            xb_drain_ms = xb_lag = None
            if prev_carry is not None:
                t_xb = _time.perf_counter()
                try:
                    for (s, fin, _nt, bp, bpp, bsh) in \
                            prev_carry["entries"]:
                        piece = fin()
                        if hc is not None:
                            hc.leaf(s, piece)
                        arr = jax.device_put(
                            piece.reshape(np.shape(bp)))
                        npar, nparts = prev_carry["sa"].apply_with(
                            bp, bpp, bsh, arr)
                        xb_over[s] = (npar, nparts[0])
                        prev_carry["imported"].append(arr)
                except BaseException:
                    # a failed carried pull loses step k's update for
                    # this leaf: same contract as a mid-drain failure
                    # of the donated apply — abandon, surface, restart
                    # from a checkpoint
                    for lease in prev_carry["leases"]:
                        lease.abandon()
                    for (_s, _f, nt, *_rest) in prev_carry["entries"]:
                        if hasattr(nt, "id"):
                            state.handles.discard(nt.id)
                    raise
                centry = (prev_carry["leases"], prev_carry["imported"])
                pending.append(centry)

                def _xb_release(entry=centry):
                    try:
                        jax.block_until_ready([a for a in entry[1]
                                               if a is not None])
                    except Exception:  # noqa: BLE001 - failed imports:
                        for lease in entry[0]:  # never recycle
                            lease.abandon()
                        return
                    for lease in entry[0]:
                        lease.release()

                _release_pool().submit(_xb_release)
                metrics.counter("barrier/carry_drained").inc(
                    len(prev_carry["entries"]))
                xb_drained = len(prev_carry["entries"])
                xb_drain_ms = (_time.perf_counter() - t_xb) * 1e3
                xb_lag = xb_state["seq"] - prev_carry.get(
                    "step", xb_state["seq"] - 1)
            # param shapes, not gradient-output shapes: a shard-planned
            # leaf's program output is the flat padded sharded layout,
            # but everything imported/applied below is leaf-shaped
            shapes = [np.shape(pl) for pl in p_leaves]
            # Completion-ordered drain — IMPORT + UPDATE: issue the
            # async H2D device_put for each leaf THE MOMENT its pull
            # lands (XLA overlaps the import of tensor k with the DCN
            # PULL of tensor k+1 — the mirror of the streamed EXPORT
            # above; reference: COPYH2D as its own pipeline stage,
            # core_loops.cc:620-648), and with the sharded apply, its
            # per-leaf optimizer update right behind it — UPDATE(k)
            # overlaps PULL(k+1), the tail of the COMPUTE/PUSH/UPDATE
            # pipeline.
            import queue as _queue

            ready: "_queue.Queue" = _queue.Queue()
            for wi, (_, _, notifier) in enumerate(waiters):
                if notifier is None:
                    ready.put(wi)
                else:
                    notifier.add_done_callback(
                        lambda *_a, wi=wi: ready.put(wi))

            sa_round = sa.begin(opt_state) if sa is not None else None
            # per-leaf PULL→H2D→UPDATE drain spans (the ISSUE's
            # measurement of the import half of the pipeline): each
            # land() is one leaf's H2D issue + sharded-update dispatch
            h2d_hist = state.metrics.histogram("step/h2d_update_us")

            def land(s, piece):
                t0 = _time.perf_counter()
                if hc is not None:
                    hc.leaf(s, piece)  # health tap: stats off the drain
                arr = jax.device_put(piece.reshape(shapes[s]))
                imported[s] = arr
                if sa_round is not None:
                    ov = xb_over.pop(s, None) if xb_over else None
                    if ov is not None:
                        # this leaf's previous round was carried: chain
                        # from the carried apply's result, not the
                        # (one-step-stale) tree slices
                        new_params[s], apply_parts[s] = sa.apply_with(
                            ov[0], ov[1], sa_round.slice(s)[1], arr)
                    else:
                        new_params[s], apply_parts[s] = sa_round.apply(
                            p_leaves[s], s, arr)
                dt = _time.perf_counter() - t0
                h2d_hist.record_seconds(dt)
                if prof is not None:
                    prof.stage_sample("H2D_UPDATE", dt)

            def land_shard(s, dev, piece):
                # import shard `dev` of leaf `s` onto the device that
                # owns it — 1/local_size of the H2D the whole-leaf
                # import moved, overlapped with the remaining pulls
                t0 = _time.perf_counter()
                if hc is not None:
                    hc.leaf(s, piece)  # shard pieces sum into the leaf
                info = active_shard[s]
                parts = shard_parts[s]
                parts[dev] = jax.device_put(piece, axis_devs[dev])
                shard_left[s] -= 1
                dt = _time.perf_counter() - t0
                h2d_hist.record_seconds(dt)
                if prof is not None:
                    prof.stage_sample("H2D_UPDATE", dt)
                if shard_left[s]:
                    return
                # last shard landed: assemble the P(axis)-sharded
                # gradient, run the update on the shards, and dispatch
                # the all-gather that rebuilds the replicated leaves
                garr = jax.make_array_from_single_device_arrays(
                    (info["n"] * info["shard_len"],),
                    stream_state["nsharding"], parts)
                imported[s] = garr
                t_ag = _time.perf_counter()
                if ssa is not None and sa_round is not None:
                    pparts, shared = sa_round.slice(s)
                    new_sh, npp_sh, n_shared = ssa.apply(
                        p_leaves[s], pparts, shared, garr)
                    fulls = ssa.gather((new_sh, *npp_sh),
                                       [p_leaves[s], *pparts])
                    new_params[s] = fulls[0]
                    apply_parts[s] = (list(fulls[1:]), n_shared)
                else:
                    # transform not shard-separable (or fused apply):
                    # gather the GRADIENT instead and apply full-leaf —
                    # the D2H/wire/H2D savings stand, only the update
                    # FLOPs stay replicated
                    tmpl = jax.ShapeDtypeStruct(shapes[s], info["dtype"])
                    full = sa_state["gather"]((garr,), [tmpl])[0]
                    imported[s] = full
                    if sa_round is not None:
                        new_params[s], apply_parts[s] = sa_round.apply(
                            p_leaves[s], s, full)
                dt = _time.perf_counter() - t_ag
                ag_hist.record_seconds(dt)
                if prof is not None:
                    prof.stage_sample("ALLGATHER", dt)

            def _dispatch(wi):
                slot, finish, _ = waiters[wi]
                if isinstance(slot, list):
                    for s, piece in zip(slot, finish()):
                        land(s, piece)
                elif isinstance(slot, tuple):
                    land_shard(slot[1], slot[2], finish())
                else:
                    land(slot, finish())

            # cross-barrier release condition: every NON-carryable
            # waiter must land this step (front-of-model leaves,
            # buckets, shards, rowsparse); carry-eligible tail leaves
            # land if their pull has already fired, and are otherwise
            # carried across the step boundary. With the window off
            # this is exactly the old "drain everything" loop.
            xb_carry_wi = {wi for wi, (sl, _f, _n) in enumerate(waiters)
                           if isinstance(sl, int) and sl in xb_carry_set}
            must_land = len(waiters) - len(xb_carry_wi)
            done_wi: set = set()
            landed_req = 0
            while landed_req < must_land:
                t_wait = _time.perf_counter()
                wi = ready.get()
                if prof is not None:
                    # time the drain sat blocked waiting for a pull to
                    # land — the direct "PULL is the bottleneck" signal
                    prof.add_pull_wait(_time.perf_counter() - t_wait)
                _dispatch(wi)
                done_wi.add(wi)
                if wi not in xb_carry_wi:
                    landed_req += 1
            # opportunistic: a carry-eligible pull that already fired
            # costs nothing to drain now
            while xb_carry_wi:
                try:
                    wi = ready.get_nowait()
                except _queue.Empty:
                    break
                _dispatch(wi)
                done_wi.add(wi)
            xb_pend = sorted(xb_carry_wi - done_wi)
            if xb_pend:
                centries = []
                for wi in xb_pend:
                    s, fin, notif = waiters[wi]
                    pparts, shared = sa_round.slice(s)
                    ov = xb_over.pop(s, None)
                    bp = ov[0] if ov is not None else p_leaves[s]
                    bpp = ov[1] if ov is not None else pparts
                    centries.append((s, fin, notif, bp, bpp, shared))
                    # the step returns the freshest APPLIED value for a
                    # carried leaf — at most one step behind — and its
                    # stale state slices; the carry's base_override
                    # chain keeps the true state, and ``flush`` folds
                    # the final values in at end of run
                    new_params[s] = bp
                    apply_parts[s] = (bpp, shared)
                ckeys = {f"{names[s]}:out~x{xb_par}"
                         for (s, *_rest) in centries}
                # the carried leaves' result slots stay leased until
                # the carried drain consumes them next step — they must
                # NOT ride this step's deferred release
                cleases = [lz for lz in leases if lz.key in ckeys]
                leases[:] = [lz for lz in leases if lz.key not in ckeys]
                xb_state["carry"] = {"entries": centries,
                                     "leases": cleases,
                                     "imported": [], "sa": sa,
                                     "step": xb_state["seq"]}
                metrics.counter("barrier/carried_leaves").inc(
                    len(centries))
            if sa is None:
                # fused apply: wait for the H2D transfers (apply_fn
                # needs them anyway) so the arena slots are provably
                # idle before release
                jax.block_until_ready([x for x in imported
                                       if x is not None])
            if prof is not None:
                prof.mark("drain_done")
        except BaseException:
            # a failed round (submission OR drain) may leave pulls
            # mid-flight into these slots: abandon (drop from the
            # table) instead of recycling them under a late writer.
            # The not-yet-drained sibling handles must not pin their
            # gradient-sized result buffers in the handle table for
            # the life of the process either (the same leak class
            # the TF graph tier discards against).
            stream_state["holder"]["round"] = None
            if round_obj is not None:
                # quiesce BEFORE the abandon/discard loops: an ingest
                # mid-flight on the export worker may still be checking
                # out a lease / allocating a handle
                round_obj.cancel()
            # a raised step voids the cross-barrier chain: overrides
            # reference buffers from the failed round, and a restarted
            # run must not apply them onto checkpoint-restored trees
            xbc = xb_state["carry"]
            xb_state["carry"] = None
            if xbc is not None:
                for lease in xbc["leases"]:
                    lease.abandon()
                for (_s, _f, nt, *_rest) in xbc["entries"]:
                    if hasattr(nt, "id"):
                        state.handles.discard(nt.id)
            xb_state["over"].clear()
            for lease in leases:
                lease.abandon()
            for _, _, notifier in waiters:
                if hasattr(notifier, "id"):
                    state.handles.discard(notifier.id)
            if round_obj is not None:
                for h in round_obj.handles():
                    state.handles.discard(h.id)
            raise
        stream_state["holder"]["round"] = None
        if round_obj is not None and round_obj.broken:
            # taps compiled but never fired at runtime: without this
            # latch every FUTURE step would re-pay the full claim
            # timeouts before falling back — the once-only cost the
            # build/dispatch handlers already guarantee
            _disable_stream(
                stream_state,
                "streamed gradient export taps never fired at "
                "runtime; falling back to post-jit export")
        state.telemetry.record_export(
            round_obj.streamed if round_obj is not None else 0,
            len(names) - (round_obj.streamed
                          if round_obj is not None else 0),
            first_push[0],
            shard_leaves=(round_obj.shard_leaves
                          if round_obj is not None else 0))
        if sa is not None:
            # UPDATEs are already in flight; the end-of-step barrier is
            # gone. The leases release on whichever fires first: the
            # export worker (as soon as the imports are ready — covers
            # the LAST step of a run and a rebuilt step closure, which
            # would otherwise pin the slots forever and conflict a new
            # closure's checkouts into fresh allocations) or the next
            # step's deterministic drain (release() is idempotent, so
            # double-firing is harmless).
            entry = (list(leases), imported)
            pending.append(entry)

            def _release_when_ready(entry=entry):
                try:
                    jax.block_until_ready([a for a in entry[1]
                                           if a is not None])
                except Exception:  # noqa: BLE001 - failed imports:
                    for lease in entry[0]:    # never recycle the slots
                        lease.abandon()
                    return
                for lease in entry[0]:
                    lease.release()

            _release_pool().submit(_release_when_ready)
            params = treedef.unflatten(new_params)
            opt_state = sa.merge(opt_state, apply_parts)
        else:
            for lease in leases:
                lease.release()
            grads = treedef.unflatten(imported)
            params, opt_state = apply_fn(params, opt_state, grads)
        n_streamed = round_obj.streamed if round_obj is not None else 0
        # training-health finalize: close the step's per-leaf stats
        # into the StepReport fields (incl. the bounded HEALTH_PULL
        # fidelity sweep); the HealthPlane observer inside end_step
        # then runs the detector, and with BYTEPS_NAN_GUARD a
        # nonfinite round raises HERE — after the flight events and
        # counters landed, never before (detect → record → fail-fast)
        health_fields = None
        if hc is not None:
            try:
                health_fields = hplane.finalize(hc, names, state)
            except Exception:  # noqa: BLE001 - diagnostics never kill
                health_fields = None          # the step
        # cross-barrier staleness fields for the StepReport and its
        # time-series: drained-tail size/wall, effective staleness and
        # the depth still deferred into the NEXT step (None when the
        # cross-barrier plane is off — the series simply skip)
        xb_fields = None
        if xb_on:
            _c = xb_state["carry"]
            xb_fields = {
                "carried_leaves": xb_drained,
                "carry_drain_ms": xb_drain_ms,
                "staleness_lag": xb_lag,
                "window_depth": len(_c["entries"]) if _c else 0,
            }
        state.profiler.end_step(
            prof,
            ttfp_ms=first_push[0] * 1e3 if first_push[0] is not None
            else None,
            streamed=n_streamed, fallback=len(names) - n_streamed,
            health=health_fields, xb=xb_fields)
        if hplane is not None:
            hplane.raise_if_fatal()
        return params, opt_state, loss

    def flush(params, opt_state):
        """Drain the cross-barrier carry and fold every outstanding
        override into ``(params, opt_state)`` — call once after the
        LAST step of a run (a checkpoint cut counts). Without
        BYTEPS_CROSS_BARRIER (or with nothing carried) this returns
        its arguments unchanged."""
        carry = xb_state["carry"]
        xb_state["carry"] = None
        over = xb_state["over"]
        if carry is not None:
            try:
                for (s, fin, _nt, bp, bpp, bsh) in carry["entries"]:
                    piece = fin()
                    arr = jax.device_put(piece.reshape(np.shape(bp)))
                    npar, nparts = carry["sa"].apply_with(
                        bp, bpp, bsh, arr)
                    over[s] = (npar, nparts[0])
                    carry["imported"].append(arr)
                jax.block_until_ready(carry["imported"])
            except BaseException:
                for lease in carry["leases"]:
                    lease.abandon()
                raise
            for lease in carry["leases"]:
                lease.release()
        if not over:
            return params, opt_state
        sa = sa_state["sa"]
        leaves, tdef = jax.tree_util.tree_flatten(params)
        rnd = sa.begin(opt_state)
        results = []
        for s in range(len(leaves)):
            pp, sh = rnd.slice(s)
            ov = over.pop(s, None)
            if ov is not None:
                leaves[s] = ov[0]
                pp = ov[1]
            results.append((pp, sh))
        return tdef.unflatten(leaves), sa.merge(opt_state, results)

    # tick the Chrome-trace step counter: the PUSH/PULL/COMPRESS spans the
    # scheduler records are windowed by step (BYTEPS_TRACE_START/END_STEP)
    stepper = _with_tracer_tick(step)
    stepper.flush = flush
    return stepper


def make_async_ps_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DP_AXIS,
):
    """Asynchronous data-parallel train step (the reference's
    BYTEPS_ENABLE_ASYNC mode, torch/__init__.py:188-216, server.cc:315-319):
    each worker updates its params locally, pushes the weight DELTA to the
    PS — which folds it into the authoritative weights with no aggregation
    barrier — and pulls the current weights back. Workers never wait for
    each other; staleness is the accepted tradeoff.

    The server must run with BYTEPS_ENABLE_ASYNC=1. On the first step each
    worker init-pushes its initial weights (first arrival seeds the
    authoritative copy — start workers from identical or broadcast params).

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``.
    Without a PS configured, degrades to plain local (single-worker) SGD.
    """
    import numpy as np

    from ..core.state import get_state
    from ..server.client import get_or_init_ctx

    def local_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = psum_tree(grads, axis=axis, average=True)
        updates, opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        delta = jax.tree.map(jnp.subtract, new_params, params)
        loss = jax.lax.pmean(loss, axis)
        return loss, delta, opt_state

    local_fn = jax.jit(jax.shard_map(
        local_step, mesh=mesh, in_specs=(P(), P(), P(axis)),
        out_specs=(P(), P(), P()), check_vma=False))

    # seeding is keyed to the client that received it: suspend/resume
    # replaces state.ps_client with fresh (unseeded) servers, and a stale
    # `seeded` set would skip init_weights — the pull would then return
    # bare deltas and silently destroy the model (the sync paths carry
    # the same client-keyed guard on their compression registry)
    seed_state = {"client": None, "names": set()}

    def step(params, opt_state, batch):
        state = get_state()
        client = state.ps_client
        loss, delta, opt_state = local_fn(params, opt_state, batch)
        if client is None:
            params = jax.tree.map(jnp.add, params, delta)
            return params, opt_state, loss
        if seed_state["client"] is not client:
            seed_state["client"] = client
            seed_state["names"] = set()
        seeded = seed_state["names"]
        paths, treedef = jax.tree_util.tree_flatten_with_path(params)
        deltas = jax.tree.leaves(delta)
        leaves = []
        for (path, leaf), d in zip(paths, deltas):
            name = "asyncw/" + "/".join(
                str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
            host_w = np.asarray(leaf).reshape(-1)
            ctx = get_or_init_ctx(state, name, host_w)
            if name not in seeded:
                client.init_weights(ctx, host_w)
                seeded.add(name)
            leaves.append((ctx, leaf, np.asarray(d).reshape(-1)))

        # overlap the per-leaf round trips (they'd otherwise serialize the
        # step on sum-of-RTTs) on the shared tensor-level pool — NOT
        # client._pool (these calls block on client-pool futures and
        # would deadlock it), and not a per-step executor (spawn/join of
        # 16 threads every step on the hot path)
        def one(item):
            ctx, leaf, d = item
            out = client.push_delta_pull_weights(ctx, d)
            state.telemetry.record_round_trip(out.nbytes)
            return jnp.asarray(out.reshape(leaf.shape))

        pulled = list(_comp_pool().map(one, leaves))
        params = treedef.unflatten(pulled)
        return params, opt_state, loss

    return _with_tracer_tick(step)


def init_zero_state(params, tx: optax.GradientTransformation, mesh: Mesh,
                    axis: str = DP_AXIS):
    """Initialize optimizer state over flat 1/N param shards (matches
    make_zero_train_step's layout)."""
    opt_specs = _zero_state_specs(params, tx, mesh, axis)

    def init(params_):
        shards = reduce_scatter_tree(params_, axis=axis, average=True)
        return tx.init(shards)

    return jax.jit(jax.shard_map(
        init, mesh=mesh, in_specs=(P(),), out_specs=opt_specs,
        check_vma=False))(params)
