"""Optimizer-pass machinery: hand-fused steps and the sharded apply.

``fused_adam_step`` computes mu/nu/bias-correction/param-new in ONE
elementwise expression per leaf — the best case a fused (XLA- or
Pallas-lowered) optimizer pass can reach, vs optax.adam's chain of
per-transform tree passes. Numerics validated bit-close to optax
(max |Δparam| ≈ 1e-7 after 5 steps on the tiny llama config; the CPU
validation lives alongside the A/B in examples/mfu_experiments.py).
Shared by bench.py's ``fused_adam`` train variant and the MFU harness
so the validated math exists exactly once.

``make_sharded_apply`` splits an optax transformation into per-leaf
jitted partial updates for the PS train step's tail overlap
(BYTEPS_SHARDED_APPLY): UPDATE(k) is issued from the
completion-ordered drain the moment leaf k's pull lands, overlapping
PULL(k+1) — the worker-side form of "Automatic Cross-Replica Sharding
of Weight Update in Data-Parallel Training" (PAPERS.md), where the
weight update decomposes cleanly per shard. Transforms that are NOT
per-leaf separable (global-norm clipping, masked/multi-transform
label trees) are detected by a numeric probe at build time and the
caller falls back to the fused apply.

Reference context: the reference leaves optimizer fusion to the
framework (torch fused adam etc.); here it is an A/B lever for the
"optimizer pass" suspect in docs/performance.md's ceiling analysis.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp


def fused_adam_step(loss_fn, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                    mu_dtype=jnp.bfloat16):
    """Build ``(init, step)`` for a fully hand-fused adam train step.

    ``loss_fn(params, batch) -> scalar``; ``step(params, opt_state,
    batch) -> (params, opt_state, loss)`` with every per-leaf update in
    a single fused expression. ``mu_dtype=bfloat16`` halves the first
    moment's HBM traffic (matching the bench's optax baseline); nu
    stays f32 (variance needs the range).
    """

    def init(params):
        return {"mu": jax.tree.map(
                    lambda p: jnp.zeros(p.shape, mu_dtype), params),
                "nu": jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params),
                "count": jnp.zeros((), jnp.int32)}

    def step(p, o, batch):
        loss, g = jax.value_and_grad(lambda q: loss_fn(q, batch))(p)
        c = o["count"] + 1
        cf = c.astype(jnp.float32)
        bc1, bc2 = 1.0 - b1 ** cf, 1.0 - b2 ** cf

        def leaf(pl, m, v, gl):
            gf = gl.astype(jnp.float32)
            m2 = b1 * m.astype(jnp.float32) + (1.0 - b1) * gf
            v2 = b2 * v + (1.0 - b2) * gf * gf
            new = pl - lr * (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            return new, m2.astype(mu_dtype), v2

        tup = jax.tree.map(leaf, p, o["mu"], o["nu"], g)
        is_t = lambda x: isinstance(x, tuple)  # noqa: E731
        return (jax.tree.map(lambda x: x[0], tup, is_leaf=is_t),
                {"mu": jax.tree.map(lambda x: x[1], tup, is_leaf=is_t),
                 "nu": jax.tree.map(lambda x: x[2], tup, is_leaf=is_t),
                 "count": c}, loss)

    return init, step


# --------------------------------------------------------------------- #
# sharded optimizer apply (BYTEPS_SHARDED_APPLY)
# --------------------------------------------------------------------- #


class ShardedApply:
    """Per-leaf partial updates over one optax transformation.

    Built by :func:`make_sharded_apply` (which verifies per-leaf
    separability first — use it, not this constructor). The optimizer
    state is analysed once into nodes that mirror the params tree
    ("param nodes": adam's mu/nu, momentum traces — sliced per leaf)
    and nodes that don't (shared scalars like adam's count — passed to
    every leaf update, never donated, identical across leaves by
    separability). ``apply_leaf`` runs the whole transform chain on a
    single leaf with the param and its param-node state slices donated;
    ``merge`` reassembles the full optimizer state from the per-leaf
    results.
    """

    def __init__(self, tx, params_treedef, state_top_treedef,
                 node_kinds: List[bool], donate: bool = True):
        self._ptd = params_treedef
        self._std = state_top_treedef
        self._kinds = node_kinds

        def leaf_update(param, param_parts, shared_parts, grad):
            nodes, pi, si = [], 0, 0
            for is_param in self._kinds:
                if is_param:
                    nodes.append(param_parts[pi])
                    pi += 1
                else:
                    nodes.append(shared_parts[si])
                    si += 1
            state_i = jax.tree.unflatten(self._std, nodes)
            import optax
            updates, new_state = tx.update(grad, state_i, param)
            new_param = optax.apply_updates(param, updates)
            out_nodes = self._std.flatten_up_to(new_state)
            n_pparts = [n for n, k in zip(out_nodes, self._kinds) if k]
            n_shared = [n for n, k in zip(out_nodes, self._kinds) if not k]
            return new_param, n_pparts, n_shared

        # donate the param and its param-node state slices (per-leaf
        # buffers); shared scalars are read by EVERY leaf update, so
        # donating them would hand leaf 0 the buffer leaf 1 still needs
        self._jit = jax.jit(leaf_update,
                            donate_argnums=(0, 1) if donate else ())
        # non-donating twin for the cross-barrier carried drain: a
        # carried leaf's base param is ALSO the value the previous step
        # returned to the caller (it rides the next forward while its
        # update is still in flight), so donating it would invalidate a
        # buffer the user's tree still references
        self._jit_keep = jax.jit(leaf_update)

    # -- state plumbing ------------------------------------------------ #

    def begin(self, opt_state) -> "_ShardedRound":
        """Pre-flatten the state ONCE for a whole round of per-leaf
        applies. ``apply_leaf`` below re-flattens per call — O(leaves²)
        per step for the drain's hot loop — so the train step's
        completion-ordered drain goes through a round instead."""
        return _ShardedRound(self, opt_state)

    def slice_leaf(self, opt_state, i: int) -> Tuple[list, list]:
        """(param_parts, shared_parts) views of ``opt_state`` for params
        leaf ``i`` — no copies, just tree surgery."""
        return _ShardedRound(self, opt_state).slice(i)

    def apply_leaf(self, param_leaf, opt_state, i: int, grad_leaf):
        """One leaf's full update chain: returns
        ``(new_param_leaf, (param_parts_i, shared_parts_i))``. Issue it
        the moment leaf ``i``'s gradient lands; jax dispatch is async,
        so the update computes while later pulls are still in flight.
        Convenience form (re-flattens the state per call) — hot loops
        use ``begin(opt_state)`` + ``round.apply``."""
        return _ShardedRound(self, opt_state).apply(param_leaf, i,
                                                    grad_leaf)

    def apply_with(self, param_leaf, pparts, shared, grad_leaf):
        """Explicit-base apply: update from caller-supplied
        ``(param_parts, shared_parts)`` instead of slicing a live
        opt_state. The cross-barrier carried drain needs this — when a
        tail leaf's step-k gradient lands AFTER step k+1 has begun, its
        base state is the snapshot captured at step k (the live
        opt_state has moved on), so the carry hands that snapshot back
        in. Returns ``(new_param_leaf, (param_parts, shared_parts))``
        like ``_ShardedRound.apply``. Never donates: the base buffers
        are shared with the caller's (stale) params/opt_state trees."""
        new_p, n_pparts, n_shared = self._jit_keep(param_leaf, pparts,
                                                   shared, grad_leaf)
        return new_p, (n_pparts, n_shared)

    def merge(self, opt_state_template, results: List[Tuple[list, list]]):
        """Reassemble the full optimizer state from every leaf's
        ``(param_parts, shared_parts)``. ``opt_state_template`` supplies
        only the tree STRUCTURE (its buffers may already be donated).
        Shared nodes are taken from leaf 0 — separability (verified at
        build) means every leaf computed the same value."""
        nodes, pi, si = [], 0, 0
        for is_param in self._kinds:
            if is_param:
                nodes.append(jax.tree.unflatten(
                    self._ptd, [r[0][pi] for r in results]))
                pi += 1
            else:
                nodes.append(results[0][1][si])
                si += 1
        return jax.tree.unflatten(self._std, nodes)


class _ShardedRound:
    """One round's pre-flattened view of the optimizer state: the
    param-shaped nodes' leaf lists and the shared scalars, computed
    once, indexed per leaf — the drain's per-leaf work drops from
    O(leaves) tree traversal to O(param nodes) list indexing."""

    __slots__ = ("_sa", "_pnode_leaves", "_shared")

    def __init__(self, sa: ShardedApply, opt_state):
        nodes = sa._std.flatten_up_to(opt_state)
        self._sa = sa
        self._pnode_leaves = [jax.tree.leaves(nd)
                              for nd, k in zip(nodes, sa._kinds) if k]
        self._shared = [nd for nd, k in zip(nodes, sa._kinds) if not k]

    def slice(self, i: int) -> Tuple[list, list]:
        return [pl[i] for pl in self._pnode_leaves], list(self._shared)

    def apply(self, param_leaf, i: int, grad_leaf):
        pparts, shared = self.slice(i)
        new_p, n_pparts, n_shared = self._sa._jit(param_leaf, pparts,
                                                  shared, grad_leaf)
        return new_p, (n_pparts, n_shared)


def _probe_separable(tx, params_treedef) -> bool:
    """Numeric separability probe on tiny surrogate params sharing the
    real tree structure: the fused ``tx.update`` restricted to each leaf
    must equal the per-leaf update built from sliced state. Global-norm
    clipping, masked label trees and friends either mismatch or raise —
    both mean "not separable"."""
    import numpy as np
    import optax

    n = params_treedef.num_leaves
    rng = np.random.RandomState(0)
    pp = jax.tree.unflatten(params_treedef, [
        jnp.asarray(rng.randn(2, 3).astype(np.float32)) for _ in range(n)])
    gg = jax.tree.unflatten(params_treedef, [
        jnp.asarray(rng.randn(2, 3).astype(np.float32)) for _ in range(n)])
    state0 = tx.init(pp)
    fused_u, fused_s = tx.update(gg, state0, pp)
    std, kinds = _analyze_state(state0, params_treedef)
    if std is None:
        return False
    sa = ShardedApply(tx, params_treedef, std, kinds, donate=False)
    p_leaves = jax.tree.leaves(pp)
    g_leaves = jax.tree.leaves(gg)
    fu_leaves = jax.tree.leaves(
        jax.tree.map(optax.apply_updates, pp, fused_u))
    results = []
    for i in range(n):
        new_p, parts = sa.apply_leaf(p_leaves[i], state0, i, g_leaves[i])
        if not np.allclose(np.asarray(new_p), np.asarray(fu_leaves[i]),
                           rtol=1e-6, atol=1e-7):
            return False
        results.append(parts)
    merged = sa.merge(state0, results)
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(fused_s)):
        if np.asarray(a).shape != np.asarray(b).shape or \
                not np.allclose(np.asarray(a), np.asarray(b),
                                rtol=1e-6, atol=1e-7):
            return False
    return True


def _analyze_state(opt_state, params_treedef):
    """Split the state's top-level nodes into params-shaped trees vs
    shared leaves. Returns (top_treedef, kinds) or (None, None) when the
    layout can't be decomposed (a node partially overlaps the params
    structure)."""
    def is_param_node(x):
        try:
            return jax.tree.structure(x) == params_treedef
        except Exception:  # noqa: BLE001 - unflattenable exotic node
            return False

    try:
        top = jax.tree.structure(opt_state, is_leaf=is_param_node)
        nodes = top.flatten_up_to(opt_state)
    except Exception:  # noqa: BLE001
        return None, None
    kinds = [is_param_node(nd) for nd in nodes]
    # a non-param node containing arrays the size of params would be
    # silently shared (wrong); require non-param nodes to be single
    # leaves (scalar counts, hyperparams), not containers
    for nd, k in zip(nodes, kinds):
        if not k and jax.tree.structure(nd).num_leaves not in (0, 1):
            return None, None
    return top, kinds


# --------------------------------------------------------------------- #
# shard-mapped optimizer apply (BYTEPS_LOCAL_SHARD_EXPORT)
# --------------------------------------------------------------------- #


class LeafGather:
    """Cached jitted all-gathers: flat P(axis)-sharded arrays back to
    replicated leaves shaped/typed like the given templates. One jit per
    ((shape, dtype), ...) signature — two leaves can share a shard shape
    but trim to different sizes (padding), so the trim is part of the
    cache key, not data. Shared by :class:`ShardApply` (params + state
    nodes after the shard update) and the train step's gradient-gather
    fallback (shard-exported leaves whose transform cannot shard)."""

    def __init__(self, mesh, axis: str):
        self._mesh = mesh
        self._axis = axis
        self._cache: dict = {}

    def __call__(self, shards, templates):
        from jax.sharding import PartitionSpec as P

        meta = tuple((tuple(t.shape), jnp.dtype(t.dtype).name)
                     for t in templates)
        fn = self._cache.get(meta)
        if fn is None:
            axis = self._axis

            def body(flats):
                outs = []
                for sh, (shape, dtype) in zip(flats, meta):
                    full = jax.lax.all_gather(
                        sh, axis_name=axis, axis=0,
                        tiled=False).reshape(-1)
                    size = 1
                    for d in shape:
                        size *= d
                    outs.append(full[:size].reshape(shape).astype(dtype))
                return tuple(outs)

            fn = jax.jit(jax.shard_map(
                body, mesh=self._mesh, in_specs=(P(axis),),
                out_specs=P(), check_vma=False))
            self._cache[meta] = fn
        return fn(tuple(shards))


class ShardApply:
    """Per-leaf update over 1/N shards, compiled as a shard_map.

    The locality-sharded import path lands each leaf's PS-aggregated
    gradient as a sharded jax.Array (shard k on the device that owns
    it); this class runs the optimizer update ON THE SHARD ONLY — each
    device slices its 1/N of the (replicated) param and param-shaped
    state nodes by ``axis_index``, applies the full transform chain to
    the slice, and emits sharded results — then a separate jitted
    all-gather (:meth:`gather`) rebuilds replicated params and state so
    the step's external contract (replicated trees in, replicated trees
    out) is unchanged. Per-device H2D and update FLOPs divide by N.

    Built by :func:`make_shard_apply`, which layers a SHARD-granularity
    separability probe on top of the per-leaf one: per-leaf separable
    transforms that mix elements WITHIN a leaf (block-norm clipping)
    pass the leaf probe but fail here and fall back to the full-leaf
    sharded apply. State plumbing (slice/merge) is shared with the base
    :class:`ShardedApply` so mixed rounds — some leaves sharded, some
    whole — merge through one code path."""

    def __init__(self, tx, base: ShardedApply, mesh, axis: str):
        from jax.sharding import PartitionSpec as P

        self.base = base
        self._axis = axis
        std, kinds = base._std, base._kinds

        def leaf_update_shard(param, pparts, shared, grad_shard):
            # inside shard_map: grad_shard is THIS device's flat shard;
            # param/pparts are replicated and sliced to the matching
            # subrange — the same padded layout as ops.push_pull.
            # shard_layout, so shard k of the gradient meets shard k of
            # the param bit-for-bit
            n = jax.lax.axis_size(axis)
            shard_len = grad_shard.shape[0]
            idx = jax.lax.axis_index(axis)

            def slice_shard(x):
                flat = x.reshape(-1)
                pad = shard_len * n - flat.shape[0]
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                return jax.lax.dynamic_slice(flat, (idx * shard_len,),
                                             (shard_len,))

            p_sh = slice_shard(param)
            pparts_sh = [slice_shard(x) for x in pparts]
            nodes, pi, si = [], 0, 0
            for is_param in kinds:
                if is_param:
                    nodes.append(pparts_sh[pi])
                    pi += 1
                else:
                    nodes.append(shared[si])
                    si += 1
            state_i = jax.tree.unflatten(std, nodes)
            import optax
            updates, new_state = tx.update(grad_shard, state_i, p_sh)
            new_p = optax.apply_updates(p_sh, updates)
            out_nodes = std.flatten_up_to(new_state)
            n_pparts = [nd for nd, k in zip(out_nodes, kinds) if k]
            n_shared = [nd for nd, k in zip(out_nodes, kinds) if not k]
            return new_p, n_pparts, n_shared

        # no donation: replicated inputs cannot alias sharded outputs,
        # and the donation warning would fire per leaf per step
        self._jit = jax.jit(jax.shard_map(
            leaf_update_shard, mesh=mesh,
            in_specs=(P(), P(), P(), P(axis)),
            out_specs=(P(axis), P(axis), P()), check_vma=False))

        self._gatherer = LeafGather(mesh, axis)

    def apply(self, param_leaf, pparts, shared, grad_sharded):
        """One leaf's shard update. ``grad_sharded`` is the flat padded
        P(axis)-sharded gradient; ``pparts``/``shared`` come from the
        shared ``ShardedApply`` round's ``slice(i)``. Returns
        ``(new_param_shard, new_pparts_shards, new_shared)`` — the
        first two still sharded (feed :meth:`gather`)."""
        return self._jit(param_leaf, pparts, shared, grad_sharded)

    def gather(self, shards, templates):
        """All-gather flat shards back to replicated leaves shaped/typed
        like ``templates``; returns a tuple aligned with ``shards``.
        The BROADCAST half of the hierarchical exchange — dispatched
        asynchronously, so the gather of leaf k overlaps the PULL of
        leaf k+1."""
        return self._gatherer(shards, templates)


def _probe_shard_separable(tx, params_treedef, num_shards: int) -> bool:
    """SHARD-granularity separability probe: the per-leaf update
    restricted to each padded 1/N subrange must equal the subrange of
    the full-leaf update. Per-LEAF separable transforms that mix
    elements within a leaf — block-RMS/block-norm scaling — pass the
    base probe but must fail here (a shard's RMS is not the leaf's).
    Emulated eagerly on tiny surrogates with plain slicing, no mesh."""
    import numpy as np
    import optax

    from ..ops.push_pull import shard_layout

    n_leaves = params_treedef.num_leaves
    rng = np.random.RandomState(1)
    pp = jax.tree.unflatten(params_treedef, [
        jnp.asarray(rng.randn(2, 3).astype(np.float32))
        for _ in range(n_leaves)])
    gg = jax.tree.unflatten(params_treedef, [
        jnp.asarray(rng.randn(2, 3).astype(np.float32))
        for _ in range(n_leaves)])
    state0 = tx.init(pp)
    std, kinds = _analyze_state(state0, params_treedef)
    if std is None:
        return False
    nodes = std.flatten_up_to(state0)
    pnode_leaves = [jax.tree.leaves(nd)
                    for nd, k in zip(nodes, kinds) if k]
    shared = [nd for nd, k in zip(nodes, kinds) if not k]
    full_u, full_s = tx.update(gg, state0, pp)
    full_new = jax.tree.map(optax.apply_updates, pp, full_u)
    fn_leaves = jax.tree.leaves(full_new)
    p_leaves, g_leaves = jax.tree.leaves(pp), jax.tree.leaves(gg)

    def pad_flat(x, total):
        flat = np.asarray(x).reshape(-1)
        return np.pad(flat, (0, total - flat.size))

    shard_len, _ = shard_layout(p_leaves[0].size, num_shards)
    total = shard_len * num_shards
    for i in range(n_leaves):
        pf = pad_flat(p_leaves[i], total)
        gf = pad_flat(g_leaves[i], total)
        parts_f = [pad_flat(pl[i], total) for pl in pnode_leaves]
        got = np.empty(total, np.float32)
        for k in range(num_shards):
            lo, hi = k * shard_len, (k + 1) * shard_len
            nds, pi, si = [], 0, 0
            for is_param in kinds:
                if is_param:
                    nds.append(jnp.asarray(parts_f[pi][lo:hi]))
                    pi += 1
                else:
                    nds.append(shared[si])
                    si += 1
            state_i = jax.tree.unflatten(std, nds)
            try:
                u, _ = tx.update(jnp.asarray(gf[lo:hi]), state_i,
                                 jnp.asarray(pf[lo:hi]))
            except Exception:  # noqa: BLE001 - shape-dependent: fused
                return False
            got[lo:hi] = np.asarray(
                optax.apply_updates(jnp.asarray(pf[lo:hi]), u))
        want = np.asarray(fn_leaves[i]).reshape(-1)
        if not np.array_equal(got[:want.size], want):
            return False
    return True


def make_shard_apply(tx, params, opt_state, mesh, axis: str,
                     num_shards: int,
                     base: Optional[ShardedApply] = None
                     ) -> Optional["ShardApply"]:
    """Build the shard-mapped per-leaf apply for the locality-sharded
    import path, or None when the transform cannot decompose to shard
    granularity (the caller then gathers gradients and keeps the
    full-leaf apply). Requires a prior :func:`make_sharded_apply`
    success (``base``); additionally verifies that every param-shaped
    state leaf matches its param leaf's SHAPE on the real trees (a
    factored/covariance state would slice the wrong subranges) and that
    the update is shard-separable (see :func:`_probe_shard_separable`).
    """
    if base is None:
        base = make_sharded_apply(tx, params, opt_state, donate=False)
    if base is None:
        return None
    p_leaves = jax.tree.leaves(params)
    try:
        nodes = base._std.flatten_up_to(opt_state)
    except Exception:  # noqa: BLE001 - structure drifted: fused
        return None
    for nd, k in zip(nodes, base._kinds):
        if not k:
            continue
        for pl, sl in zip(p_leaves, jax.tree.leaves(nd)):
            if tuple(getattr(sl, "shape", ())) != tuple(pl.shape):
                return None
    try:
        if not _probe_shard_separable(tx, base._ptd, num_shards):
            return None
    except Exception:  # noqa: BLE001 - probe failures mean "no shard"
        return None
    try:
        return ShardApply(tx, base, mesh, axis)
    except Exception:  # noqa: BLE001 - build failures mean "no shard"
        return None


def make_sharded_apply(tx, params, opt_state,
                       donate: bool = True) -> Optional[ShardedApply]:
    """Build per-leaf partial updates for ``tx``, or return None when
    the transform chain is not per-leaf separable (the caller then keeps
    the fused apply).

    ``params`` / ``opt_state`` fix the REAL tree structures (the probe
    itself runs on tiny surrogates, so a large model costs nothing to
    verify). Separability is verified numerically, not assumed from the
    transform names: anything whose update mixes leaves — global-norm
    clipping, cross-leaf masking — fails the probe and falls back.
    """
    params_treedef = jax.tree.structure(params)
    std, kinds = _analyze_state(opt_state, params_treedef)
    if std is None:
        return None
    try:
        if not _probe_separable(tx, params_treedef):
            return None
    except Exception:  # noqa: BLE001 - probe failures mean "fused"
        return None
    # structural round-trip on the REAL state: slice + merge must
    # reproduce it exactly (guards probe/real structure divergence,
    # e.g. shape-dependent factored states)
    try:
        sa = ShardedApply(tx, params_treedef, std, kinds, donate=donate)
        n = params_treedef.num_leaves
        results = [sa.slice_leaf(opt_state, i) for i in range(n)]
        merged = sa.merge(opt_state, results)
        if jax.tree.structure(merged) != jax.tree.structure(opt_state):
            return None
        for a, b in zip(jax.tree.leaves(merged),
                        jax.tree.leaves(opt_state)):
            if a is not b:
                return None
    except Exception:  # noqa: BLE001
        return None
    return sa
