"""Hand-fused optimizer steps for MFU experiments and the bench.

``fused_adam_step`` computes mu/nu/bias-correction/param-new in ONE
elementwise expression per leaf — the best case a fused (XLA- or
Pallas-lowered) optimizer pass can reach, vs optax.adam's chain of
per-transform tree passes. Numerics validated bit-close to optax
(max |Δparam| ≈ 1e-7 after 5 steps on the tiny llama config; the CPU
validation lives alongside the A/B in examples/mfu_experiments.py).
Shared by bench.py's ``fused_adam`` train variant and the MFU harness
so the validated math exists exactly once.

Reference context: the reference leaves optimizer fusion to the
framework (torch fused adam etc.); here it is an A/B lever for the
"optimizer pass" suspect in docs/performance.md's ceiling analysis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fused_adam_step(loss_fn, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                    mu_dtype=jnp.bfloat16):
    """Build ``(init, step)`` for a fully hand-fused adam train step.

    ``loss_fn(params, batch) -> scalar``; ``step(params, opt_state,
    batch) -> (params, opt_state, loss)`` with every per-leaf update in
    a single fused expression. ``mu_dtype=bfloat16`` halves the first
    moment's HBM traffic (matching the bench's optax baseline); nu
    stays f32 (variance needs the range).
    """

    def init(params):
        return {"mu": jax.tree.map(
                    lambda p: jnp.zeros(p.shape, mu_dtype), params),
                "nu": jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params),
                "count": jnp.zeros((), jnp.int32)}

    def step(p, o, batch):
        loss, g = jax.value_and_grad(lambda q: loss_fn(q, batch))(p)
        c = o["count"] + 1
        cf = c.astype(jnp.float32)
        bc1, bc2 = 1.0 - b1 ** cf, 1.0 - b2 ** cf

        def leaf(pl, m, v, gl):
            gf = gl.astype(jnp.float32)
            m2 = b1 * m.astype(jnp.float32) + (1.0 - b1) * gf
            v2 = b2 * v + (1.0 - b2) * gf * gf
            new = pl - lr * (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            return new, m2.astype(mu_dtype), v2

        tup = jax.tree.map(leaf, p, o["mu"], o["nu"], g)
        is_t = lambda x: isinstance(x, tuple)  # noqa: E731
        return (jax.tree.map(lambda x: x[0], tup, is_leaf=is_t),
                {"mu": jax.tree.map(lambda x: x[1], tup, is_leaf=is_t),
                 "nu": jax.tree.map(lambda x: x[2], tup, is_leaf=is_t),
                 "count": c}, loss)

    return init, step
