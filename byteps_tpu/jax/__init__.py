"""byteps_tpu.jax — the framework adapter.

The reference ships per-framework adapters (byteps/{torch,tensorflow,mxnet})
whose common surface is: a DistributedOptimizer that intercepts gradients and
push_pulls them before the update, broadcast of initial parameters/objects,
and rank/size introspection (reference: byteps/torch/__init__.py:37-293).
This module is the single first-class JAX adapter (SURVEY.md §7): the
optimizer wrapper is an optax gradient transformation, the gradient hook is
functional (grads flow through ``update``), and everything composes with
pjit/shard_map instead of autograd hooks.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.state import get_state
from ..ops.push_pull import psum_tree, broadcast
from ..parallel.mesh import DP_AXIS

__all__ = [
    "DistributedOptimizer",
    "distributed_optimizer",
    "broadcast_parameters",
    "broadcast_object",
]


def _psum_transform(axis: str, average: bool) -> optax.GradientTransformation:
    """Stateless cross-replica gradient sum as an optax transformation."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(grads, state, params=None):
        del params
        return psum_tree(grads, axis=axis, average=average), state

    return optax.GradientTransformation(init_fn, update_fn)


def distributed_optimizer(
    tx: optax.GradientTransformation,
    axis: str = DP_AXIS,
    average: bool = True,
    backward_passes_per_step: int = 1,
    compression: Optional[dict] = None,
    params_example: Optional[Any] = None,
    min_compress_bytes: Optional[int] = None,
    lr_schedule=None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so its gradients are push_pulled across
    ``axis`` before the update — the functional equivalent of the reference's
    ``_DistributedOptimizer`` grad-accumulator hooks
    (reference: byteps/torch/__init__.py:37-216).

    Must be used inside ``shard_map``/``pjit`` with ``axis`` bound (the train
    step is compiled over the mesh). ``backward_passes_per_step`` maps to
    optax.MultiSteps, mirroring the reference's gradient accumulation
    (torch/__init__.py:85-115).

    ``compression`` is a string-kwargs dict for the codec registry (e.g.
    ``{"compressor": "onebit", "ef": "vanilla"}``, the reference's
    byteps_compressor parameter surface); it requires ``params_example`` to
    fix payload shapes, and swaps the plain psum for the compressed
    all_gather reduction with EF/momentum state carried in the optimizer
    state.
    """
    if compression is not None:
        if params_example is None:
            raise ValueError(
                "compression requires params_example (a pytree of arrays "
                "or ShapeDtypeStructs matching the gradients)")
        from ..ops.compression import compression_transform
        comm = compression_transform(params_example, compression, axis=axis,
                                     average=average,
                                     min_compress_bytes=min_compress_bytes,
                                     lr_schedule=lr_schedule)
    else:
        comm = _psum_transform(axis, average)

    wrapped = optax.chain(comm, tx)
    if backward_passes_per_step > 1:
        wrapped = optax.MultiSteps(wrapped, every_k_schedule=backward_passes_per_step)
    return wrapped


# Horovod-style alias matching the reference's class name.
DistributedOptimizer = distributed_optimizer


def opt_state_specs(tx: optax.GradientTransformation, params: Any,
                    axis: str = DP_AXIS) -> Any:
    """PartitionSpec pytree for ``tx.init(params)``'s state.

    Compression state (EF error, momentum residuals) is *per-replica* — each
    device corrects its own local compression loss — so those leaves shard
    over ``axis`` (each device owns its slice of the flat global array).
    Everything else (adam moments, counts) is replicated. Use together with
    ``init_opt_state`` and pass to ``make_train_step(opt_specs=...)``;
    declaring the per-replica state replicated would be a silent-corruption
    hazard on any reshard/checkpoint.
    """
    from jax.sharding import PartitionSpec as P

    shapes = jax.eval_shape(tx.init, params)

    def spec_of(path, leaf):
        keys = {getattr(k, "key", None) for k in path}
        if "compress" in keys and getattr(leaf, "ndim", 0) > 0:
            return P(axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec_of, shapes)


def init_opt_state(tx: optax.GradientTransformation, params: Any, mesh,
                   axis: str = DP_AXIS):
    """Initialize optimizer state with per-replica compression state laid
    out sharded over ``axis`` (see opt_state_specs). Returns
    (opt_state, opt_specs)."""
    from jax.sharding import PartitionSpec as P

    specs = opt_state_specs(tx, params, axis)
    init = jax.jit(jax.shard_map(
        tx.init, mesh=mesh, in_specs=(P(),), out_specs=specs,
        check_vma=False))
    return init(params), specs


def broadcast_parameters(params: Any, root_rank: int = 0,
                         axis: str = DP_AXIS) -> Any:
    """Make every device's copy of ``params`` equal to the root's.

    Reference semantics: byteps/torch/__init__.py:261-293 (zero-non-root +
    push_pull). Here: a native broadcast collective per leaf; in
    multi-worker PS mode each leaf also round-trips the DCN PS keyed by its
    tree path, so workers converge to the root worker's copy.
    """
    leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in leaves:
        name = "param/" + "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        out.append(broadcast(leaf, root_rank=root_rank, name=name,
                             axis=axis))
    return treedef.unflatten(out)


def broadcast_object(obj: Any, root_rank: int = 0, axis: str = DP_AXIS,
                     name: str = "obj") -> Any:
    """Broadcast an arbitrary picklable object from the root.

    Reference: byteps/torch/__init__.py:419-459 (cloudpickle -> byte tensor ->
    push_pull). In a single-controller JAX process all mesh devices are driven
    by the same Python, so the object is already shared; the byte-tensor round
    trip is kept for behavioral parity (it exercises the same collective path
    and matters in multi-worker PS mode). Like the reference, the payload
    LENGTH is broadcast first: each worker's pickle of its local object can
    differ in size, and the PS tier needs every worker pushing equal-sized
    buffers under one key. ``name`` disambiguates concurrent broadcasts
    (stable keys must match across workers).
    """
    buf = pickle.dumps(obj)
    nm = "bcastobj/" + name
    ln = broadcast(np.asarray([len(buf)], np.int32), root_rank=root_rank,
                   axis=axis, name=nm + "/len")
    root_len = int(np.asarray(ln)[0])
    payload = np.zeros(root_len, np.uint8)
    take = min(len(buf), root_len)
    payload[:take] = np.frombuffer(buf, np.uint8)[:take]
    out = broadcast(payload, root_rank=root_rank, axis=axis,
                    name=nm + "/payload")
    return pickle.loads(np.asarray(out).tobytes())
