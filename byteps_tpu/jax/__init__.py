"""byteps_tpu.jax — the framework adapter.

The reference ships per-framework adapters (byteps/{torch,tensorflow,mxnet})
whose common surface is: a DistributedOptimizer that intercepts gradients and
push_pulls them before the update, broadcast of initial parameters/objects,
and rank/size introspection (reference: byteps/torch/__init__.py:37-293).
This module is the single first-class JAX adapter (SURVEY.md §7): the
optimizer wrapper is an optax gradient transformation, the gradient hook is
functional (grads flow through ``update``), and everything composes with
pjit/shard_map instead of autograd hooks.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.state import get_state
from ..ops.push_pull import psum_tree, reduce_scatter_tree, all_gather_tree, broadcast
from ..parallel.mesh import DP_AXIS

__all__ = [
    "DistributedOptimizer",
    "distributed_optimizer",
    "broadcast_parameters",
    "broadcast_object",
]


def distributed_optimizer(
    tx: optax.GradientTransformation,
    axis: str = DP_AXIS,
    average: bool = True,
    backward_passes_per_step: int = 1,
    compression: Optional[Any] = None,
    named_tensors: bool = True,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so its gradients are push_pulled across
    ``axis`` before the update — the functional equivalent of the reference's
    ``_DistributedOptimizer`` grad-accumulator hooks
    (reference: byteps/torch/__init__.py:37-216).

    Must be used inside ``shard_map``/``pjit`` with ``axis`` bound (the train
    step is compiled over the mesh). ``backward_passes_per_step`` maps to
    optax.MultiSteps, mirroring the reference's gradient accumulation
    (torch/__init__.py:85-115). ``compression`` is a codec from
    byteps_tpu.ops.compression applied leaf-wise before the cross-replica
    sum (the COMPRESS/DECOMPRESS pipeline stages).
    """

    def init_fn(params):
        return tx.init(params)

    def update_fn(grads, state, params=None):
        if compression is not None:
            grads = compression.forward_tree(grads, axis=axis, average=average)
        else:
            grads = psum_tree(grads, axis=axis, average=average)
        return tx.update(grads, state, params)

    wrapped = optax.GradientTransformation(init_fn, update_fn)
    if backward_passes_per_step > 1:
        wrapped = optax.MultiSteps(wrapped, every_k_schedule=backward_passes_per_step)
    return wrapped


# Horovod-style alias matching the reference's class name.
DistributedOptimizer = distributed_optimizer


def broadcast_parameters(params: Any, root_rank: int = 0,
                         axis: str = DP_AXIS) -> Any:
    """Make every device's copy of ``params`` equal to the root's.

    Reference semantics: byteps/torch/__init__.py:261-293 (zero-non-root +
    push_pull). Here: a native broadcast collective per leaf.
    """
    return jax.tree.map(lambda p: broadcast(p, root_rank=root_rank, axis=axis),
                        params)


def broadcast_object(obj: Any, root_rank: int = 0, axis: str = DP_AXIS) -> Any:
    """Broadcast an arbitrary picklable object from the root.

    Reference: byteps/torch/__init__.py:419-459 (cloudpickle -> byte tensor ->
    push_pull). In a single-controller JAX process all mesh devices are driven
    by the same Python, so the object is already shared; the byte-tensor round
    trip is kept for behavioral parity (it exercises the same collective path
    and will matter in multi-process mode).
    """
    buf = pickle.dumps(obj)
    arr = jnp.frombuffer(np.frombuffer(buf, dtype=np.uint8), dtype=jnp.uint8)
    out = broadcast(arr, root_rank=root_rank, axis=axis)
    return pickle.loads(np.asarray(out).tobytes())
