"""On-device codec execution for the DCN PS path.

SURVEY §7's stage list specifies "COMPRESS (on-device) — the D2H moves
*compressed* bytes". The host-codec path (server/compressed.py) brings
every gradient to the host as dense f32 — 32x the wire bytes for onebit
— and compresses in numpy. This module instead runs the full
momentum -> error-feedback -> codec stack INSIDE one jitted program, so
only wire-sized payload arrays cross device->host, and the aggregated
reply crosses host->device wire-sized and is decompressed on device
(where the Pallas/XLA unpack is effectively free next to the optimizer
pass).

Wire-format parity: the payload arrays serialize to exactly the
ops/compression/host.py layouts — the C++ server cannot tell which
worker tier produced a push. Onebit uses the portable u32-LE bit layout
(codecs.py's jnp path; the Pallas sublane-folded layout is NOT wire
format). Randomk/dithering counter-RNG streams are bit-exact across
np/jnp (tests/test_compression.py), so the server's homomorphic randomk
fast path keeps working.

The transport is the same priority-scheduled pipeline as the host path
(PartitionTask with a prebuilt wire, scheduler.submit_wire): per-4MB
partitions, per-key serialization, credit admission, PUSH/PULL overlap.
Reference splice point: operations.cc:199-204 (COMPRESS/DECOMPRESS as
scheduled-queue stages); here the COMPRESS stage is the XLA program
itself.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import (
    DataType, RequestType, TensorContext, get_command_type,
)
from ..ops.compression import make_compressor
from ..ops.compression.codecs import (
    Codec, DitheringCodec, OnebitCodec, RandomkCodec, TopkCodec,
)
from ..ops.compression.feedback import CompressorStack

CMD_COMP_F32 = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                                DataType.FLOAT32)
CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


def _portable(codec: Codec) -> Codec:
    """Wire-layout codec variant: onebit's Pallas kernel uses a
    sublane-folded word order that is not the wire format, so the PS
    tier always runs the portable jnp path for it."""
    import dataclasses
    if isinstance(codec, OnebitCodec) and codec.use_pallas:
        return dataclasses.replace(codec, use_pallas=False)
    return codec


def payload_to_wire(codec: Optional[Codec], payload: Dict[str, np.ndarray],
                    ) -> np.ndarray:
    """Serialize one partition's (host-fetched) payload arrays into the
    host.py wire layout. ``codec=None`` = dense partition (raw f32)."""
    if codec is None:
        return np.ascontiguousarray(payload["raw"]).view(np.uint8)
    if isinstance(codec, OnebitCodec):
        bits = np.ascontiguousarray(payload["bits"], np.uint32)
        scale = np.float32(payload["scale"])
        return np.frombuffer(bits.tobytes() + scale.tobytes(), np.uint8)
    if isinstance(codec, (TopkCodec, RandomkCodec)):
        idx = np.ascontiguousarray(payload["indices"], np.int32)
        val = np.ascontiguousarray(payload["values"], np.float32)
        if isinstance(codec, TopkCodec):
            # the host wire writes topk indices ASCENDING (host.py
            # HostTopk.select); lax.top_k emits |x|-descending order.
            # Randomk stays in RNG generation order — the server re-draws
            # the same stream for its homomorphic fast path.
            order = np.argsort(idx, kind="stable")
            idx = idx[order]
            val = val[order]
        return np.frombuffer(idx.tobytes() + val.tobytes(), np.uint8)
    if isinstance(codec, DitheringCodec):
        lv = np.ascontiguousarray(payload["levels"], np.int8)
        norm = np.float32(payload["norm"])
        return np.frombuffer(lv.tobytes() + norm.tobytes(), np.uint8)
    raise TypeError(f"no wire serializer for {type(codec).__name__}")


def wire_to_payload(codec: Optional[Codec], n: int,
                    reply: np.ndarray) -> Dict[str, np.ndarray]:
    """Parse one partition's reply bytes into the payload-array dict the
    jnp codec's decompress consumes (zero-copy views where possible)."""
    raw = np.frombuffer(reply, np.uint8)
    if codec is None:
        return {"raw": raw.view(np.float32)}
    if isinstance(codec, OnebitCodec):
        return {"bits": raw[:-4].view(np.uint32),
                "scale": raw[-4:].view(np.float32)[0]}
    if isinstance(codec, (TopkCodec, RandomkCodec)):
        k = codec.k
        return {"indices": raw[: 4 * k].view(np.int32),
                "values": raw[4 * k:].view(np.float32)}
    if isinstance(codec, DitheringCodec):
        return {"levels": raw[:n].view(np.int8),
                "norm": raw[n: n + 4].view(np.float32)[0]}
    raise TypeError(f"no wire parser for {type(codec).__name__}")


class _PackSpec:
    """Static packing plan for a payload pytree: one flat buffer per
    dtype, with per-leaf (bucket, offset, size, shape) slots. Built once
    per jitted-fn cache key from ``jax.eval_shape`` of the compress
    program, so the slot order is exactly the tree-flatten order both
    the device and host sides use."""

    def __init__(self, treedef, leaf_meta):
        self.treedef = treedef
        self.leaf_meta = leaf_meta          # [(dtype_name, shape, size, off)]

    @classmethod
    def from_structs(cls, payload_structs):
        flat, treedef = jax.tree_util.tree_flatten(payload_structs)
        offsets: Dict[str, int] = {}
        meta = []
        for s in flat:
            dt = np.dtype(s.dtype).name
            size = int(np.prod(s.shape)) if s.shape else 1
            off = offsets.get(dt, 0)
            offsets[dt] = off + size
            meta.append((dt, tuple(s.shape), size, off))
        return cls(treedef, meta)

    def pack(self, payloads) -> Dict[str, jnp.ndarray]:
        """In-jit: payload pytree -> {dtype: flat buffer}."""
        flat = self.treedef.flatten_up_to(payloads)
        buckets: Dict[str, list] = {}
        for (dt, _, _, _), leaf in zip(self.leaf_meta, flat):
            buckets.setdefault(dt, []).append(jnp.ravel(leaf))
        return {dt: (v[0] if len(v) == 1 else jnp.concatenate(v))
                for dt, v in buckets.items()}

    def unpack_np(self, packed: Dict[str, np.ndarray]):
        """Host: fetched {dtype: buffer} -> payload pytree of np views."""
        leaves = []
        for dt, shape, size, off in self.leaf_meta:
            v = packed[dt][off: off + size].reshape(shape)
            leaves.append(v)
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def pack_np(self, payloads) -> Dict[str, np.ndarray]:
        """Host: reply payload pytree (np views) -> {dtype: buffer} for
        a couple of H2D uploads. Always returns buffers INDEPENDENT of
        the inputs (np.concatenate copies; the single-bucket case copies
        explicitly): the views may alias arena reply slots that are
        recycled the moment the caller releases them, which must not
        race the async upload."""
        flat = self.treedef.flatten_up_to(payloads)
        buckets: Dict[str, list] = {}
        for (dt, _, _, _), leaf in zip(self.leaf_meta, flat):
            buckets.setdefault(dt, []).append(
                np.ravel(np.asarray(leaf, dtype=dt)))
        return {dt: np.concatenate(v) if len(v) > 1 else v[0].copy()
                for dt, v in buckets.items()}

    def unpack_jnp(self, packed: Dict[str, jnp.ndarray]):
        """In-jit: uploaded {dtype: buffer} -> payload pytree."""
        leaves = []
        for dt, shape, size, off in self.leaf_meta:
            leaves.append(packed[dt][off: off + size].reshape(shape))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    @staticmethod
    def for_payloads(plans: List["_LeafPlan"]):
        """Payload structure via eval_shape of a structural twin of the
        compress program (leaf VALUES don't matter, only shapes)."""
        payload_structs = []
        for p in plans:
            pl = []
            for (q, stack, st) in zip(p.ctx.partitions, p.stacks, p.states):
                pn = q.length // 4
                if stack is None:
                    pl.append({"raw": jax.ShapeDtypeStruct((pn,),
                                                           jnp.float32)})
                    continue
                payload, _ = jax.eval_shape(
                    lambda x, s, stk=stack: stk.compress(x, s, 0),
                    jax.ShapeDtypeStruct((pn,), jnp.float32),
                    jax.tree.map(
                        lambda a: jax.ShapeDtypeStruct(
                            jnp.shape(a), jnp.result_type(a)), st))
                pl.append(payload)
            payload_structs.append(pl)
        return _PackSpec.from_structs(payload_structs)


class _LeafPlan:
    """Per-tensor device-compression plan: partition layout, per-partition
    device codec stacks + EF/momentum state, and the host base codecs
    used only for server kwargs/wire sizes."""

    def __init__(self, name: str, ctx: TensorContext, kwargs: Dict[str, str],
                 min_compress_bytes: int):
        from ..ops.compression.host import make_host_codec

        self.name = name
        self.ctx = ctx
        self.n = (ctx.partitions[-1].offset + ctx.partitions[-1].length) // 4
        # ef/momentum run on device (the server mirrors only the base
        # codec); index_coding is a host-tier wire option — the device
        # payload stays dense int8 (XLA needs static shapes), so the
        # server must not be told to expect the varint wire
        if kwargs.get("index_coding", "dense") != "dense":
            from ..utils.logging import log
            log.warning(
                "compression index_coding=%r is a host-tier wire option; "
                "the device tier ships dense int8 levels (XLA static "
                "shapes). Pass device_compress=False to make_ps_train_step "
                "to use the coded sparse wire.", kwargs["index_coding"])
        base_kwargs = {k: v for k, v in kwargs.items()
                       if k not in ("ef", "momentum", "momentum_mu",
                                    "index_coding")}
        self.stacks: List[Optional[CompressorStack]] = []
        self.codecs: List[Optional[Codec]] = []   # portable base codecs
        self.host_base = []                       # kwargs_wire providers
        self.states: List[Dict[str, Any]] = []    # device EF/momentum state
        for p in ctx.partitions:
            pn = p.length // 4
            if p.length < max(min_compress_bytes, 8):
                self.stacks.append(None)
                self.codecs.append(None)
                self.host_base.append(None)
                self.states.append({})
            else:
                stack = make_compressor(kwargs, pn)
                stack = CompressorStack(codec=_portable(stack.codec),
                                        use_ef=stack.use_ef,
                                        momentum_mu=stack.momentum_mu)
                self.stacks.append(stack)
                self.codecs.append(stack.codec)
                self.host_base.append(make_host_codec(base_kwargs, pn))
                self.states.append(stack.init_state(pn))
        self.step = 0
        self.priority = -ctx.declared_key
        self.installed = False

    def reply_len(self, i: int) -> int:
        hb = self.host_base[i]
        return self.ctx.partitions[i].length if hb is None else \
            hb.wire_bytes()

    def wire_bytes(self) -> int:
        return sum(self.reply_len(i) for i in range(len(self.ctx.partitions)))


class DeviceCompressor:
    """Whole-tree on-device compress/decompress around the scheduled PS
    pipeline. One instance per (client, kwargs) — holds device-resident
    EF/momentum state per tensor partition across steps."""

    def __init__(self, client, num_workers: int, kwargs: Dict[str, str],
                 min_compress_bytes: int = 0):
        self.client = client
        self.num_workers = num_workers
        self.kwargs = dict(kwargs)
        self.min_compress_bytes = min_compress_bytes
        self._plans: Dict[str, _LeafPlan] = {}
        self._fns: Dict[Tuple, Tuple] = {}
        self._lock = threading.Lock()

    # ---- planning / server install ------------------------------------ #

    def plan(self, state, name: str, n_elems: int) -> _LeafPlan:
        with self._lock:
            p = self._plans.get(name)
            if p is None or p.n != n_elems:
                ctx = state.registry.init_tensor(name, n_elems * 4,
                                                 DataType.FLOAT32)
                p = _LeafPlan(name, ctx, self.kwargs,
                              self.min_compress_bytes)
                self._plans[name] = p
            return p

    def _install(self, plan: _LeafPlan) -> None:
        """Dense init-push (allocates the store + init barrier), then the
        in-band per-key codec kwargs (operations.cc:396-408)."""
        with self._lock:
            if plan.installed:
                return
            # per-partition zeros (ensure_init): the transient allocation
            # is bounded by partition_bytes, not the whole tensor
            self.client.ensure_init(plan.ctx, plan.n * 4)
            for p, hb in zip(plan.ctx.partitions, plan.host_base):
                if hb is not None:
                    self.client.comp_init(p.server, p.key, hb.kwargs_wire())
            plan.installed = True

    # ---- jitted whole-tree codec programs ------------------------------ #

    def _get_fns(self, plans: List[_LeafPlan], average: bool):
        key = (tuple((p.name, p.n) for p in plans), average)
        fns = self._fns.get(key)
        if fns is not None:
            return fns
        # static per-partition codec structure, closed over (hashable
        # frozen dataclasses); dynamic state/payloads flow as pytrees
        stacks = [p.stacks for p in plans]
        codecs = [p.codecs for p in plans]
        parts = [[(q.offset // 4, q.length // 4) for q in p.ctx.partitions]
                 for p in plans]
        nw = self.num_workers

        def compress(leaves, states, step):
            payloads, new_states = [], []
            for leaf, st_list, stk_list, part in zip(
                    leaves, states, stacks, parts):
                flat = leaf.reshape(-1).astype(jnp.float32)
                pl, ns = [], []
                for (off, pn), stack, st in zip(part, stk_list, st_list):
                    x = jax.lax.dynamic_slice_in_dim(flat, off, pn)
                    if stack is None:
                        pl.append({"raw": x})
                        ns.append(st)
                    else:
                        payload, st2 = stack.compress(x, st, step)
                        pl.append(payload)
                        ns.append(st2)
                payloads.append(pl)
                new_states.append(ns)
            return payloads, new_states

        def decompress(replies):
            flats = []
            for reps, cd_list, part in zip(replies, codecs, parts):
                chunks = []
                for payload, codec in zip(reps, cd_list):
                    if codec is None:
                        chunks.append(payload["raw"])
                    else:
                        chunks.append(codec.decompress(payload))
                flat = chunks[0] if len(chunks) == 1 \
                    else jnp.concatenate(chunks)
                if average and nw > 1:
                    flat = flat / nw
                flats.append(flat)
            return flats

        # ---- transfer packing -------------------------------------- #
        # The payload tree has 2 leaves PER PARTITION (e.g. onebit bits +
        # scale): fetching each individually costs a blocking readback,
        # and on a high-latency transport (the axon tunnel here: ~67ms
        # per round trip) the choreography dominates the round (~0.7s of
        # a 0.76s round measured; the server round is 65ms). Pack all
        # leaves into ONE buffer per dtype inside the jitted program so
        # each direction moves 1-2 arrays regardless of partition count
        # — also the right DMA shape on PCIe-attached hosts.
        spec = _PackSpec.for_payloads(plans)

        def compress_packed(leaves, states, step):
            payloads, new_states = compress(leaves, states, step)
            return spec.pack(payloads), new_states

        def decompress_packed(packed):
            return decompress(spec.unpack_jnp(packed))

        fns = (jax.jit(compress_packed, donate_argnums=(1,)),
               jax.jit(decompress_packed), spec)
        self._fns[key] = fns
        return fns

    # ---- the round-trip ------------------------------------------------ #

    def push_pull_leaves(self, state, names: List[str], leaves: List,
                         average: bool = True) -> List:
        """Compress on device, push/pull wire bytes through the priority
        pipeline, decompress the aggregate on device. ``leaves``: device
        arrays (any float dtype/shape); returns device arrays of the same
        shapes/dtypes. Blocking (the internal pipeline overlaps)."""
        # zero-size leaves carry no data: pass them through unchanged (a
        # padded 1-element plan would trace a size-1 dynamic_slice of a
        # 0-element array and crash the step at compile time)
        live = [(i, nm, lf)
                for i, (nm, lf) in enumerate(zip(names, leaves))
                if int(np.prod(lf.shape))]
        if len(live) < len(leaves):
            out = list(leaves)
            if live:
                sub = self.push_pull_leaves(
                    state, [nm for _, nm, _ in live],
                    [lf for _, _, lf in live], average)
                for (i, _, _), r in zip(live, sub):
                    out[i] = r
            return out
        plans = [self.plan(state, nm, int(np.prod(lf.shape)))
                 for nm, lf in zip(names, leaves)]
        for p in plans:
            self._install(p)
        compress_fn, decompress_fn, spec = self._get_fns(plans, average)

        states = [p.states for p in plans]
        # one compression round for the whole tree: all partitions of a
        # tensor share the round number (seeds randomk/dithering and
        # matches the server's completed_rounds in sync mode)
        steps = [p.step for p in plans]
        if len(set(steps)) != 1:
            # re-planned subset; realign on the max (server tolerates
            # skipped seeds — the round counter only seeds RNG streams)
            step0 = max(steps)
            for p in plans:
                p.step = step0
        step0 = plans[0].step
        packed, new_states = compress_fn(leaves, states, jnp.int32(step0))
        for p, ns in zip(plans, new_states):
            p.states = ns
            p.step += 1
        # ONE wire-sized buffer per payload dtype crosses device->host
        # (1-2 transfers total — the whole point of this path); the
        # per-partition payload dicts below are zero-copy views into it
        for v in packed.values():
            if hasattr(v, "copy_to_host_async"):
                v.copy_to_host_async()
        packed_np = {k: np.asarray(v) for k, v in packed.items()}
        payloads = spec.unpack_np(packed_np)

        # reply buffers check out of the persistent staging arena
        # (core/arena.py) instead of np.empty per round; leases are
        # released once pack_np below has copied the payloads out, or
        # abandoned if the round errors with pulls possibly mid-flight
        arena = getattr(state, "arena", None)
        leases: List = []
        handles = []
        try:
            for plan, pl in zip(plans, payloads):
                wires = []
                for i, (payload, codec) in enumerate(zip(pl, plan.codecs)):
                    wires.append(payload_to_wire(codec, payload))
                reply_lens = [plan.reply_len(i) for i in range(len(wires))]
                reply_bufs = None
                if arena is not None:
                    ls = [arena.checkout(f"{plan.name}:reply:{i}", rl)
                          for i, rl in enumerate(reply_lens)]
                    leases.extend(ls)
                    reply_bufs = [lease.buf for lease in ls]
                handle = state.handles.allocate(plan.name)
                state.scheduler.submit_wire(
                    plan.ctx, wires, reply_lens,
                    [CMD_F32 if c is None else CMD_COMP_F32
                     for c in plan.codecs],
                    handle, version=state.next_version(plan.name),
                    priority=plan.priority, reply_bufs=reply_bufs)
                handles.append(handle)

            replies_np = [state.handles.wait_and_clear(h.id)
                          for h in handles]
            replies = []
            for plan, reps in zip(plans, replies_np):
                parsed = []
                for i, (rep, codec) in enumerate(zip(reps, plan.codecs)):
                    pn = plan.ctx.partitions[i].length // 4
                    parsed.append(wire_to_payload(codec, pn, rep))
                replies.append(parsed)
            # mirror of the push side: host-concatenate the reply payloads
            # into one buffer per dtype (cheap memcpy) so the host->device
            # hop is 1-2 uploads, then slice them back apart inside the
            # jitted decompress. pack_np COPIES, so the arena reply slots
            # are idle from here on.
            packed_replies = spec.pack_np(replies)
        except BaseException:
            for lease in leases:
                lease.abandon()
            raise
        for lease in leases:
            lease.release()
        flats = decompress_fn(packed_replies)
        return [f.reshape(lf.shape).astype(lf.dtype)
                for f, lf in zip(flats, leaves)]
