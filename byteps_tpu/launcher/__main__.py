"""``python -m byteps_tpu.launcher <cmd...>`` — the bpslaunch entry
(reference: launcher/launch.py console script)."""

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
