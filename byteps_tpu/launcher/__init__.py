"""byteps_tpu launcher — the ``bpslaunch`` equivalent.

Single-node launcher with role dispatch (reference: launcher/launch.py):

- role ``worker``: spawn one training process per local worker
  (``BYTEPS_LOCAL_SIZE``, default 1 — on TPU a single process owns every
  local chip, so local_size>1 is only for CPU-emulation tests and host-side
  data workers), set ``BYTEPS_LOCAL_RANK/SIZE`` per child
  (reference: launch.py:155-239), pin each child to an allocated set of
  physical cores (reference NUMA allocator: launch.py:43-135), optionally
  wrap in gdb (``BYTEPS_ENABLE_GDB``, launch.py:159-162), and create the
  trace dir tree when tracing is on (launch.py:181-191).
- role ``server``: run the native DCN PS in-process
  (reference: launch.py:241-249 runs ``python3 -c 'import byteps.server'``).
- role ``scheduler``: no-op kept for launch-script parity — the reference
  needs a ps-lite rendezvous process, but byteps_tpu's transport derives
  every server address statically from DMLC_PS_ROOT_URI/PORT
  (server.client.server_addresses), so there is nothing to coordinate.

Multi-node SSH fan-out lives in ``byteps_tpu.launcher.dist``
(reference: launcher/dist_launcher.py).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional, Sequence

from ..utils.logging import log

__all__ = [
    "allocate_cpu_cores",
    "launch_workers",
    "run_role",
    "main",
]


# ------------------------------------------------------------------ #
# CPU core allocation (reference: launcher/launch.py:43-135)
# ------------------------------------------------------------------ #


def _parse_core_list(spec: str) -> List[int]:
    """Parse "0-3,8,10-11" into [0,1,2,3,8,10,11]."""
    cores: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, _, hi = part.partition("-")
            cores.extend(range(int(lo), int(hi) + 1))
        else:
            cores.append(int(part))
    return cores


def _physical_cores() -> Dict[int, List[int]]:
    """Map physical core -> [logical siblings] from sysfs topology, so
    hyperthread siblings are allocated together (the reference allocates
    sibling pairs as one unit, launch.py:60-95). Falls back to
    each-logical-is-physical when sysfs is unavailable."""
    avail = sorted(os.sched_getaffinity(0))
    seen: Dict[int, List[int]] = {}
    for cpu in avail:
        path = (f"/sys/devices/system/cpu/cpu{cpu}/topology/"
                "thread_siblings_list")
        try:
            with open(path) as f:
                siblings = _parse_core_list(f.read().strip())
        except OSError:
            siblings = [cpu]
        phys = min(siblings)
        seen.setdefault(phys, [])
        if cpu not in seen[phys]:
            seen[phys].append(cpu)
    return seen


def allocate_cpu_cores(local_size: int,
                       avail: Optional[Sequence[int]] = None) -> List[List[int]]:
    """Partition host cores into ``local_size`` affinity sets.

    Env knobs (reference names, launch.py:96-135,219-236):

    - ``BYTEPS_VISIBLE_CPU_CORES``: explicit per-worker sets separated by
      ``;`` (e.g. ``"0-3;4-7"``) — manual override, used verbatim.
    - ``BYTEPS_CPU_BLACKLIST``: comma/range list of cores never allocated.
    - ``BYTEPS_NUMA_DEFAULT_QUOTA``: max physical cores per worker
      (0 = fair share).
    - ``BYTEPS_MULTITHREADED_CPU``: when false, only the first hyperthread
      sibling of each physical core is used.

    Returns one (possibly empty) core list per local worker; an empty list
    means "don't pin".
    """
    visible = os.environ.get("BYTEPS_VISIBLE_CPU_CORES", "")
    if visible:
        sets = [_parse_core_list(s) for s in visible.split(";") if s.strip()]
        if len(sets) < local_size:
            raise ValueError(
                f"BYTEPS_VISIBLE_CPU_CORES has {len(sets)} sets for "
                f"{local_size} workers")
        return sets[:local_size]

    blacklist = set(_parse_core_list(os.environ.get("BYTEPS_CPU_BLACKLIST", "")))
    use_ht = os.environ.get("BYTEPS_MULTITHREADED_CPU", "1") not in (
        "0", "false", "False")
    quota = int(os.environ.get("BYTEPS_NUMA_DEFAULT_QUOTA", "0") or 0)

    if avail is not None:
        phys = {c: [c] for c in avail}
    else:
        phys = _physical_cores()
    units: List[List[int]] = []
    for p in sorted(phys):
        logical = [c for c in sorted(phys[p]) if c not in blacklist]
        if not use_ht:
            logical = logical[:1]
        if logical:
            units.append(logical)

    if not units or local_size <= 0:
        return [[] for _ in range(max(0, local_size))]

    share = max(1, len(units) // local_size)
    if quota:
        share = min(share, quota)
    out: List[List[int]] = []
    for i in range(local_size):
        chunk = units[i * share:(i + 1) * share]
        if not chunk:  # more workers than cores: round-robin single units
            chunk = [units[i % len(units)]]
        out.append([c for u in chunk for c in u])
    return out


# ------------------------------------------------------------------ #
# process spawning
# ------------------------------------------------------------------ #


def _child_env(local_rank: int, local_size: int) -> Dict[str, str]:
    env = dict(os.environ)
    env["BYTEPS_LOCAL_RANK"] = str(local_rank)
    env["BYTEPS_LOCAL_SIZE"] = str(local_size)
    return env


def _maybe_gdb(command: List[str]) -> List[str]:
    """Wrap in gdb for crash backtraces (reference: launch.py:159-162)."""
    if os.environ.get("BYTEPS_ENABLE_GDB", "0") in ("1", "true", "True"):
        return ["gdb", "-ex", "run", "-ex", "bt", "-batch", "--args"] + command
    return command


def _make_trace_dirs(local_size: int) -> None:
    """Pre-create per-rank trace dirs (reference: launch.py:181-191)."""
    if os.environ.get("BYTEPS_TRACE_ON", "0") in ("1", "true", "True"):
        base = os.environ.get("BYTEPS_TRACE_DIR", "./traces")
        for r in range(local_size):
            os.makedirs(os.path.join(base, str(r)), exist_ok=True)


def launch_workers(command: Sequence[str],
                   local_size: Optional[int] = None) -> int:
    """Spawn ``local_size`` copies of ``command`` with per-rank env and core
    pinning; wait for all; return the first nonzero exit code (terminating
    the rest, like the reference's process-group teardown)."""
    if local_size is None:
        local_size = int(os.environ.get("BYTEPS_LOCAL_SIZE", "1"))
    _make_trace_dirs(local_size)
    core_sets = allocate_cpu_cores(local_size)
    cmd = _maybe_gdb(list(command))

    procs: List[subprocess.Popen] = []
    try:
        for r in range(local_size):
            cores = core_sets[r]

            def preexec(cores=cores):
                if cores:
                    try:
                        os.sched_setaffinity(0, set(cores))
                    except OSError:
                        pass

            log.info("launching worker local_rank=%d cores=%s cmd=%s",
                     r, cores or "any", shlex.join(cmd))
            procs.append(subprocess.Popen(
                cmd, env=_child_env(r, local_size), preexec_fn=preexec))
    except Exception:
        # a failed spawn (fork ENOMEM, missing gdb wrapper...) must tear
        # down already-launched ranks — they would otherwise sit forever
        # in the collective init barrier waiting for the missing peers
        for q in procs:
            if q.poll() is None:
                q.kill()
        raise

    # wait in completion order, not rank order: a crashed rank must tear
    # down survivors that are blocked on it (e.g. in a collective), which
    # rank-order wait() would deadlock on. SIGTERM escalates to SIGKILL so
    # a child with a wedged TERM handler can't hang the launcher.
    import time
    rc = 0
    live = list(procs)
    kill_deadline = None
    while live:
        done = [p for p in live if p.poll() is not None]
        if not done:
            if kill_deadline is not None and time.time() > kill_deadline:
                for q in live:
                    q.kill()
                kill_deadline = time.time() + 30  # re-arm; kill is decisive
            time.sleep(0.05)
            continue
        for p in done:
            live.remove(p)
            if p.returncode != 0 and rc == 0:
                rc = p.returncode
                for q in live:
                    q.terminate()
                kill_deadline = time.time() + 10
    return rc


def run_role(command: Sequence[str]) -> int:
    """Dispatch on DMLC_ROLE (reference: launch.py:241-253)."""
    role = os.environ.get("DMLC_ROLE", "worker")
    if role == "server":
        from ..server import run_server
        return run_server()
    if role == "scheduler":
        log.info("byteps_tpu uses static rendezvous "
                 "(DMLC_PS_ROOT_URI/PORT + server index); scheduler role "
                 "is a no-op kept for launch-script parity")
        return 0
    if not command:
        print("usage: bpslaunch <training command...>", file=sys.stderr)
        return 2
    return launch_workers(command)


_USAGE = """\
usage: bpslaunch <training command...>

Launches DMLC_ROLE (worker | server | scheduler) from the environment
(reference launcher/launch.py parity):
  worker     spawn BYTEPS_LOCAL_SIZE copies of <training command> with
             NUMA/core pinning, per-rank env, optional gdb wrap
  server     run the C++ parameter server in-process
  scheduler  no-op (static rendezvous via DMLC_PS_ROOT_URI/PORT)

Key env: DMLC_ROLE, DMLC_NUM_WORKER, DMLC_NUM_SERVER, DMLC_WORKER_ID,
DMLC_PS_ROOT_URI, DMLC_PS_ROOT_PORT, BYTEPS_LOCAL_SIZE,
BYTEPS_FORCE_DISTRIBUTED. Multi-host SSH fan-out:
python -m byteps_tpu.launcher.dist --help
"""


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(_USAGE)
        return 0
    return run_role(argv)
