"""Multi-node SSH fan-out launcher (reference: launcher/dist_launcher.py).

Reads worker/server hostfiles, builds per-host commands that export the
DMLC_* topology env and run ``bpslaunch`` remotely, then fans them out over
ssh, teeing each host's output to ``sshlog/<host>.log``
(reference: dist_launcher.py:36-100). ``--dry-run`` prints the commands
instead of executing (used by tests and for operator inspection).

Usage:
    python -m byteps_tpu.launcher.dist \
        --worker-hostfile workers.txt --server-hostfile servers.txt \
        --scheduler-uri 10.0.0.1 --scheduler-port 9000 \
        -- python train.py
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
from typing import Dict, List, Optional, Sequence

from ..utils.logging import log


def read_hostfile(path: str) -> List[str]:
    """One host per line; blank lines and #-comments ignored
    (reference: dist_launcher.py:23-33)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line)
    return hosts


def _export_str(env: Dict[str, str]) -> str:
    return " ".join(f"export {k}={shlex.quote(v)};" for k, v in env.items())


def build_commands(workers: Sequence[str], servers: Sequence[str],
                   scheduler_uri: str, scheduler_port: int,
                   command: Sequence[str],
                   extra_env: Optional[Dict[str, str]] = None,
                   username: str = "") -> List[Dict[str, str]]:
    """Per-host launch plan: list of {host, role, ssh_cmd, remote_cmd}.

    Env layout mirrors the reference (dist_launcher.py:60-92): every host
    gets DMLC_NUM_WORKER/NUM_SERVER/PS_ROOT_URI/PORT + its role; workers
    additionally get DMLC_WORKER_ID; servers get BYTEPS_SERVER_ID (which
    byteps_tpu.server uses to derive its listen port).
    """
    base = {
        "DMLC_NUM_WORKER": str(len(workers)),
        "DMLC_NUM_SERVER": str(len(servers)),
        "DMLC_PS_ROOT_URI": scheduler_uri,
        "DMLC_PS_ROOT_PORT": str(scheduler_port),
    }
    if extra_env:
        base.update(extra_env)
    plans: List[Dict[str, str]] = []

    def plan(host: str, role: str, role_env: Dict[str, str],
             cmd: Sequence[str]) -> Dict[str, str]:
        env = dict(base)
        env["DMLC_ROLE"] = role
        env.update(role_env)
        remote = f"{_export_str(env)} cd {shlex.quote(os.getcwd())}; " \
                 f"{shlex.join(cmd)}"
        target = f"{username}@{host}" if username else host
        ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no", target, remote]
        return {"host": host, "role": role,
                "remote_cmd": remote, "ssh_cmd": shlex.join(ssh_cmd)}

    launcher = ["python", "-m", "byteps_tpu.launcher"]
    for i, host in enumerate(servers):
        plans.append(plan(host, "server", {"BYTEPS_SERVER_ID": str(i)},
                          launcher))
    for i, host in enumerate(workers):
        plans.append(plan(host, "worker", {"DMLC_WORKER_ID": str(i)},
                          launcher + list(command)))
    return plans


def run_plans(plans: List[Dict[str, str]], log_dir: str = "sshlog") -> int:
    """Execute the ssh commands concurrently, teeing output per host
    (reference: dist_launcher.py:36-58 thread-per-host). The first host to
    fail (spawn error or nonzero exit) tears down the remaining ssh
    processes — a dead server must not leave workers parked forever in the
    init barrier."""
    import time

    os.makedirs(log_dir, exist_ok=True)
    procs: List[Optional[subprocess.Popen]] = []
    codes: List[Optional[int]] = []
    # per-plan log names: a host listed N times (N workers on one box)
    # must not truncate/interleave one shared file
    seen: dict = {}
    for p in plans:
        n = seen.get((p["role"], p["host"]), 0)
        seen[(p["role"], p["host"])] = n + 1
        suffix = f"-{n}" if n else ""
        path = os.path.join(log_dir, f"{p['role']}-{p['host']}{suffix}.log")
        try:
            f = open(path, "wb")
            procs.append(subprocess.Popen(shlex.split(p["ssh_cmd"]),
                                          stdout=f, stderr=subprocess.STDOUT))
            codes.append(None)
        except OSError as e:  # spawn failure IS a host failure, not success
            log.error("failed to launch %s@%s: %s", p["role"], p["host"], e)
            procs.append(None)
            codes.append(127)

    rc = 1 if any(c == 127 for c in codes) else 0
    if rc == 0:
        while any(c is None for c in codes):
            progressed = False
            for i, proc in enumerate(procs):
                if codes[i] is None and proc.poll() is not None:
                    codes[i] = proc.returncode
                    progressed = True
            if rc == 0 and any(c not in (None, 0) for c in codes):
                rc = 1
                break
            if not progressed:
                time.sleep(0.2)
    if rc != 0:  # teardown survivors
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 10
        for proc in procs:
            if proc is None:
                continue
            while proc.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if proc.poll() is None:
                proc.kill()
    for i, proc in enumerate(procs):
        if proc is not None and codes[i] is None:
            codes[i] = proc.wait()
    bad = [p["host"] for p, c in zip(plans, codes) if c != 0]
    if bad:
        log.error("nonzero exit on hosts: %s (logs in %s/)", bad, log_dir)
        return 1
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker-hostfile", required=True)
    ap.add_argument("--server-hostfile", default="")
    ap.add_argument("--scheduler-uri", default="")
    ap.add_argument("--scheduler-port", type=int, default=9000)
    ap.add_argument("--username", default="")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE exported on every host")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the per-host commands and exit")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="training command (after --)")
    args = ap.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    workers = read_hostfile(args.worker_hostfile)
    servers = (read_hostfile(args.server_hostfile)
               if args.server_hostfile else [])
    scheduler = args.scheduler_uri or (servers[0].split(":")[0] if servers
                                       else "127.0.0.1")
    extra = dict(e.split("=", 1) for e in args.env)
    plans = build_commands(workers, servers, scheduler, args.scheduler_port,
                           command, extra_env=extra, username=args.username)
    if args.dry_run:
        for p in plans:
            print(f"[{p['role']}@{p['host']}] {p['ssh_cmd']}")
        return 0
    return run_plans(plans)


if __name__ == "__main__":
    import sys
    sys.exit(main())
