"""Ring attention: exact causal attention over a sequence-parallel mesh axis.

The reference framework has no sequence/context parallelism at all
(SURVEY.md §5.7 — verified absent); this is green-field TPU design, following
the ring-attention pattern (Liu et al.; blockwise online-softmax streaming):
the sequence is sharded over the ``sp`` axis, each device keeps its Q shard
resident and passes K/V shards around the ring with ``lax.ppermute``, folding
each incoming block into a numerically-stable streaming softmax (running
max / running normalizer, flash-attention style). Communication rides
ICI neighbor links — n-1 permutes of the local KV shard — instead of an
all_gather of the whole sequence, so the memory high-water mark stays
O(S/n) per device and compute overlaps the permute.

Causality over the ring: with ring step r, the incoming KV block originated
at device (me - r) mod n. Blocks from later devices are fully masked (we
skip their contribution entirely via lax.cond-free where-masking to stay
SPMD-uniform); the self block applies the triangular mask; earlier blocks
attend fully.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import SP_AXIS

_NEG_INF = -1e30


def _block_attn_accum(q, k, v, mask, m, l, o, scale):
    """Fold one KV block into the streaming softmax accumulators.

    q [B,Sq,H,D]; k,v [B,Sk,H,D]; mask [Sq,Sk] bool or None;
    m,l [B,H,Sq]; o [B,Sq,H,D].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1)                      # [B,H,Sq]
    m_new = jnp.maximum(m, m_blk)
    # exp with the new running max; fully-masked rows stay zero
    p = jnp.exp(scores - m_new[..., None])                # [B,H,Sq,Sk]
    corr = jnp.exp(m - m_new)                             # [B,H,Sq]
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] \
        + jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis: str = SP_AXIS, causal: bool = True) -> jnp.ndarray:
    """Exact attention with sequence sharded over ``axis``.

    q [B, S_local, H, D], k/v [B, S_local, Hkv, D] (GQA: Hkv divides H).
    Must run inside shard_map with ``axis`` bound. Returns [B,S_local,H,D].
    """
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    B, S, H, D = q.shape
    groups = H // k.shape[2]
    scale = 1.0 / np.sqrt(D)

    q32 = q.astype(jnp.float32)
    tri = jnp.tril(jnp.ones((S, S), bool))

    m0 = jnp.full((B, H, S), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    o0 = jnp.zeros((B, S, H, D), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(r, carry):
        m, l, o, kr, vr = carry
        src = (me - r) % n                  # where this KV block came from
        # K/V ride the ring with their compact Hkv heads; the GQA expansion
        # happens per-fold so ppermute traffic stays 1/groups of H
        k32, v32 = kr.astype(jnp.float32), vr.astype(jnp.float32)
        if groups > 1:
            k32 = jnp.repeat(k32, groups, axis=2)
            v32 = jnp.repeat(v32, groups, axis=2)
        if causal:
            # src < me: full attention; src == me: triangular; src > me:
            # fully masked. Computed uniformly (SPMD) with a where-mask.
            full = src < me
            diag = src == me
            mask2d = (tri & diag) | full          # broadcasts to (S, S)
            m2, l2, o2 = _block_attn_accum(q32, k32, v32, mask2d,
                                           m, l, o, scale)
            use = full | diag
            m = jnp.where(use, m2, m)
            l = jnp.where(use, l2, l)
            o = jnp.where(use, o2, o)
        else:
            m, l, o = _block_attn_accum(q32, k32, v32, None, m, l, o, scale)

        # rotate KV around the ring; the rotation after the last fold is
        # dead traffic, so skip it (r is uniform across devices, making the
        # cond collective-safe)
        def rotate(kv):
            kk, vv = kv
            return (jax.lax.ppermute(kk, axis, perm),
                    jax.lax.ppermute(vv, axis, perm))

        kr, vr = jax.lax.cond(r < n - 1, rotate, lambda kv: kv, (kr, vr))
        return m, l, o, kr, vr

    m, l, o, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attn(axis: str = SP_AXIS, causal: bool = True):
    """Bind ring_attention as a models.llama ``attn_impl``."""

    def impl(q, k, v):
        return ring_attention(q, k, v, axis=axis, causal=causal)

    return impl
