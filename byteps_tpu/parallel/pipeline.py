"""Pipeline parallelism over the ``pp`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.8 — absent); this is
green-field TPU design. The approach is the standard TPU SPMD pipeline
(GPipe-style microbatching expressed as collective ops so it compiles into
one XLA program):

- layer params are stacked on a leading [L] dim (as in models/llama.py) and
  sharded over the ``pp`` axis — each of the P stages holds L/P layers;
- inside ``shard_map`` each stage repeatedly (a) injects the next microbatch
  at stage 0, (b) runs its local layers, (c) collects finished microbatches
  at the last stage, (d) rotates activations one stage forward with
  ``lax.ppermute`` (a cyclic shift whose wrap-around edge carries only
  ignored padding);
- the loop runs M + P - 1 ticks (`lax.scan`), the classic pipeline fill +
  drain schedule; bubbles are idle compute on garbage data, masked at the
  edges.

Because ``ppermute`` is differentiable (its transpose is the reverse
permutation), ``jax.grad`` through ``pipeline_forward`` yields exactly the
1F1B-communication-pattern backward for free — XLA schedules the reverse
rotations.

Everything here is called INSIDE shard_map with the ``pp`` axis bound.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from .mesh import PP_AXIS


def pipeline_forward(
    x: jnp.ndarray,
    stage_params: Any,
    layer_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
    *,
    num_microbatches: int,
    axis: str = PP_AXIS,
    remat: bool = False,
) -> jnp.ndarray:
    """Run the local batch ``x`` through all L stacked layers pipelined over
    ``axis``.

    x: [B, ...] local-batch activations (replicated over ``axis``; B must be
       divisible by ``num_microbatches``).
    stage_params: pytree whose leaves have leading dim L_local = L / P —
       this stage's shard of the stacked layer params.
    layer_fn(h, p_layer) -> h: applies ONE layer (unstacked params).

    Returns [B, ...] outputs, valid ONLY on the last stage (others hold
    zeros) — combine with :func:`last_stage_value` or compute the loss
    locally and mask+psum (see models/llama.py loss_fn_pp).
    """
    M = num_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    P = jax.lax.axis_size(axis)
    s = jax.lax.axis_index(axis)
    mb = B // M
    x_mb = x.reshape((M, mb) + x.shape[1:])

    def run_stage(h):
        def body(h, p_layer):
            fn = jax.checkpoint(layer_fn) if remat else layer_fn
            return fn(h, p_layer), None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    perm = [(i, (i + 1) % P) for i in range(P)]

    def tick(carry, t):
        state, out_buf = carry
        # (a) inject microbatch t at stage 0 (clamped index; validity is
        # implied by the collect window, garbage never reaches out_buf)
        inj = jax.lax.dynamic_index_in_dim(x_mb, jnp.minimum(t, M - 1), 0,
                                           keepdims=False)
        state = jnp.where(s == 0, inj.astype(state.dtype), state)
        # (b) this stage's layers
        state = run_stage(state)
        # (c) last stage finished microbatch t-(P-1) at tick t
        m = t - (P - 1)
        out_buf = jax.lax.cond(
            m >= 0,
            lambda buf: jax.lax.dynamic_update_index_in_dim(
                buf, state.astype(buf.dtype), jnp.maximum(m, 0), 0),
            lambda buf: buf,
            out_buf)
        # (d) rotate activations one stage forward
        state = jax.lax.ppermute(state, axis, perm)
        return (state, out_buf), None

    state0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    out0 = jnp.zeros((M, mb) + x.shape[1:], x.dtype)
    (_, out_buf), _ = jax.lax.scan(
        tick, (state0, out0), jnp.arange(M + P - 1))

    out = out_buf.reshape((B,) + x.shape[1:])
    # only the last stage collected real data; zero elsewhere so callers can
    # psum-broadcast without double counting
    return jnp.where(s == P - 1, out, jnp.zeros_like(out))


def last_stage_value(v: jnp.ndarray, axis: str = PP_AXIS) -> jnp.ndarray:
    """Broadcast a value that is only valid on the last pipeline stage to
    every stage (zero elsewhere + psum).

    Gradient-correct under per-device ``jax.grad``: the broadcast output is
    replicated, so every stage seeds cotangent 1 and the psum transpose
    would inflate upstream gradients by the stage count P; the
    stop-gradient rescale keeps the value while scaling the differentiable
    path by 1/P, so block grads come out exact per stage.
    """
    P = jax.lax.axis_size(axis)
    s = jax.lax.axis_index(axis)
    summed = jax.lax.psum(jnp.where(s == P - 1, v, jnp.zeros_like(v)), axis)
    if P == 1:
        return summed
    return summed / P + jax.lax.stop_gradient(summed) * ((P - 1) / P)


def replicated_grad_correction(grads: Any, axis: str = PP_AXIS) -> Any:
    """Sum gradients of pp-replicated params (embeddings, lm head, final
    norm) across stages: each stage only touched them in its own segment of
    the computation, so the true gradient is the sum of the per-stage
    partials."""
    return jax.lax.psum(grads, axis)
