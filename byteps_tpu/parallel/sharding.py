"""Named-sharding rules: how model params/batches lay out over the mesh.

This is the GSPMD tier of the framework: annotate shardings, jit, and XLA
inserts the collectives (psum over dp for gradients, all-gathers/
reduce-scatters for tp) — the compiler-native counterpart of the
hand-scheduled pipeline in the reference's core_loops.cc. The shard_map tier
(ops/push_pull.py) is used where we want explicit control (push_pull
semantics, ring attention, the PS boundary); this tier is used for whole-
model tensor parallelism where the Megatron pattern is expressed purely as
weight layouts:

- column-parallel (out-dim over tp):  QKV projections, MLP in/gate
- row-parallel (in-dim over tp):      attention output, MLP down
- vocab-parallel: embedding + lm head
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DP_AXIS, EP_AXIS, PP_AXIS, SP_AXIS, TP_AXIS


def llama_param_specs(params_shape: Any) -> Any:
    """PartitionSpec pytree for models/llama.py params (layers stacked on
    leading dim L, which never shards)."""
    rules = {
        "embed": P(TP_AXIS, None),          # vocab-parallel
        "final_norm": P(),
        "lm_head": P(None, TP_AXIS),        # vocab-parallel out
        "blocks": {
            "attn_norm": P(),
            "wq": P(None, None, TP_AXIS),   # column-parallel
            "wk": P(None, None, TP_AXIS),
            "wv": P(None, None, TP_AXIS),
            "wo": P(None, TP_AXIS, None),   # row-parallel
            "mlp_norm": P(),
            "w_gate": P(None, None, TP_AXIS),
            "w_up": P(None, None, TP_AXIS),
            "w_down": P(None, TP_AXIS, None),
        },
    }
    return rules


def bert_param_specs(params_shape: Any) -> Any:
    b = {
        "wq": P(None, None, TP_AXIS), "bq": P(),
        "wk": P(None, None, TP_AXIS), "bk": P(),
        "wv": P(None, None, TP_AXIS), "bv": P(),
        "wo": P(None, TP_AXIS, None), "bo": P(),
        "ln1_g": P(), "ln1_b": P(),
        "w_in": P(None, None, TP_AXIS), "b_in": P(),
        "w_out": P(None, TP_AXIS, None), "b_out": P(),
        "ln2_g": P(), "ln2_b": P(),
    }
    return {
        "tok_embed": P(TP_AXIS, None), "pos_embed": P(), "type_embed": P(),
        "embed_ln_g": P(), "embed_ln_b": P(),
        "blocks": b,
        "mlm_dense": P(None, TP_AXIS), "mlm_bias": P(),
        "mlm_ln_g": P(), "mlm_ln_b": P(),
        "mlm_out_bias": P(),
    }


def llama_pp_param_specs() -> Any:
    """PartitionSpec pytree for pipeline-parallel Llama: the stacked
    [n_layers] leading dim of every block leaf shards over ``pp`` (each
    stage owns n_layers/P layers); embeddings/head replicate across stages
    (their grads are psum'd, parallel/pipeline.py)."""
    return {
        "embed": P(),
        "final_norm": P(),
        "lm_head": P(),
        "blocks": {
            k: P(PP_AXIS) for k in
            ("attn_norm", "wq", "wk", "wv", "wo",
             "mlp_norm", "w_gate", "w_up", "w_down")
        },
    }


def moe_param_specs() -> Any:
    """PartitionSpec pytree for models/moe.py params under shard_map: the
    experts dim shards over ``ep``; attention/router/embeddings replicate.
    (Megatron-tp attention sharding is only valid on the GSPMD/jit tier
    where XLA inserts the reduction collectives — attn_sublayer has no
    explicit tp psum, so tp specs must not be combined with shard_map.)"""
    return {
        "embed": P(),
        "final_norm": P(),
        "lm_head": P(),
        "blocks": {
            "attn_norm": P(),
            "wq": P(), "wk": P(), "wv": P(),
            "wo": P(),
            "mlp_norm": P(),
            "router": P(),
            # [L, E, d, h]: experts over ep
            "w_gate": P(None, EP_AXIS),
            "w_up": P(None, EP_AXIS),
            "w_down": P(None, EP_AXIS),
        },
    }


def fsdp_param_specs(params: Any, axis: str = DP_AXIS, *, axis_size: int,
                     base_specs: Any = None,
                     min_elements: int = 1 << 14) -> Any:
    """ZeRO-3/FSDP layout: shard each large param leaf over the data axis.

    The GSPMD expression of fully-sharded data parallelism: params (and,
    via mirror_opt_specs, optimizer state) live sharded over ``axis``;
    XLA inserts the per-layer all-gathers in forward/backward and
    reduce-scatters the gradients — the compiler-native generalization of
    the reference's hierarchical owns-1/N scheme (core_loops.cc:216-268),
    extended from optimizer state (ZeRO-1, make_zero_train_step) to the
    parameters themselves.

    Per leaf: the first dimension divisible by ``axis_size`` that
    ``base_specs`` (e.g. Megatron TP rules, for dp x tp 2D sharding)
    leaves unsharded gets the axis; leaves smaller than ``min_elements``
    or with no divisible free dim stay on their base spec (replicated
    over ``axis``) — sharding tiny tensors costs more in collective
    latency than it saves in HBM.
    """
    import numpy as _np

    def leaf_spec(leaf, base):
        shape = tuple(getattr(leaf, "shape", ()))
        entries = list(base) if base is not None else []
        entries += [None] * (len(shape) - len(entries))
        if int(_np.prod(shape or (0,))) < min_elements:
            return P(*entries)
        for i, d in enumerate(shape):
            if entries[i] is None and d % axis_size == 0:
                entries[i] = axis
                return P(*entries)
        return P(*entries)

    if base_specs is None:
        return jax.tree.map(lambda leaf: leaf_spec(leaf, None), params)
    return jax.tree.map(leaf_spec, params, base_specs,
                        is_leaf=lambda x: isinstance(x, P))


def _keystr(k) -> str:
    return str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))


def mirror_opt_specs(tx, params: Any, param_specs: Any) -> Any:
    """PartitionSpec tree for ``tx.init(params)``'s state.

    Optimizer-state leaves that mirror a param leaf (adam mu/nu, momentum
    buffers, ...) inherit that param's spec, matched by tree-path *suffix*
    (an opt-state path like ``(0, 'mu', 'blocks', 'wq')`` ends with the
    param path ``('blocks', 'wq')``) with a shape check so equal-shaped
    params with different specs can't cross-contaminate. Scalar counts and
    anything unmatched replicate.
    """
    opt_shapes = jax.eval_shape(tx.init, params)
    spec_by_path = {
        tuple(_keystr(k) for k in path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            param_specs, is_leaf=lambda x: isinstance(x, P))[0]
    }
    shape_by_path = {
        tuple(_keystr(k) for k in path): tuple(leaf.shape)
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }

    def spec_of(path, leaf):
        keys = tuple(_keystr(k) for k in path)
        shape = tuple(getattr(leaf, "shape", ()))
        for i in range(len(keys)):
            suffix = keys[i:]
            if (suffix in spec_by_path
                    and shape_by_path.get(suffix) == shape):
                return spec_by_path[suffix]
        return P()

    return jax.tree_util.tree_map_with_path(spec_of, opt_shapes)


def to_shardings(mesh: Mesh, spec_tree: Any) -> Any:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def batch_spec(shard_seq: bool = False) -> P:
    """Batch tokens [B, S]: B over dp, optionally S over sp."""
    return P(DP_AXIS, SP_AXIS) if shard_seq else P(DP_AXIS)


def place_params(params: Any, mesh: Mesh, spec_tree: Any) -> Any:
    """device_put the param pytree according to the spec tree."""
    shardings = to_shardings(mesh, spec_tree)
    return jax.tree.map(jax.device_put, params, shardings)
