"""Ulysses-style all-to-all sequence parallelism.

The second of the framework's two long-context strategies (the other is
parallel/ring_attention.py). The reference has neither (SURVEY.md §5.7:
sequence parallelism is green-field for the TPU build); this follows the
DeepSpeed-Ulysses scheme (arXiv:2309.14509): with the sequence sharded
over the ``sp`` mesh axis, two all-to-alls re-shard q/k/v from
sequence-split to HEAD-split, every device then runs ordinary dense
attention over the FULL sequence for its subset of heads, and a final
all-to-all restores sequence sharding.

Trade-off vs ring attention: Ulysses moves activations twice through
all-to-all (cheap on ICI's all-to-all-friendly torus) and reuses the
plain fused attention kernel — best when heads >= axis size and the
sequence fits one device's memory for score blocks; ring attention
streams KV around the ring with O(1) extra memory — best at extreme
sequence lengths. Both are exact; pick per workload.

Usage matches make_ring_attn: pass as ``attn_impl`` to models.llama
forward/loss_fn with ``sp_axis`` set, inside shard_map with the batch
pre-shifted and sequence-sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import SP_AXIS


def _dense_causal(q, k, v, causal: bool):
    """Plain attention over full sequence; q/k/v [B, S, H, D] (same head
    count — GQA expansion happens before the all-to-all)."""
    B, S, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis: str = SP_AXIS, causal: bool = True,
                      local_attn=None) -> jnp.ndarray:
    """Exact attention with the sequence sharded over ``axis`` via head
    re-sharding. q [B, S_local, H, D], k/v [B, S_local, Hkv, D] with
    Hkv | H; H must be divisible by the axis size. Must run inside
    shard_map with ``axis`` bound; returns [B, S_local, H, D].

    Sequence chunks concatenate in device order along the axis, so RoPE
    global positions (models.llama.forward's sp_axis slicing) line up
    with the causal mask.
    """
    n = jax.lax.axis_size(axis)
    H = q.shape[2]
    if H % n != 0:
        raise ValueError(
            f"ulysses requires n_heads ({H}) divisible by the '{axis}' "
            f"axis size ({n}); use ring attention otherwise")
    groups = H // k.shape[2]
    if groups > 1:
        # expand GQA groups so every device gets whole (q-head, kv-head)
        # pairs after the head split; costs kv bandwidth — ring attention
        # is the bandwidth-optimal choice for small-kv configs
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)

    def seq_to_heads(x):
        # [B, S/P, H, D] -> [B, S, H/P, D]
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    q = seq_to_heads(q)
    k = seq_to_heads(k)
    v = seq_to_heads(v)
    if local_attn is None:
        o = _dense_causal(q, k, v, causal)
    else:
        # the per-device problem is ordinary attention over the FULL
        # sequence for a head subset — exactly where flash/blockwise
        # pays at long S (ops/flash_attention.py); any attn_impl-shaped
        # callable works
        o = local_attn(q, k, v)
    # [B, S, H/P, D] -> [B, S/P, H, D]
    return jax.lax.all_to_all(o, axis, split_axis=1, concat_axis=2,
                              tiled=True)


def make_ulysses_attn(axis: str = SP_AXIS, causal: bool = True,
                      flash: bool = False):
    """Bind ulysses_attention as a models.llama ``attn_impl``.
    ``flash=True`` runs the post-all-to-all local attention through
    ops.flash_attention (O(S*block) residency over the full gathered
    sequence — the long-context composition)."""
    local = None
    if flash:
        from ..ops.flash_attention import make_flash_attn
        local = make_flash_attn(causal=causal)

    def impl(q, k, v):
        return ulysses_attention(q, k, v, axis=axis, causal=causal,
                                 local_attn=local)

    return impl
