"""Multi-process runtime: jax.distributed bootstrap + global-batch helpers.

Reference mapping (SURVEY.md §2.4): ps-lite's scheduler rendezvous
(``DMLC_PS_ROOT_URI/PORT``, reference: byteps/common/global.cc:283-297)
becomes JAX's coordination service, and worker identity (reference:
byteps/common/communicator.cc:60-96) maps to ``jax.process_index``.

Two multi-process modes, chosen by topology:

- **global-mesh** (``num_servers == 0``): every process's chips join one
  global ``Mesh``; gradient sync is an XLA collective riding ICI within a
  slice and DCN between slices. This is the native JAX scale-out path
  (BASELINE config 3: BERT-large on v5e-256).
- **PS mode** (``num_servers > 0``): each process keeps a *local* mesh
  (ICI collectives intra-process) and cross-process summation rides the
  DCN parameter server — the exact analogue of the reference's
  NCCL-intra-machine + ps-lite-inter-machine split
  (docs/architecture.md "General Workflow").

On CPU (tests / dryrun) the cross-process collective backend is gloo;
on TPU pods it is the platform transport. Either way the code is the
same: ``jax.distributed.initialize`` then ordinary jit/shard_map.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DP_AXIS

# Offset added to the scheduler port for the JAX coordination service when
# BYTEPS_COORD_PORT is not set: keeps the whole port block derivable from
# DMLC_PS_ROOT_PORT (servers live at scheduler_port + server_id,
# server/__init__.py:30).
COORD_PORT_OFFSET = 512


def coordinator_address(config) -> str:
    port = config.coord_port or config.scheduler_port + COORD_PORT_OFFSET
    return f"{config.scheduler_uri}:{port}"


def ensure_initialized(config) -> bool:
    """Bootstrap jax.distributed for a multi-process topology (idempotent).

    Returns True when this process is part of an initialized multi-process
    JAX runtime afterwards. The reference's equivalent is GetOrInitPS's
    ps::StartPS + global barrier (global.cc:283-297): every process blocks
    here until the whole process set has rendezvoused at the coordinator.
    """
    if config.num_processes <= 1:
        return False
    # NB: don't probe jax.process_count() here — any device query would
    # initialize the XLA backend and make distributed-init impossible.
    if jax.distributed.is_initialized():
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator_address(config),
        num_processes=config.num_processes,
        process_id=config.process_id,
    )
    return True


def process_identity() -> tuple:
    """(process_id, process_count) of the live JAX runtime."""
    return jax.process_index(), jax.process_count()


def global_batch(mesh: Mesh, local_array, axis: str = DP_AXIS,
                 sharding: Optional[NamedSharding] = None):
    """Assemble a globally-sharded array from per-process local data.

    Each process passes its local shard of the batch (e.g. from its own
    data-loader partition); the result is one global jax.Array whose
    addressable shards are this process's devices — the single-controller
    equivalent of "each worker feeds its own minibatch".
    """
    if sharding is None:
        sharding = NamedSharding(mesh, P(axis))
    return jax.make_array_from_process_local_data(sharding, local_array)


def sync_global_devices(tag: str = "byteps_tpu") -> None:
    """Cross-process barrier (the reference's Postoffice::Barrier)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)
