"""Device-mesh construction for byteps_tpu.

The reference bootstraps NCCL communicators per PCIe switch and per ring
(reference: byteps/common/nccl_manager.cc:95-163). On TPU the equivalent
object is a static ``jax.sharding.Mesh`` over the slice: collectives are
compiled into the program, so there is no id-exchange bootstrap and no
root/non-root process choreography — one process owns all local chips.

Axis conventions (used across the framework):

- ``dp``: data parallel (gradient push_pull axis; the BytePS axis)
- ``tp``: tensor parallel (megatron-style within attention/mlp)
- ``sp``: sequence/context parallel (ring attention)
- ``pp``: pipeline parallel stages
- ``ep``: expert parallel (MoE)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
TP_AXIS = "tp"
SP_AXIS = "sp"
PP_AXIS = "pp"
EP_AXIS = "ep"


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh. Default: every device on the ``dp`` axis.

    ``axes`` maps axis name -> size, in major-to-minor order, e.g.
    ``{"dp": 4, "tp": 2}``. One axis may be -1 to absorb the remainder.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if not axes:
        axes = {DP_AXIS: n}
    axes = dict(axes)
    # Resolve a single -1.
    known = 1
    wild = None
    for name, size in axes.items():
        if size == -1:
            wild = name
        else:
            known *= size
    if wild is not None:
        axes[wild] = n // known
        known *= axes[wild]
    if known != n:
        raise ValueError(f"mesh axes {axes} do not multiply to {n} devices")
    arr = np.array(devices).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh, axis: str = DP_AXIS) -> NamedSharding:
    """Batch-dim sharding over the data-parallel axis."""
    return NamedSharding(mesh, P(axis))


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)
