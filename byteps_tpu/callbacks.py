"""Training-loop callbacks — Keras-adapter parity for the JAX loop.

The reference ships Keras callbacks (byteps/_keras/callbacks.py:23-196):
broadcast-on-start, cross-worker metric averaging, LR schedules and warmup.
This module provides the same four behaviors as framework-neutral hooks a
training loop drives; ``byteps_tpu.torch`` users can drive the same
objects (they only touch the comm layer through push_pull/broadcast).

LR control follows the optax idiom: wrap your optimizer with
``optax.inject_hyperparams`` so the learning rate is a leaf in the
optimizer state, and the LR callbacks rewrite that leaf
(``apply_lr(opt_state)``) — the functional equivalent of the reference's
``K.set_value(self.model.optimizer.lr, ...)``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "Callback", "CallbackList",
    "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
    "LearningRateScheduleCallback", "LearningRateWarmupCallback",
]


class Callback:
    """Hook points mirroring the Keras surface the reference extends."""

    def on_train_begin(self, state: Dict[str, Any]) -> None: ...

    def on_epoch_begin(self, epoch: int, state: Dict[str, Any]) -> None: ...

    def on_batch_begin(self, batch: int, state: Dict[str, Any]) -> None: ...

    def on_batch_end(self, batch: int, state: Dict[str, Any]) -> None: ...

    def on_epoch_end(self, epoch: int, state: Dict[str, Any]) -> None: ...

    # LR callbacks implement this; the loop applies it to the optimizer
    # state after the hooks ran
    def lr_scale(self) -> Optional[float]:
        return None


class CallbackList:
    def __init__(self, callbacks: Sequence[Callback]):
        self.callbacks = list(callbacks)

    def _fire(self, hook: str, *args) -> None:
        for cb in self.callbacks:
            getattr(cb, hook)(*args)

    def on_train_begin(self, state): self._fire("on_train_begin", state)

    def on_epoch_begin(self, e, state): self._fire("on_epoch_begin", e, state)

    def on_batch_begin(self, b, state): self._fire("on_batch_begin", b, state)

    def on_batch_end(self, b, state): self._fire("on_batch_end", b, state)

    def on_epoch_end(self, e, state): self._fire("on_epoch_end", e, state)

    def lr_scale(self) -> float:
        scale = 1.0
        for cb in self.callbacks:
            s = cb.lr_scale()
            if s is not None:
                scale *= s
        return scale

    def apply_lr(self, opt_state, base_lr: float):
        """Return a new opt_state with the ``learning_rate`` hyperparam
        leaf rewritten (requires the optimizer be wrapped in
        optax.inject_hyperparams). Functional: the input state is not
        mutated, so stashed references (checkpoints, rollback copies) keep
        their recorded LR."""
        if not hasattr(opt_state, "hyperparams"):
            raise ValueError(
                "apply_lr requires optax.inject_hyperparams(...) so the "
                "learning rate is part of the optimizer state")
        hyper = dict(opt_state.hyperparams)
        hyper["learning_rate"] = base_lr * self.lr_scale()
        return opt_state._replace(hyperparams=hyper)


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial parameters from the root worker before training
    (reference: _keras/callbacks.py:23-50, BroadcastGlobalVariablesHook).
    The loop must put its params pytree in ``state['params']``."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, state: Dict[str, Any]) -> None:
        if self._done:
            return
        from .jax import broadcast_parameters
        state["params"] = broadcast_parameters(state["params"],
                                               root_rank=self.root_rank)
        self._done = True


class MetricAverageCallback(Callback):
    """Average epoch metrics across workers after each epoch (reference:
    _keras/callbacks.py:54-86). Metrics live in ``state['metrics']`` as a
    name -> float dict."""

    def on_epoch_end(self, epoch: int, state: Dict[str, Any]) -> None:
        import os

        import byteps_tpu as bps
        from .core.state import get_state

        metrics = state.get("metrics")
        if not metrics:
            return
        if get_state().scheduler is None:
            # no PS: the ICI mean cannot stall on a missing peer push
            for name in sorted(metrics):
                v = np.asarray([float(metrics[name])], np.float32)
                out = bps.push_pull(v, name=f"metric/{name}", average=True)
                metrics[name] = float(np.asarray(out)[0])
            return
        # PS tier: submit all, then drain under ONE shared deadline — a
        # metric key logged by only one worker can never reach
        # num_workers contributions, and hanging the job at epoch end
        # with no diagnostic is the worst failure mode. The deadline is
        # computed once and each wait gets the REMAINING time: the old
        # per-metric full timeout let N slow metrics stack up to
        # N x BYTEPS_METRIC_TIMEOUT_S of wall before the diagnostic.
        import time as _time

        timeout = float(os.environ.get("BYTEPS_METRIC_TIMEOUT_S", "60"))
        deadline = _time.monotonic() + timeout
        hs = {name: bps.push_pull_async(
                  np.asarray([float(metrics[name])], np.float32),
                  f"metric/{name}", average=True)
              for name in sorted(metrics)}
        for name, h in hs.items():
            try:
                out = bps.synchronize(
                    h, timeout=max(0.0, deadline - _time.monotonic()))
            except TimeoutError as e:
                # the raise is fatal for this epoch's metrics and nothing
                # retries them: drop the timed-out handle and every
                # sibling so their result buffers don't pin memory for
                # the rest of the process
                for h2 in hs.values():
                    get_state().handles.discard(h2)
                raise TimeoutError(
                    f"metric {name!r}: cross-worker average timed out "
                    f"after {timeout:.0f}s — every worker must log the "
                    f"SAME metric keys each epoch; "
                    f"BYTEPS_METRIC_TIMEOUT_S overrides") from e
            metrics[name] = float(np.asarray(out)[0])


class LearningRateScheduleCallback(Callback):
    """Multiply the LR by ``multiplier`` (a float or an epoch->float
    callable) within [start_epoch, end_epoch) (reference:
    _keras/callbacks.py:90-147). ``staircase`` quantizes a callable
    multiplier to integer epochs."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 steps_per_epoch: Optional[int] = None):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self._multiplier = (multiplier if callable(multiplier)
                            else (lambda e: multiplier))
        self._epoch = 0.0
        self._scale = 1.0

    def _in_window(self) -> bool:
        if self._epoch < self.start_epoch:
            return False
        return self.end_epoch is None or self._epoch < self.end_epoch

    def on_epoch_begin(self, epoch: int, state: Dict[str, Any]) -> None:
        self._epoch = float(epoch)
        if self._in_window():
            e = math.floor(self._epoch) if self.staircase else self._epoch
            self._scale = float(self._multiplier(e))

    def on_batch_begin(self, batch: int, state: Dict[str, Any]) -> None:
        if self.staircase or not self.steps_per_epoch:
            return
        self._epoch = math.floor(self._epoch) + batch / self.steps_per_epoch
        if self._in_window():
            self._scale = float(self._multiplier(self._epoch))

    def lr_scale(self) -> Optional[float]:
        return self._scale if self._in_window() else None


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup of the LR multiplier from 1/size to 1.0 over
    ``warmup_epochs`` (reference: _keras/callbacks.py:150-196 — 'Accurate,
    Large Minibatch SGD' gradual warmup; the base lr is assumed already
    scaled by size)."""

    def __init__(self, warmup_epochs: int = 5,
                 steps_per_epoch: Optional[int] = None,
                 verbose: bool = False,
                 size: Optional[int] = None):
        import byteps_tpu as bps

        n = size if size is not None else bps.size()
        self.verbose = verbose

        def multiplier(epoch: float) -> float:
            progress = min(epoch / warmup_epochs, 1.0) if warmup_epochs \
                else 1.0
            return 1.0 / n + (1.0 - 1.0 / n) * progress

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False, steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch: int, state: Dict[str, Any]) -> None:
        if self.verbose and epoch + 1 == self.end_epoch:
            from .utils.logging import log
            log.info("warmup complete at epoch %d", epoch)
