"""Developer tooling that ships with the framework (not runtime code).

``byteps_tpu.tools.lint`` — the project-native static analysis suite
(docs/static-analysis.md). Nothing under this package is imported by
the training path.
"""
