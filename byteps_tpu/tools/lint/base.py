"""byteps-lint core: project model, findings, rule registry, suppression.

The framework is deliberately dependency-free (ast + re + pathlib): it
must run in CI boxes and pre-commit hooks without the training stack.
Each rule is one class with a ``name``, a one-line ``doc`` and a
``check(project)`` returning structured findings; ``run_lint`` filters
per-line suppressions (``# bps-lint: disable=<rule>`` on the flagged
line or the line directly above; ``//`` comments work in C++ sources).

The rules encode invariants that previously lived only in reviewers'
heads — see docs/static-analysis.md for the catalog and the historical
bug each rule pins down.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence

# Directories never scanned: the linter itself (its sources quote rule
# names, env vars and metric names as DATA), caches, VCS internals.
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "lint"}

_SUPPRESS_RE = re.compile(r"(?:#|//)\s*bps-lint:\s*disable=([\w,\-\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: rule slug, repo-relative path, 1-based line."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Project:
    """Lazily-cached view of the tree being linted.

    ``root`` is either the real repo root (``byteps_tpu/`` package plus
    ``docs/``) or a fixture tree mimicking the same shape; every lookup
    degrades gracefully when a piece is absent so single-rule fixtures
    stay tiny.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        pkg = os.path.join(self.root, "byteps_tpu")
        self.pkg_root = pkg if os.path.isdir(pkg) else self.root
        self.docs_root = os.path.join(self.root, "docs")
        self._text: Dict[str, Optional[str]] = {}
        self._ast: Dict[str, Optional[ast.AST]] = {}

    # -- file discovery ------------------------------------------------ #

    def _walk(self, top: str, suffix: str) -> List[str]:
        out: List[str] = []
        if not os.path.isdir(top):
            return out
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for f in sorted(filenames):
                if f.endswith(suffix):
                    out.append(os.path.join(dirpath, f))
        return out

    def py_files(self) -> List[str]:
        """Package Python sources (the system under lint — excludes the
        linter itself and anything outside the package)."""
        return self._walk(self.pkg_root, ".py")

    def cc_files(self) -> List[str]:
        return self._walk(self.pkg_root, ".cc")

    def native_source(self) -> Optional[str]:
        """The wire-protocol ground truth (``native/ps.cc``), or the
        first .cc file for fixture trees."""
        ccs = self.cc_files()
        for c in ccs:
            if os.path.basename(c) == "ps.cc":
                return c
        return ccs[0] if ccs else None

    def doc(self, name: str) -> Optional[str]:
        p = os.path.join(self.docs_root, name)
        return p if os.path.exists(p) else None

    def env_scan_files(self) -> List[str]:
        """Sources scanned for BYTEPS_*/DMLC_* env reads: the package
        (.py and .cc) plus the repo-level bench/examples entry points
        that read documented knobs."""
        out = self.py_files() + self.cc_files()
        bench = os.path.join(self.root, "bench.py")
        if os.path.exists(bench):
            out.append(bench)
        out += self._walk(os.path.join(self.root, "examples"), ".py")
        return out

    # -- content caches ------------------------------------------------ #

    def text(self, path: str) -> Optional[str]:
        if path not in self._text:
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    self._text[path] = f.read()
            except OSError:
                self._text[path] = None
        return self._text[path]

    def lines(self, path: str) -> List[str]:
        t = self.text(path)
        return t.splitlines() if t is not None else []

    def tree(self, path: str) -> Optional[ast.AST]:
        if path not in self._ast:
            t = self.text(path)
            try:
                self._ast[path] = ast.parse(t) if t is not None else None
            except SyntaxError:
                self._ast[path] = None
        return self._ast[path]

    def rel(self, path: str) -> str:
        return os.path.relpath(path, self.root)

    # -- suppression --------------------------------------------------- #

    def suppressed(self, path: str, line: int, rule: str) -> bool:
        """True when the flagged line (or the one directly above, for
        statements too long to carry a trailing comment) disables the
        rule. ``disable=all`` silences every rule on that line — use
        sparingly; the named form documents WHICH invariant is waived."""
        lines = self.lines(path)
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = _SUPPRESS_RE.search(lines[ln - 1])
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    if rule in rules or "all" in rules:
                        return True
        return False


class Rule:
    """Base class: subclasses set ``name``/``doc`` and implement
    ``check``. Findings come back unfiltered; ``run_lint`` applies
    suppressions so every rule gets them for free."""

    name = "abstract"
    doc = ""

    def check(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def all_rules() -> List[Rule]:
    """The registered rule set, import-cycle-free (rules import base,
    never each other)."""
    from .device_thread import DeviceThreadRule
    from .env_sync import EnvSyncRule
    from .locks import GuardedByRule
    from .metrics_schema import MetricsSchemaRule
    from .wire_layout import WireLayoutRule

    return [WireLayoutRule(), GuardedByRule(), DeviceThreadRule(),
            EnvSyncRule(), MetricsSchemaRule()]


def run_lint(root: str,
             rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the suite over ``root``; returns suppression-filtered
    findings sorted by (path, line, rule). ``rules``: optional subset
    of rule names."""
    project = Project(root)
    selected = all_rules()
    if rules:
        wanted = set(rules)
        unknown = wanted - {r.name for r in selected}
        if unknown:
            raise ValueError(
                f"unknown rule(s): {sorted(unknown)}; available: "
                f"{sorted(r.name for r in selected)}")
        selected = [r for r in selected if r.name in wanted]
    findings: List[Finding] = []
    for rule in selected:
        for f in rule.check(project):
            abs_path = os.path.join(project.root, f.path)
            if not project.suppressed(abs_path, f.line, f.rule):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
