"""Minimal C++ fact extraction from ``native/ps.cc``.

Not a parser — targeted regexes over the comment-stripped source for
exactly the declarations that form the cross-language wire contract:
the packed ``MsgHeader`` struct, its ``static_assert`` size, ``kMagic``,
and the ``WireCodec`` / ``DType`` enums. The wire-layout rule treats
these as ground truth and diffs every Python mirror against them.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

# Fixed-width integer types only: the wire header must not contain
# anything whose size is platform-dependent.
CTYPE_SIZES = {
    "uint8_t": 1, "int8_t": 1, "uint16_t": 2, "int16_t": 2,
    "uint32_t": 4, "int32_t": 4, "uint64_t": 8, "int64_t": 8,
}

# struct-module format char per C type (little-endian "<" prefix added
# by the caller; pack(push, 1) means no padding either side).
CTYPE_FMT = {
    "uint8_t": "B", "int8_t": "b", "uint16_t": "H", "int16_t": "h",
    "uint32_t": "I", "int32_t": "i", "uint64_t": "Q", "int64_t": "q",
}


@dataclasses.dataclass
class HeaderInfo:
    fields: List[Tuple[str, str]]        # (ctype, name) in wire order
    line: int                            # struct declaration line
    asserted_size: Optional[int]         # static_assert(sizeof==N)
    assert_line: int
    magic: Optional[int]
    magic_line: int

    @property
    def computed_size(self) -> Optional[int]:
        try:
            return sum(CTYPE_SIZES[t] for t, _ in self.fields)
        except KeyError:
            return None

    @property
    def fmt(self) -> Optional[str]:
        """Expected struct-module format ("<" + one char per field)."""
        try:
            return "<" + "".join(CTYPE_FMT[t] for t, _ in self.fields)
        except KeyError:
            return None


def _strip_comments(text: str) -> str:
    """Blank out // and /* */ comments, preserving newlines so line
    numbers computed on the stripped text match the original."""
    out = []
    i, n = 0, len(text)
    while i < n:
        if text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j
        elif text.startswith("/*", i):
            j = text.find("*/", i + 2)
            seg = text[i:(n if j < 0 else j + 2)]
            out.append("\n" * seg.count("\n"))
            i = n if j < 0 else j + 2
        else:
            out.append(text[i])
            i += 1
    return "".join(out)


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def parse_header(text: str, struct_name: str = "MsgHeader"
                 ) -> Optional[HeaderInfo]:
    stripped = _strip_comments(text)
    m = re.search(r"struct\s+%s\s*\{(.*?)\};" % re.escape(struct_name),
                  stripped, re.S)
    if not m:
        return None
    fields = re.findall(r"(\w+)\s+(\w+)\s*;", m.group(1))
    sa = re.search(
        r"static_assert\(\s*sizeof\(%s\)\s*==\s*(\d+)"
        % re.escape(struct_name), stripped)
    mg = re.search(r"kMagic\s*=\s*(0[xX][0-9a-fA-F]+|\d+)", stripped)
    return HeaderInfo(
        fields=fields,
        line=_line_of(stripped, m.start()),
        asserted_size=int(sa.group(1)) if sa else None,
        assert_line=_line_of(stripped, sa.start()) if sa else 0,
        magic=int(mg.group(1), 0) if mg else None,
        magic_line=_line_of(stripped, mg.start()) if mg else 0,
    )


def parse_enum(text: str, enum_name: str) -> Dict[str, int]:
    """``enum Name [: type] { A = 1, B, ... };`` -> {A: 1, B: 2, ...}."""
    stripped = _strip_comments(text)
    m = re.search(
        r"enum\s+%s\s*(?::\s*\w+)?\s*\{(.*?)\};" % re.escape(enum_name),
        stripped, re.S)
    if not m:
        return {}
    out: Dict[str, int] = {}
    nxt = 0
    for entry in m.group(1).split(","):
        entry = entry.strip()
        if not entry:
            continue
        em = re.match(r"(\w+)(?:\s*=\s*(0[xX][0-9a-fA-F]+|\d+))?", entry)
        if not em:
            continue
        if em.group(2) is not None:
            nxt = int(em.group(2), 0)
        out[em.group(1)] = nxt
        nxt += 1
    return out


def parse_name_array(text: str, name: str
                     ) -> Optional[Tuple[List[str], int]]:
    """``static const char* const kX[] = {"a", "b", ...};`` ->
    (["a", "b", ...], line). The slot/field manifests the native side
    declares next to its stat vector and packed record structs — the
    ground truth the slot-layout check diffs Python mirrors against.
    None when the array does not exist in this tree."""
    stripped = _strip_comments(text)
    m = re.search(
        r"%s\s*\[\]\s*=\s*\{(.*?)\};" % re.escape(name), stripped, re.S)
    if not m:
        return None
    return (re.findall(r'"([^"]*)"', m.group(1)),
            _line_of(stripped, m.start()))


def getenv_reads(text: str) -> List[Tuple[str, int]]:
    """(var, line) for every ``getenv("X")`` in a C++ source."""
    stripped = _strip_comments(text)
    return [(m.group(1), _line_of(stripped, m.start()))
            for m in re.finditer(r'getenv\(\s*"([A-Z][A-Z0-9_]*)"',
                                 stripped)]
