"""Rule ``env-sync``: every BYTEPS_*/DMLC_* knob is documented, every
documented knob exists, and config defaults match the docs.

Historical bug class: each PR adds knobs (PR 6: wire retry/chaos,
PR 9: seven codec-plane vars) and ``docs/env.md`` is updated by
memory; a missed row means an operator cannot discover the knob, a
stale default means they reason from the wrong baseline (the
``BYTEPS_PARTITION_BYTES`` row drifted from the code's 4096000 to a
plausible-but-wrong 4 MiB). Three checks:

1. every ``BYTEPS_``/``DMLC_`` name READ in package code (Python call
   sites / env subscripts — docstrings and log messages do not count —
   AND native ``getenv``) appears somewhere in ``docs/env.md``;
2. every var named in an env.md TABLE row is referenced somewhere in
   code (a documented knob nothing reads is a lie);
3. for single-var table rows read through ``config.py``'s typed
   helpers (``_env_int``/``_env_bool``), the row's default equals the
   code default (module-level constants are resolved). String-typed
   knobs are presence-checked only — their doc cells are often prose
   ("auto", "partition-dependent").
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from . import cpp
from .base import Finding, Project, Rule

_VAR_NAME_RE = re.compile(r"(?:BYTEPS|DMLC)_[A-Z0-9_]+")
_ROW_RE = re.compile(r"^\s*\|(.+)")
_TICKED_RE = re.compile(r"`((?:BYTEPS|DMLC)_[A-Z0-9_]+)`")
_ANY_VAR_RE = re.compile(r"\b((?:BYTEPS|DMLC)_[A-Z0-9_]+)\b")


def _py_env_refs(tree) -> List[Tuple[str, int]]:
    """(var, line) for env-var string literals in READ positions: the
    first argument of any call (``os.environ.get("X")``, ``getenv``,
    the typed ``_env_*`` helpers, local wrappers like the codec
    plane's ``env()``) or a subscript key (``environ["X"]``).
    Deliberately AST-based, not a text regex: a knob quoted in a
    docstring, comment or log message is NOT a read — counting those
    would both raise false undocumented-read findings and keep stale
    env.md rows alive forever (the drift class this rule exists to
    catch)."""
    refs: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args:
            cand = node.args[0]
        elif isinstance(node, ast.Subscript):
            cand = node.slice
        else:
            continue
        if isinstance(cand, ast.Constant) and isinstance(cand.value, str) \
                and _VAR_NAME_RE.fullmatch(cand.value):
            refs.append((cand.value, node.lineno))
    return refs


def _doc_rows(lines: List[str]):
    """(vars, default_cell, line) per table row naming at least one
    env var; header/separator rows carry none."""
    for i, text in enumerate(lines, start=1):
        if not _ROW_RE.match(text):
            continue
        cells = [c.strip() for c in text.strip().strip("|").split("|")]
        if not cells:
            continue
        names = _TICKED_RE.findall(cells[0])
        if names:
            default = cells[1] if len(cells) > 1 else ""
            yield names, default, i


def _config_defaults(project: Project) -> Dict[str, Tuple[object, str]]:
    """var -> (default value, helper name) from config.py's from_env
    reads, with module-level constants resolved."""
    out: Dict[str, Tuple[object, str]] = {}
    cfg = None
    for p in project.py_files():
        if os.path.basename(p) == "config.py":
            cfg = p
            break
    if cfg is None:
        return out
    tree = project.tree(cfg)
    if tree is None:
        return out
    consts: Dict[str, object] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant):
            consts[node.targets[0].id] = node.value.value
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("_env_int", "_env_bool", "_env_str")
                and node.args
                and isinstance(node.args[0], ast.Constant)):
            continue
        var = node.args[0].value
        helper = node.func.id
        default: object = False if helper == "_env_bool" else None
        if len(node.args) > 1:
            d = node.args[1]
            if isinstance(d, ast.Constant):
                default = d.value
            elif isinstance(d, ast.Name) and d.id in consts:
                default = consts[d.id]
            else:
                continue  # computed default: not statically comparable
        elif helper != "_env_bool":
            continue
        out[var] = (default, helper)
    return out


def _default_token(cell: str) -> Optional[str]:
    """First meaningful token of a doc default cell ("0 (off)" -> "0";
    "—" and prose -> None)."""
    cell = cell.replace("`", "").strip()
    if not cell:
        return None
    tok = cell.split()[0]
    return tok if re.fullmatch(r"-?\d+(\.\d+)?", tok) else None


class EnvSyncRule(Rule):
    name = "env-sync"
    doc = ("BYTEPS_*/DMLC_* knobs read in code and rows in docs/env.md "
           "must agree, including config.py defaults")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        env_md = project.doc("env.md")
        if env_md is None:
            return findings  # fixture without docs: nothing to sync

        # -- code references ------------------------------------------ #
        code_refs: Dict[str, Tuple[str, int]] = {}
        for path in project.env_scan_files():
            if path.endswith(".cc"):
                text = project.text(path)
                refs = cpp.getenv_reads(text) if text is not None else []
            else:
                tree = project.tree(path)
                refs = _py_env_refs(tree) if tree is not None else []
            for var, line in refs:
                code_refs.setdefault(var, (project.rel(path), line))

        # -- doc side -------------------------------------------------- #
        doc_lines = project.lines(env_md)
        doc_text = project.text(env_md) or ""
        doc_any = set(_ANY_VAR_RE.findall(doc_text))
        rel_doc = project.rel(env_md)

        # 1: code reads must be documented (anywhere in env.md)
        for var in sorted(code_refs):
            if var not in doc_any:
                path, line = code_refs[var]
                findings.append(Finding(
                    self.name, path, line,
                    f"{var} is read in code but has no mention in "
                    f"docs/env.md — operators cannot discover it"))

        # 2 + 3: table rows must be read, and typed defaults must match
        defaults = _config_defaults(project)
        for names, default_cell, line in _doc_rows(doc_lines):
            for var in names:
                if var not in code_refs:
                    findings.append(Finding(
                        self.name, rel_doc, line,
                        f"docs/env.md documents {var} but nothing in "
                        f"the code reads it — stale row?"))
            if len(names) != 1 or names[0] not in defaults:
                continue
            var = names[0]
            code_default, helper = defaults[var]
            tok = _default_token(default_cell)
            if tok is None:
                continue  # prose default: presence-only
            doc_val = float(tok)
            if helper == "_env_bool":
                code_val = 1.0 if code_default else 0.0
            else:
                try:
                    code_val = float(code_default)
                except (TypeError, ValueError):
                    continue
            if doc_val != code_val:
                findings.append(Finding(
                    self.name, rel_doc, line,
                    f"docs/env.md says {var} defaults to {tok} but "
                    f"config.py says {code_default!r} — fix whichever "
                    f"side drifted"))
        return findings
