"""byteps-lint: project-native static analysis (docs/static-analysis.md).

Run with ``python -m byteps_tpu.tools.lint``; programmatic entry is
``run_lint(root) -> List[Finding]``. Five rules, each encoding an
invariant a past PR enforced only by memory: ``wire-layout``,
``guarded-by``, ``device-thread``, ``env-sync``, ``metrics-schema``.
Per-line suppression: ``# bps-lint: disable=<rule>``.
"""

from .base import Finding, Project, Rule, all_rules, run_lint

__all__ = ["Finding", "Project", "Rule", "all_rules", "run_lint"]
