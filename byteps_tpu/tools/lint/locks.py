"""Rule ``guarded-by``: annotated shared state is only touched under
its lock.

Historical bug class: the scheduler/registry/metrics/codec-plane hot
path accumulates lock-guarded state with every PR (priority pins,
retry parks, codec plans, server loads), and the discipline — WHICH
lock covers WHICH attribute — existed only in comments and reviewers'
heads. PR 5's registry load-accounting imbalance and PR 6's
sibling-failover race were both "touched guarded state on the wrong
side of the lock" bugs found at runtime.

Contract: an attribute assigned in a class body with a trailing
``# guarded-by: <lock>`` comment (``|``/``,`` separates alternatives —
e.g. a Condition and the Lock it wraps) may only be read or written
lexically inside a ``with self.<lock>:`` block of that class.
Exemptions:

- ``__init__`` (construction precedes sharing);
- methods/functions whose name ends in ``_locked`` (the project's
  caller-holds-the-lock convention) — but NOT as a blanket pass: when
  the class's guarded attributes sit under a single lock group, that
  group is what the caller is assumed to hold; when the class mixes
  locks (e.g. ``_mu`` + ``_ingest_mu``), the convention is ambiguous
  and the ``def`` line must say which with ``# caller-holds: <lock>``
  — otherwise touching an attribute guarded by a DIFFERENT lock than
  the caller actually holds would pass silently, which is the exact
  wrong-side-of-the-lock class this rule exists for;
- per-line suppression for documented racy reads
  (``# bps-lint: disable=guarded-by`` with a WHY next to it).

Lexical means lexical: code inside a nested ``def`` runs later on an
unknown thread, so held locks do NOT propagate into it (lambdas and
comprehensions DO keep them — condition-variable predicates run under
the lock).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from .base import Finding, Project, Rule

_ANNOT_RE = re.compile(r"#\s*guarded-by:\s*([\w|,\s]+)")
_HOLDS_RE = re.compile(r"#\s*caller-holds:\s*([\w|,\s]+)")
_SELF_ATTR_RE = re.compile(r"self\.(\w+)")


def _caller_holds(project: Project, path: str, fn) -> Set[str]:
    """Locks a ``# caller-holds: <lock>`` annotation on the ``def``
    line (or the line directly above) says the caller must hold."""
    lines = project.lines(path)
    for ln in (fn.lineno, fn.lineno - 1):
        if 1 <= ln <= len(lines):
            m = _HOLDS_RE.search(lines[ln - 1])
            if m:
                return {tok.strip()
                        for tok in re.split(r"[|,]", m.group(1))
                        if tok.strip()}
    return set()


def _class_annotations(project: Project, path: str, tree: ast.AST,
                       findings: List[Finding]):
    """class name -> {attr: {lock, ...}} from trailing comments. An
    annotation that cannot be bound to a ``self.<attr>`` in a class is
    appended to ``findings`` — a guard comment that protects nothing
    must never silently disarm."""
    lines = project.lines(path)
    rel = project.rel(path)
    spans = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            spans[node.name] = (node.lineno,
                                max(getattr(node, "end_lineno",
                                            node.lineno), node.lineno))
    out: Dict[str, Dict[str, Set[str]]] = {}
    for i, text in enumerate(lines, start=1):
        m = _ANNOT_RE.search(text)
        if not m:
            continue
        # the annotated attribute: same line, the next line when the
        # annotation stands alone (annotation-above style), or the
        # previous line when it trails a wrapped statement
        attr_m = _SELF_ATTR_RE.search(text)
        if attr_m is None:
            if text.lstrip().startswith("#") and i < len(lines):
                attr_m = _SELF_ATTR_RE.search(lines[i])
            elif i >= 2:
                attr_m = _SELF_ATTR_RE.search(lines[i - 2])
        cls_hit = next((cls for cls, (lo, hi) in spans.items()
                        if lo <= i <= hi), None)
        if attr_m and cls_hit is not None:
            locks = {tok.strip()
                     for tok in re.split(r"[|,]", m.group(1))
                     if tok.strip()}
            attrs = out.setdefault(cls_hit, {})
            prev = attrs.get(attr_m.group(1))
            if prev is not None and prev != locks:
                # a re-annotation naming a DIFFERENT lock is author
                # error (a refactor swapped the lock on one site only).
                # FIRST annotation wins for enforcement — unioning
                # would accept either lock, weaker than either
                # annotation alone
                findings.append(Finding(
                    "guarded-by", rel, i,
                    f"conflicting '# guarded-by:' annotations for "
                    f"{cls_hit}.{attr_m.group(1)}: "
                    f"{'|'.join(sorted(locks))} here vs "
                    f"{'|'.join(sorted(prev))} earlier — pick one "
                    f"(the first is enforced until then)"))
            elif prev is None:
                attrs[attr_m.group(1)] = locks
        else:
            what = ("does not sit inside a class body"
                    if attr_m else "binds to no self.<attr> on this, "
                    "the next, or the previous line")
            findings.append(Finding(
                "guarded-by", rel, i,
                f"'# guarded-by:' annotation {what} — it guards "
                f"nothing; attach it to the attribute assignment or "
                f"delete it"))
    return out


class _Checker(ast.NodeVisitor):
    """Walk one method tracking lexically held ``with self.<lock>``
    blocks."""

    def __init__(self, rule: str, rel: str, cls: str,
                 guarded: Dict[str, Set[str]], findings: List[Finding],
                 entry_held: Set[str]):
        self.rule = rule
        self.rel = rel
        self.cls = cls
        self.guarded = guarded
        self.findings = findings
        self.held: Set[str] = set()
        self.entry_held = entry_held
        self.func_stack: List[str] = []

    def run(self, node) -> None:
        """Check the class-body method ``node``, entering with the
        locks its caller is assumed to hold (``set()`` for ordinary
        methods; the caller-holds set for ``*_locked`` ones)."""
        self.func_stack.append(node.name)
        self.held = set(self.entry_held)
        for stmt in node.body:
            self.visit(stmt)
        self.func_stack.pop()

    # -- lock tracking -------------------------------------------------- #

    @staticmethod
    def _lock_name(expr: ast.AST):
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            return expr.attr
        return None

    def visit_With(self, node: ast.With) -> None:
        added = set()
        for item in node.items:
            name = self._lock_name(item.context_expr)
            if name is not None and name not in self.held:
                added.add(name)
        self.held |= added
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    # -- scope boundaries ----------------------------------------------- #

    def _visit_func(self, node) -> None:
        saved, self.held = self.held, set()
        self.func_stack.append(node.name)
        for stmt in node.body:
            self.visit(stmt)
        self.func_stack.pop()
        self.held = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_func(node)

    # -- guarded accesses ----------------------------------------------- #

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and node.attr in self.guarded:
            fn = self.func_stack[-1] if self.func_stack else "?"
            locks = self.guarded[node.attr]
            if fn != "__init__" and not (locks & self.held):
                hint = ""
                if fn.endswith("_locked") and len(self.func_stack) == 1 \
                        and not self.entry_held:
                    # the caller-holds convention did not cover this
                    # attribute's lock — the class mixes lock groups,
                    # so WHICH lock the caller holds must be spelled out
                    hint = (" (the class mixes lock groups, so the "
                            "*_locked convention is ambiguous here — "
                            "annotate the def with '# caller-holds: "
                            "<lock>')")
                self.findings.append(Finding(
                    "guarded-by", self.rel, node.lineno,
                    f"{self.cls}.{node.attr} is guarded-by "
                    f"{'|'.join(sorted(locks))} but {fn}() touches it "
                    f"without holding the lock{hint}"))
        self.generic_visit(node)


class GuardedByRule(Rule):
    name = "guarded-by"
    doc = ("attributes annotated '# guarded-by: <lock>' may only be "
           "accessed inside 'with self.<lock>:' in their class")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for path in project.py_files():
            tree = project.tree(path)
            if tree is None:
                continue
            annots = _class_annotations(project, path, tree, findings)
            if not annots:
                continue
            rel = project.rel(path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                guarded = annots.get(node.name)
                if not guarded:
                    continue
                # the lock set a *_locked method's caller is assumed
                # to hold: the intersection of every guarded attr's
                # alternatives. Non-empty (e.g. {_mu} across '_mu' and
                # '_mu|_cv' — one lock family) means one lock satisfies
                # every attr, so the bare convention stays unambiguous;
                # empty (truly mixed locks, '_mu' vs '_ingest_mu') forces
                # an explicit '# caller-holds:' annotation
                single = set.intersection(
                    *(set(locks) for locks in guarded.values()))
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        entry_held: Set[str] = set()
                        if stmt.name.endswith("_locked"):
                            entry_held = (_caller_holds(project, path,
                                                        stmt) or single)
                        checker = _Checker(self.name, rel, node.name,
                                           guarded, findings, entry_held)
                        checker.run(stmt)
        return findings
