"""CLI: ``python -m byteps_tpu.tools.lint [--root DIR] [--rules a,b]``.

Exit codes (pinned by tests/test_lint.py): 0 clean, 1 findings,
2 usage error. Finding format: ``path:line: [rule] message``.
"""

from __future__ import annotations

import argparse
import os
import sys

from .base import all_rules, run_lint


def _repo_root() -> str:
    # byteps_tpu/tools/lint -> byteps_tpu/tools -> byteps_tpu -> repo
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(os.path.dirname(here)))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="byteps-lint",
        description="project-native static analysis "
                    "(docs/static-analysis.md)")
    parser.add_argument("--root", default=_repo_root(),
                        help="tree to lint (default: this repo)")
    parser.add_argument("--rules", default="",
                        help="comma-separated subset of rules")
    parser.add_argument("--list", action="store_true",
                        help="list rules and exit")
    args = parser.parse_args(argv)

    if args.list:
        for rule in all_rules():
            print(f"{rule.name}: {rule.doc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        findings = run_lint(args.root, rules or None)
    except ValueError as e:
        print(f"byteps-lint: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.format())
    n_rules = len(rules) if rules else len(all_rules())
    if findings:
        print(f"byteps-lint: {len(findings)} finding(s) "
              f"({n_rules} rule(s) run)")
        return 1
    print(f"byteps-lint: clean ({n_rules} rule(s) run)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
