"""Rule ``wire-layout``: the cross-language wire header cannot drift.

Historical bug class: the ``MsgHeader`` layout drifted twice already —
36B -> 40B when the replay epoch landed (PR 6) and magic
``0xB17E5001`` -> ``0xB17E5002`` when the codec tag landed (PR 9).
Each time, every mirror (Python header constants, codec-id table,
dtype codes) had to be found and updated by memory; a missed one
means payload bytes misparsed as headers, or worse, dense bytes
silently summed with codec payloads. This rule re-derives the layout
from ``native/ps.cc`` (the ground truth: field list, ``static_assert``
size, ``kMagic``, ``WireCodec``/``DType`` enums) and fails on ANY
disagreement with the Python side, in both directions:

- ``server/client.py`` ``WIRE_MAGIC`` / ``WIRE_HEADER_FMT`` /
  ``WIRE_HEADER_BYTES`` — size, field order and magic;
- ``core/codec_plane.py`` ``WIRE_CODEC_IDS`` — every codec name/id;
- ``core/types.py`` ``DataType`` — every wire dtype code.
"""

from __future__ import annotations

import ast
import struct
from typing import Dict, List, Optional, Tuple

from . import cpp
from .base import Finding, Project, Rule

# DType enum name (ps.cc) per DataType member name (core/types.py).
_DTYPE_TRANSLATE = (
    ("BFLOAT16", "BF16"), ("FLOAT", "F"), ("UINT", "U"), ("INT", "I"),
)


def _py_to_cc_dtype(py_name: str) -> str:
    for old, new in _DTYPE_TRANSLATE:
        if py_name.startswith(old):
            return new + py_name[len(old):]
    return py_name


def _module_constants(tree: ast.AST) -> Dict[str, Tuple[ast.AST, int]]:
    out: Dict[str, Tuple[ast.AST, int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = (node.value, node.lineno)
    return out


class WireLayoutRule(Rule):
    name = "wire-layout"
    doc = ("native/ps.cc MsgHeader layout, magic and codec/dtype ids "
           "must agree with every Python mirror (the 36B->40B drift "
           "class)")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        src = project.native_source()
        if src is None:
            return findings  # no native tier in this tree: nothing to pin
        text = project.text(src) or ""
        rel_cc = project.rel(src)
        hdr = cpp.parse_header(text)
        if hdr is None:
            findings.append(Finding(
                self.name, rel_cc, 1,
                "cannot parse struct MsgHeader out of the native source "
                "— the wire contract is unverifiable"))
            return findings

        # internal consistency of the C++ side first
        if hdr.computed_size is None:
            findings.append(Finding(
                self.name, rel_cc, hdr.line,
                "MsgHeader contains a non-fixed-width field type; the "
                "wire header must use uint8_t..uint64_t only"))
            return findings
        if hdr.asserted_size is None:
            findings.append(Finding(
                self.name, rel_cc, hdr.line,
                f"missing static_assert(sizeof(MsgHeader) == "
                f"{hdr.computed_size}) next to the struct"))
        elif hdr.asserted_size != hdr.computed_size:
            findings.append(Finding(
                self.name, rel_cc, hdr.assert_line,
                f"static_assert says sizeof(MsgHeader) == "
                f"{hdr.asserted_size} but the declared fields sum to "
                f"{hdr.computed_size}"))
        if hdr.magic is None:
            findings.append(Finding(
                self.name, rel_cc, 1, "kMagic constant not found"))

        findings += self._check_header_mirror(project, hdr, rel_cc)
        findings += self._check_codec_ids(project, text, rel_cc)
        findings += self._check_dtypes(project, text, rel_cc)
        findings += self._check_ipc_desc(project, text, rel_cc)
        findings += self._check_slot_manifest(
            project, text, rel_cc, "kStatSlotNames", "_STAT_SLOTS")
        findings += self._check_slot_manifest(
            project, text, rel_cc, "kTraceRecFields", "_TRACE_REC_FIELDS",
            struct_name="TraceRec", fmt_const="TRACE_REC_FMT")
        findings += self._check_slot_manifest(
            project, text, rel_cc, "kFlightRecFields",
            "_FLIGHT_REC_FIELDS", struct_name="FlightRec",
            fmt_const="FLIGHT_REC_FMT")
        findings += self._check_slot_manifest(
            project, text, rel_cc, "kHealthRecFields",
            "_HEALTH_REC_FIELDS", struct_name="HealthRec",
            fmt_const="HEALTH_REC_FMT")
        findings += self._check_slot_manifest(
            project, text, rel_cc, "kStripeRecFields",
            "_STRIPE_REC_FIELDS", struct_name="StripeRec",
            fmt_const="STRIPE_REC_FMT")
        findings += self._check_ts_fields(project)
        findings += self._check_dict_enum(
            project, text, rel_cc, "WIRE_CTRL_OPS", "Op",
            "a skewed control op id reaches the server as an unknown op")
        findings += self._check_dict_enum(
            project, text, rel_cc, "WIRE_CTRL_LIMITS", "CtrlLimits",
            "a skewed drain limit makes control replies overflow the "
            "client buffer and drain silently empty")
        return findings

    # -- slot/record-layout manifests (bps_server_stats, trace ring,
    #    flight ring) ---------------------------------------------------- #

    def _find_tuple_const(self, project: Project, const: str):
        """Locate a module-level tuple/list-of-str constant mirror."""
        for p in project.py_files():
            tree = project.tree(p)
            if tree is None:
                continue
            node_line = _module_constants(tree).get(const)
            if node_line is None:
                continue
            node, line = node_line
            if isinstance(node, (ast.Tuple, ast.List)):
                vals = [e.value for e in node.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                if len(vals) == len(node.elts):
                    return p, line, vals
            return p, line, None  # exists but not a str tuple
        return None, 0, None

    def _check_slot_manifest(self, project: Project, cc_text: str,
                             rel_cc: str, cc_name: str, py_name: str,
                             struct_name: Optional[str] = None,
                             fmt_const: Optional[str] = None
                             ) -> List[Finding]:
        """The append-only slot/field contracts between ps.cc and the
        Python mirrors — until PR 12 enforced only by a comment
        (``_STAT_SLOTS``: "append-only contract with native/ps.cc").
        Parses the native name manifest and diffs it against the
        Python tuple BOTH directions (missing mirror, missing
        manifest, reorder/rename/truncation all fail); for the packed
        record layouts additionally pins the struct's static_assert
        size against the mirror's struct-format size (the 40B-header
        drift class, applied to the ring records)."""
        findings: List[Finding] = []
        parsed = cpp.parse_name_array(cc_text, cc_name)
        path, line, vals = self._find_tuple_const(project, py_name)
        if parsed is None and path is None:
            return findings  # neither side: tree predates this plane
        if parsed is None:
            findings.append(Finding(
                self.name, project.rel(path), line,
                f"{py_name} exists but native {cc_name} manifest was "
                f"not found — the slot layout is unverifiable"))
            return findings
        cc_slots, cc_line = parsed
        if path is None:
            findings.append(Finding(
                self.name, rel_cc, cc_line,
                f"native {cc_name} exists but no Python {py_name} "
                f"mirror was found"))
            return findings
        rel = project.rel(path)
        if vals is None:
            findings.append(Finding(
                self.name, rel, line,
                f"{py_name} is not a tuple/list of str literals"))
            return findings
        if vals != cc_slots:
            # name the FIRST divergence: reorders/renames/truncations
            # all violate the append-only contract
            i = next((i for i, (a, b) in enumerate(zip(vals, cc_slots))
                      if a != b), min(len(vals), len(cc_slots)))
            a = vals[i] if i < len(vals) else "<missing>"
            b = cc_slots[i] if i < len(cc_slots) else "<missing>"
            findings.append(Finding(
                self.name, rel, line,
                f"{py_name} disagrees with native {cc_name} at slot "
                f"{i}: python {a!r} vs native {b!r} (append-only "
                f"contract; {len(vals)} vs {len(cc_slots)} slots)"))
        if struct_name and fmt_const:
            rec = cpp.parse_header(cc_text, struct_name)
            fmt_path, fmt_line, _ = self._find_tuple_const(
                project, fmt_const)  # tuple lookup misses str consts
            fmt_val = None
            for p in project.py_files():
                tree = project.tree(p)
                if tree is None:
                    continue
                node_line = _module_constants(tree).get(fmt_const)
                if node_line and isinstance(node_line[0], ast.Constant) \
                        and isinstance(node_line[0].value, str):
                    fmt_path, fmt_line = p, node_line[1]
                    fmt_val = node_line[0].value
                    break
            if rec is not None and rec.asserted_size is not None \
                    and fmt_val is not None:
                try:
                    size = struct.calcsize(fmt_val)
                except struct.error:
                    size = -1
                if size != rec.asserted_size:
                    findings.append(Finding(
                        self.name, project.rel(fmt_path), fmt_line,
                        f"{fmt_const} packs {size} bytes but native "
                        f"{struct_name} is {rec.asserted_size} bytes"))
            elif rec is not None and fmt_val is None:
                findings.append(Finding(
                    self.name, rel, line,
                    f"native {struct_name} exists but no {fmt_const} "
                    f"struct-format mirror was found"))
        return findings

    # -- time-series field manifest <-> StepReport dataclass ----------- #

    def _check_ts_fields(self, project: Project) -> List[Finding]:
        """Every name in ``_TS_STEP_FIELDS`` (core/timeseries.py) must
        be a ``StepReport`` dataclass field — the drift class where a
        field rename silently kills its per-step series (the recorder
        samples via getattr with a None default, so nothing raises)."""
        findings: List[Finding] = []
        path, line, vals = self._find_tuple_const(
            project, "_TS_STEP_FIELDS")
        if path is None:
            return findings  # tree predates the time-series plane
        rel = project.rel(path)
        if vals is None:
            findings.append(Finding(
                self.name, rel, line,
                "_TS_STEP_FIELDS is not a tuple/list of str literals"))
            return findings
        fields: set = set()
        for p in project.py_files():
            tree = project.tree(p)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef) \
                        and node.name == "StepReport":
                    for st in node.body:
                        if isinstance(st, ast.AnnAssign) and isinstance(
                                st.target, ast.Name):
                            fields.add(st.target.id)
            if fields:
                break
        if not fields:
            findings.append(Finding(
                self.name, rel, line,
                "_TS_STEP_FIELDS exists but no StepReport dataclass "
                "was found — the series manifest is unverifiable"))
            return findings
        for name_ in vals:
            if name_ not in fields:
                findings.append(Finding(
                    self.name, rel, line,
                    f"_TS_STEP_FIELDS names {name_!r} which is not a "
                    f"StepReport field — its series would silently "
                    f"never record"))
        return findings

    # -- Python dict mirror <-> native enum (WIRE_CTRL_OPS <-> enum Op,
    #    WIRE_CTRL_LIMITS <-> enum CtrlLimits) -------------------------- #

    def _check_dict_enum(self, project: Project, cc_text: str,
                         rel_cc: str, dict_name: str, enum_name: str,
                         consequence: str) -> List[Finding]:
        """Every entry of the Python dict mirror must match the native
        enum member of the same name, by value."""
        findings: List[Finding] = []
        enum = cpp.parse_enum(cc_text, enum_name)
        table: Dict[str, int] = {}
        path = line = None
        for p in project.py_files():
            tree = project.tree(p)
            if tree is None:
                continue
            node_line = _module_constants(tree).get(dict_name)
            if node_line and isinstance(node_line[0], ast.Dict):
                path, line = p, node_line[1]
                for k, v in zip(node_line[0].keys, node_line[0].values):
                    if isinstance(k, ast.Constant) and isinstance(
                            v, ast.Constant):
                        table[k.value] = v.value
                break
        if not table:
            return findings  # tree predates this mirror
        rel = project.rel(path)
        if not enum:
            findings.append(Finding(
                self.name, rel, line,
                f"{dict_name} exists but native enum {enum_name} was "
                f"not found"))
            return findings
        for name_, val in sorted(table.items()):
            if name_ not in enum:
                findings.append(Finding(
                    self.name, rel, line,
                    f"{dict_name}[{name_!r}] has no native enum "
                    f"{enum_name} member of that name"))
            elif enum[name_] != val:
                findings.append(Finding(
                    self.name, rel, line,
                    f"{dict_name}[{name_!r}] = {val} but native "
                    f"{enum_name}::{name_} = {enum[name_]} — "
                    f"{consequence}"))
        return findings

    # -- IpcDesc (shm descriptor-ring framing) ------------------------- #

    def _check_ipc_desc(self, project: Project, cc_text: str,
                        rel_cc: str) -> List[Finding]:
        """The out-of-band descriptor that rides the shm ring in place
        of large payloads. It never crosses a language boundary (both
        ring endpoints are the same .so), so a Python mirror is
        OPTIONAL — but the struct itself must stay machine-checkable
        (fixed-width fields + a matching static_assert, the same
        internal-consistency bar as MsgHeader), and IF a mirror
        (``IPC_DESC_FMT``) exists anywhere it must pack to the same
        size. Guards the 8B->16B drift class inside the C++ side."""
        findings: List[Finding] = []
        desc = cpp.parse_header(cc_text, "IpcDesc")
        if desc is None:
            return findings  # tree predates the descriptor tier
        if desc.computed_size is None:
            findings.append(Finding(
                self.name, rel_cc, desc.line,
                "IpcDesc contains a non-fixed-width field type; ring "
                "framing must use uint8_t..uint64_t only"))
            return findings
        if desc.asserted_size is None:
            findings.append(Finding(
                self.name, rel_cc, desc.line,
                f"missing static_assert(sizeof(IpcDesc) == "
                f"{desc.computed_size}) next to the struct"))
        elif desc.asserted_size != desc.computed_size:
            findings.append(Finding(
                self.name, rel_cc, desc.assert_line,
                f"static_assert says sizeof(IpcDesc) == "
                f"{desc.asserted_size} but the declared fields sum to "
                f"{desc.computed_size}"))
        for path in project.py_files():
            tree = project.tree(path)
            if tree is None:
                continue
            node_line = _module_constants(tree).get("IPC_DESC_FMT")
            if node_line is None:
                continue
            node, line = node_line
            rel = project.rel(path)
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                findings.append(Finding(
                    self.name, rel, line,
                    "IPC_DESC_FMT is not a str literal"))
                continue
            try:
                size = struct.calcsize(node.value)
            except struct.error:
                findings.append(Finding(
                    self.name, rel, line,
                    f"IPC_DESC_FMT {node.value!r} is not a valid "
                    f"struct format"))
                continue
            if size != desc.computed_size:
                findings.append(Finding(
                    self.name, rel, line,
                    f"IPC_DESC_FMT packs {size} bytes but native "
                    f"IpcDesc is {desc.computed_size} bytes"))
        return findings

    # -- WIRE_MAGIC / WIRE_HEADER_FMT / WIRE_HEADER_BYTES -------------- #

    def _find_mirror(self, project: Project):
        """Locate the Python module declaring the header mirror."""
        for path in project.py_files():
            tree = project.tree(path)
            if tree is None:
                continue
            consts = _module_constants(tree)
            if "WIRE_HEADER_FMT" in consts or "WIRE_MAGIC" in consts:
                return path, consts
        return None, {}

    def _check_header_mirror(self, project: Project, hdr: cpp.HeaderInfo,
                             rel_cc: str) -> List[Finding]:
        findings: List[Finding] = []
        path, consts = self._find_mirror(project)
        if path is None:
            findings.append(Finding(
                self.name, rel_cc, hdr.line,
                "no Python wire-header mirror found (expected "
                "WIRE_MAGIC / WIRE_HEADER_FMT / WIRE_HEADER_BYTES in "
                "server/client.py)"))
            return findings
        rel = project.rel(path)

        def const_int(name: str) -> Tuple[Optional[int], int]:
            node_line = consts.get(name)
            if node_line is None:
                return None, 0
            node, line = node_line
            if isinstance(node, ast.Constant) and isinstance(
                    node.value, int):
                return node.value, line
            return None, line

        magic, magic_line = const_int("WIRE_MAGIC")
        if magic is None:
            findings.append(Finding(
                self.name, rel, magic_line or 1,
                "WIRE_MAGIC missing or not an int literal"))
        elif hdr.magic is not None and magic != hdr.magic:
            findings.append(Finding(
                self.name, rel, magic_line,
                f"WIRE_MAGIC is {magic:#010x} but native kMagic is "
                f"{hdr.magic:#010x} — a magic bump must land on both "
                f"sides in the same commit"))

        fmt_node = consts.get("WIRE_HEADER_FMT")
        expected_fmt = hdr.fmt
        if fmt_node is None or not (
                isinstance(fmt_node[0], ast.Constant)
                and isinstance(fmt_node[0].value, str)):
            findings.append(Finding(
                self.name, rel, 1,
                "WIRE_HEADER_FMT missing or not a str literal"))
        else:
            fmt, fmt_line = fmt_node[0].value, fmt_node[1]
            try:
                fmt_size = struct.calcsize(fmt)
            except struct.error:
                fmt_size = -1
                findings.append(Finding(
                    self.name, rel, fmt_line,
                    f"WIRE_HEADER_FMT {fmt!r} is not a valid struct "
                    f"format"))
            if expected_fmt is not None and fmt != expected_fmt \
                    and fmt_size >= 0:
                findings.append(Finding(
                    self.name, rel, fmt_line,
                    f"WIRE_HEADER_FMT {fmt!r} disagrees with the native "
                    f"field order {expected_fmt!r} "
                    f"({', '.join(f'{t} {n}' for t, n in hdr.fields)})"))
            elif fmt_size >= 0 and hdr.asserted_size is not None \
                    and fmt_size != hdr.asserted_size:
                findings.append(Finding(
                    self.name, rel, fmt_line,
                    f"WIRE_HEADER_FMT packs {fmt_size} bytes but the "
                    f"native header is {hdr.asserted_size} bytes"))

        size, size_line = const_int("WIRE_HEADER_BYTES")
        if size is None:
            findings.append(Finding(
                self.name, rel, size_line or 1,
                "WIRE_HEADER_BYTES missing or not an int literal"))
        elif hdr.asserted_size is not None and size != hdr.asserted_size:
            findings.append(Finding(
                self.name, rel, size_line,
                f"WIRE_HEADER_BYTES is {size} but the native header is "
                f"{hdr.asserted_size} bytes (the 36B->40B drift class)"))
        return findings

    # -- WIRE_CODEC_IDS <-> enum WireCodec ----------------------------- #

    def _check_codec_ids(self, project: Project, cc_text: str,
                         rel_cc: str) -> List[Finding]:
        findings: List[Finding] = []
        enum = cpp.parse_enum(cc_text, "WireCodec")
        table: Dict[str, int] = {}
        path = line = None
        for p in project.py_files():
            tree = project.tree(p)
            if tree is None:
                continue
            node_line = _module_constants(tree).get("WIRE_CODEC_IDS")
            if node_line and isinstance(node_line[0], ast.Dict):
                path, line = p, node_line[1]
                for k, v in zip(node_line[0].keys, node_line[0].values):
                    if isinstance(k, ast.Constant) and isinstance(
                            v, ast.Constant):
                        table[k.value] = v.value
                break
        if not enum and not table:
            return findings  # neither side has the adaptive plane
        if not table:
            findings.append(Finding(
                self.name, rel_cc, 1,
                "native enum WireCodec exists but no Python "
                "WIRE_CODEC_IDS mirror was found"))
            return findings
        rel = project.rel(path)
        if not enum:
            findings.append(Finding(
                self.name, rel, line,
                "WIRE_CODEC_IDS exists but native enum WireCodec was "
                "not found"))
            return findings
        for name, val in sorted(table.items()):
            cc_name = "kCodec" + name.capitalize()
            if cc_name not in enum:
                findings.append(Finding(
                    self.name, rel, line,
                    f"WIRE_CODEC_IDS[{name!r}] has no native enum "
                    f"counterpart {cc_name}"))
            elif enum[cc_name] != val:
                findings.append(Finding(
                    self.name, rel, line,
                    f"WIRE_CODEC_IDS[{name!r}] = {val} but native "
                    f"{cc_name} = {enum[cc_name]} — id skew would make "
                    f"the server validate the wrong codec tag"))
        for cc_name, val in sorted(enum.items()):
            if cc_name == "kCodecUntagged":
                continue
            py_name = cc_name[len("kCodec"):].lower()
            if py_name not in table:
                findings.append(Finding(
                    self.name, rel, line,
                    f"native {cc_name} = {val} has no WIRE_CODEC_IDS "
                    f"entry {py_name!r}"))
        return findings

    # -- DataType <-> enum DType --------------------------------------- #

    def _check_dtypes(self, project: Project, cc_text: str,
                      rel_cc: str) -> List[Finding]:
        findings: List[Finding] = []
        enum = cpp.parse_enum(cc_text, "DType")
        if not enum:
            return findings
        py: Dict[str, Tuple[int, int]] = {}
        path = None
        for p in project.py_files():
            tree = project.tree(p)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef) \
                        and node.name == "DataType":
                    for st in node.body:
                        if isinstance(st, ast.Assign) and isinstance(
                                st.targets[0], ast.Name) and isinstance(
                                st.value, ast.Constant) and isinstance(
                                st.value.value, int):
                            py[st.targets[0].id] = (st.value.value,
                                                    st.lineno)
                    path = p
                    break
            if path:
                break
        if not py:
            return findings  # fixture without a DataType mirror
        rel = project.rel(path)
        for py_name, (val, line) in sorted(py.items()):
            cc_name = _py_to_cc_dtype(py_name)
            if cc_name not in enum:
                continue  # host-only dtypes need no wire code
            if enum[cc_name] != val:
                findings.append(Finding(
                    self.name, rel, line,
                    f"DataType.{py_name} = {val} but native DType::"
                    f"{cc_name} = {enum[cc_name]} — dtype code skew "
                    f"folds payloads with the wrong element width"))
        return findings
