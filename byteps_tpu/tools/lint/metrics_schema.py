"""Rule ``metrics-schema``: instrument names in code and the
machine-checked ```schema block in docs/observability.md agree, both
directions.

Historical bug class: the PR 3 runtime liveness guard
(``tests/test_metrics.py::test_documented_schema_is_live``) catches a
documented path that fails to resolve in a LIVE snapshot — but only
for instruments a dense CPU train step happens to create, and only
docs->code. A counter created in code but never documented (or a
schema row that only a TPU/sharded run would instantiate) sails
through. This rule closes it statically:

- every ``counters.X`` / ``gauges.X`` / ``histograms.X`` schema entry
  must correspond to a ``.counter("X")`` / ``.gauge("X")`` /
  ``.histogram("X")`` creation site in code — exact literal, or the
  literal prefix of an f-string site (``f"codec/active/{tier}"``
  covers ``codec/active/dense``);
- every static instrument literal in code must appear in the schema
  block of the same kind; every dynamic (f-string) site must have at
  least one documented instance of its prefix.

``arena.*`` / ``steps.*`` entries are live-collected sections, owned
by the runtime guard, and skipped here.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .base import Finding, Project, Rule

_KINDS = {"counter": "counters", "gauge": "gauges",
          "histogram": "histograms"}
_SCHEMA_RE = re.compile(r"```schema\n(.*?)```", re.S)


def _schema_entries(project: Project, path: str):
    """(kind, name, line) from the fenced schema block."""
    text = project.text(path) or ""
    m = _SCHEMA_RE.search(text)
    if not m:
        return None
    start_line = text.count("\n", 0, m.start(1)) + 1
    out = []
    for i, row in enumerate(m.group(1).splitlines()):
        row = row.strip()
        if not row:
            continue
        kind, _, name = row.partition(".")
        out.append((kind, name, start_line + i))
    return out


def _receiver_is_tracer(func: ast.Attribute) -> bool:
    v = func.value
    name = v.id if isinstance(v, ast.Name) else (
        v.attr if isinstance(v, ast.Attribute) else "")
    return "tracer" in name.lower()


def _instrument_sites(project: Project):
    """static: (kind, name) -> (rel, line); dynamic: (kind, prefix) ->
    (rel, line) for f-string creation sites."""
    static: Dict[Tuple[str, str], Tuple[str, int]] = {}
    dynamic: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for path in project.py_files():
        tree = project.tree(path)
        if tree is None:
            continue
        rel = project.rel(path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _KINDS
                    and node.args):
                continue
            if _receiver_is_tracer(node.func):
                continue  # Chrome-trace counter events, not instruments
            kind = _KINDS[node.func.attr]
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str):
                static.setdefault((kind, arg.value), (rel, node.lineno))
            elif isinstance(arg, ast.JoinedStr):
                prefix = ""
                for part in arg.values:
                    if isinstance(part, ast.Constant):
                        prefix += str(part.value)
                    else:
                        break
                if prefix:
                    dynamic.setdefault((kind, prefix),
                                       (rel, node.lineno))
    return static, dynamic


class MetricsSchemaRule(Rule):
    name = "metrics-schema"
    doc = ("instrument names created in code and the ```schema block "
           "in docs/observability.md must agree, both directions")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        obs = project.doc("observability.md")
        if obs is None:
            return findings  # fixture without docs
        rel_doc = project.rel(obs)
        entries = _schema_entries(project, obs)
        if entries is None:
            findings.append(Finding(
                self.name, rel_doc, 1,
                "docs/observability.md lost its ```schema block — the "
                "snapshot contract is unverifiable"))
            return findings
        static, dynamic = _instrument_sites(project)
        if not static and not dynamic:
            return findings  # fixture tree without instrumented code

        doc_names: Dict[str, Set[str]] = {k: set() for k in
                                          _KINDS.values()}
        for kind, name_, _line in entries:
            if kind in doc_names:
                doc_names[kind].add(name_)

        # docs -> code: every schema instrument must be creatable
        for kind, name_, line in entries:
            if kind not in doc_names:
                continue  # arena./steps. sections: runtime guard's job
            if (kind, name_) in static:
                continue
            if any(dk == kind and name_.startswith(prefix)
                   for (dk, prefix) in dynamic):
                continue
            findings.append(Finding(
                self.name, rel_doc, line,
                f"schema documents {kind}.{name_} but no "
                f".{_kind_method(kind)}() site in the code creates it"))

        # code -> docs: every creation site must be documented
        for (kind, name_), (rel, line) in sorted(static.items()):
            if name_ not in doc_names[kind]:
                findings.append(Finding(
                    self.name, rel, line,
                    f"{kind[:-1]} {name_!r} is created in code but "
                    f"missing from the docs/observability.md schema "
                    f"block"))
        for (kind, prefix), (rel, line) in sorted(dynamic.items()):
            if not any(n.startswith(prefix) for n in doc_names[kind]):
                findings.append(Finding(
                    self.name, rel, line,
                    f"dynamic {kind[:-1]} family {prefix!r}* has no "
                    f"documented instance in the schema block"))
        return findings


def _kind_method(kind: str) -> str:
    return {v: k for k, v in _KINDS.items()}[kind]
