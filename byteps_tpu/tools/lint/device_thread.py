"""Rule ``device-thread``: io_callback taps must not block.

Historical bug class (PR 2, found the hard way): a function handed to
``jax.experimental.io_callback`` / ``pure_callback`` runs on the
device-dispatch thread, and its array arguments are LAZY — touching
one (``int(arr)``, ``np.asarray(arr)``) re-enters the very executor
running the tapped program and self-deadlocks the step at the next
collective. The same goes for any blocking call (lock acquisition,
``Future.result()``, ``queue.get()``, condition waits): the tap must
only ENQUEUE to a worker thread and return.

The rule resolves the callback argument of every
``io_callback(f, ...)`` / ``pure_callback(f, ...)`` call (a bare
function name, ``self.<method>``, a lambda, or any of those behind
``functools.partial(f, ...)``) and scans that function's body —
lexically, not transitively — for:

- materialization of a tap parameter: ``int``/``float``/``bool``/
  ``np.asarray``/``np.array``/``np.copy`` applied to a parameter, or
  ``param.item()`` / ``param.tolist()`` / ``param.block_until_ready()``;
- blocking calls: ``with`` on a lock-ish attribute, ``.acquire()``,
  ``.result()``, ``.wait()``/``.wait_for()``, zero-positional-arg
  ``.join()`` (the Thread.join shape — ``"/".join(parts)`` and
  ``os.path.join(...)`` carry args and are not flagged) and
  zero-positional-arg ``.get()`` (the queue signature), ``time.sleep``.

A tap the rule CANNOT resolve to a function defined in the same module
is itself a finding, never a silent pass: an unscannable tap is exactly
where the next PR 2 deadlock hides. Define the tap locally (the
project convention) or suppress at the registration site with a WHY.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .base import Finding, Project, Rule

_CALLBACK_NAMES = {"io_callback", "pure_callback"}
_MATERIALIZE_BUILTINS = {"int", "float", "bool"}
_MATERIALIZE_NP = {"asarray", "array", "copy"}
_MATERIALIZE_METHODS = {"item", "tolist", "block_until_ready"}
# .acquire()/.result()/.wait()/.wait_for() have no common non-blocking
# homonyms; .join() does (str.join, os.path.join), so it only counts
# when called with no positional args (the Thread.join() shape) on a
# non-literal receiver.
_BLOCKING_METHODS = {"acquire", "result", "wait", "wait_for"}
_LOCKISH = ("lock", "mu", "cv", "sem", "cond")
# Receivers whose lambda arguments run LATER on another thread — only
# these defer; a lambda anywhere else in the tap (sorted key=, an
# immediately-invoked (lambda: ...)()) executes on the device thread
# and is scanned like inline code.
_DEFER_CALLEES = {"submit", "put", "put_nowait", "add_done_callback",
                  "call_soon", "call_soon_threadsafe", "apply_async",
                  "defer", "Thread", "Timer"}


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _tap_ref(call: ast.Call) -> Optional[ast.AST]:
    """The AST node a tap call registers as its callback: the first
    positional arg or the ``callback=`` keyword, seen through
    ``functools.partial``."""
    arg: Optional[ast.AST] = call.args[0] if call.args else None
    if arg is None:
        for kw in call.keywords:
            if kw.arg == "callback":
                arg = kw.value
                break
    if arg is None:
        return None
    if isinstance(arg, ast.Call) and _callee_name(arg.func) == "partial" \
            and arg.args:
        arg = arg.args[0]
    return arg


def _ref_name(ref: ast.AST) -> Optional[str]:
    """Local def name a callback ref resolves to: a bare name or a
    ``self.<method>`` attribute (methods land in the same per-module
    def table)."""
    if isinstance(ref, ast.Name):
        return ref.id
    if isinstance(ref, ast.Attribute) and isinstance(ref.value, ast.Name) \
            and ref.value.id == "self":
        return ref.attr
    return None


class _TapScan(ast.NodeVisitor):
    """Scan one tap body (a FunctionDef or a Lambda)."""

    def __init__(self, rel: str, fn: ast.AST, findings: List[Finding]):
        self.rel = rel
        self.fn = fn
        self.name = getattr(fn, "name", "<lambda>")
        self.findings = findings
        self.params: Set[str] = {a.arg for a in fn.args.args
                                 + fn.args.posonlyargs
                                 + fn.args.kwonlyargs}
        self._deferred: Set[ast.Lambda] = set()

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "device-thread", self.rel, node.lineno,
            f"tap function {self.name}() {what} — io_callback taps "
            f"run on the device-dispatch thread and must only enqueue "
            f"(materializing a lazy callback arg or blocking here "
            f"self-deadlocks the step at the next collective; PR 2)"))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.fn:
            self.generic_visit(node)
        # nested defs are not executed on the device thread

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda handed to a deferral site (pool.submit(lambda:
        # q.get())) runs later on a worker thread, like a nested def;
        # any other lambda (sorted key=, an immediately-invoked
        # (lambda: ...)()) executes right here on the device thread
        if node is self.fn or node not in self._deferred:
            self.generic_visit(node)

    def _is_param(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.params

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            expr = item.context_expr
            name = None
            if isinstance(expr, ast.Attribute):
                name = expr.attr
            elif isinstance(expr, ast.Name):
                name = expr.id
            if name is not None and any(t in name.lower()
                                        for t in _LOCKISH):
                self._flag(node, f"acquires lock {name!r}")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        name = _callee_name(func)
        if name in _DEFER_CALLEES:
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                # partial is transparent here exactly as it is when
                # resolving the tap callback itself (_tap_ref)
                if isinstance(arg, ast.Call) \
                        and _callee_name(arg.func) == "partial":
                    for inner in list(arg.args) + [kw.value for kw
                                                   in arg.keywords]:
                        if isinstance(inner, ast.Lambda):
                            self._deferred.add(inner)
                elif isinstance(arg, ast.Lambda):
                    self._deferred.add(arg)
        if isinstance(func, ast.Name):
            if name in _MATERIALIZE_BUILTINS and node.args \
                    and self._is_param(node.args[0]):
                self._flag(node, f"materializes parameter "
                                 f"{node.args[0].id!r} via {name}()")
        elif isinstance(func, ast.Attribute):
            recv = func.value
            if name in _MATERIALIZE_NP and isinstance(recv, ast.Name) \
                    and recv.id in ("np", "numpy", "jnp") and node.args \
                    and self._is_param(node.args[0]):
                self._flag(node, f"materializes parameter "
                                 f"{node.args[0].id!r} via "
                                 f"{recv.id}.{name}()")
            elif name in _MATERIALIZE_METHODS and self._is_param(recv):
                self._flag(node, f"materializes parameter {recv.id!r} "
                                 f"via .{name}()")
            elif name in _BLOCKING_METHODS and not isinstance(
                    recv, ast.Constant):
                self._flag(node, f"calls blocking .{name}()")
            elif name == "join" and not node.args \
                    and not isinstance(recv, ast.Constant):
                self._flag(node, "calls blocking .join()")
            elif name == "get" and not node.args and not any(
                    kw.arg not in ("timeout", "block")
                    for kw in node.keywords) and not any(
                    kw.arg == "block" and isinstance(kw.value,
                                                     ast.Constant)
                    and kw.value.value is False
                    for kw in node.keywords):
                # block=False is the explicit NON-blocking drain probe
                self._flag(node, "calls blocking .get()")
            elif name == "sleep" and isinstance(recv, ast.Name) \
                    and recv.id == "time":
                self._flag(node, "calls time.sleep()")
        self.generic_visit(node)


class DeviceThreadRule(Rule):
    name = "device-thread"
    doc = ("functions passed to io_callback/pure_callback must not "
           "block or materialize their lazy args (the PR 2 "
           "self-deadlock class)")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for path in project.py_files():
            tree = project.tree(path)
            if tree is None:
                continue
            rel = project.rel(path)
            defs: Dict[str, ast.FunctionDef] = {}
            sites: List[ast.Call] = []
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    defs[node.name] = node
                elif isinstance(node, ast.Call) \
                        and _callee_name(node.func) in _CALLBACK_NAMES:
                    sites.append(node)
            scanned: Set[str] = set()
            for call in sites:
                ref = _tap_ref(call)
                if ref is None:
                    continue  # no callback arg: not a tap registration
                if isinstance(ref, ast.Lambda):
                    _TapScan(rel, ref, findings).visit(ref)
                    continue
                name = _ref_name(ref)
                fn = defs.get(name) if name is not None else None
                if fn is not None:
                    if name not in scanned:
                        scanned.add(name)
                        _TapScan(rel, fn, findings).visit(fn)
                    continue
                # fail CLOSED: a tap the rule cannot see is where the
                # next deadlock hides — never a silent pass
                what = (f"callback {name!r} is not defined in this "
                        f"module" if name is not None else
                        "callback expression cannot be resolved to a "
                        "function")
                findings.append(Finding(
                    "device-thread", rel, call.lineno,
                    f"{what} — the rule scans taps lexically and "
                    f"cannot verify this one never blocks on the "
                    f"device-dispatch thread (PR 2 deadlock class); "
                    f"define the tap in this module or suppress here "
                    f"with a WHY"))
        return findings
