"""byteps-top — the fleet's live console (``python -m byteps_tpu.tools.top``).

Renders the SAME snapshot surface everything else reads — the unified
metrics snapshot with its ``timeseries`` / ``steps`` / ``fleet`` /
``health`` / ``flight`` sections — as a terminal dashboard: per-series
sparklines (step walls, per-server per-stripe-lane wire bytes,
counter deltas), the ``classify_step`` bound-stage verdict with the
LANE-IMBALANCE annotation, health flags and flight-ring pressure.
Stdlib only (ANSI escapes, ``urllib``); no curses dependency, no
third-party TUI.

Three snapshot sources, one renderer:

- ``--url http://127.0.0.1:<port>/`` — the JSON endpoint
  ``BYTEPS_METRICS_PORT`` serves (the remote / out-of-process view);
  defaults to that env var's port when set.
- ``--file path`` — a dumped snapshot JSON, or a ``timeseries-*.jsonl``
  SIGTERM/shutdown/bench artifact (post-mortem mode: the console
  renders a dead run's tail).
- ``--local`` — ``bps.get_metrics()`` in this process (debugging a
  live training process from a REPL / the same interpreter).

``--once`` prints one machine-readable JSON frame and exits — the CI
smoke (ci/checks.sh) and test surface; its keys are pinned by
``tests/test_timeseries.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, Optional

__all__ = ["main", "build_frame", "once_frame", "load_snapshot"]

_SPARK = " ▁▂▃▄▅▆▇█"
_BOLD, _DIM, _RESET = "\x1b[1m", "\x1b[2m", "\x1b[0m"
_RED, _YEL, _GRN = "\x1b[31m", "\x1b[33m", "\x1b[32m"


def sparkline(values, width: int = 24) -> str:
    """Fixed-width unicode sparkline, right-aligned to the newest
    point; constant scale per series (min..max of the shown tail)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return " " * width
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for v in vals:
        if span <= 0:
            out.append(_SPARK[1] if hi > 0 else _SPARK[0])
        else:
            idx = 1 + int((v - lo) / span * (len(_SPARK) - 2))
            out.append(_SPARK[min(idx, len(_SPARK) - 1)])
    return "".join(out).rjust(width)


def _fmt(v) -> str:
    if v is None:
        return "-"
    f = float(v)
    if f != f:  # NaN
        return "nan"
    if abs(f) >= 1e9:
        return f"{f / 1e9:.2f}G"
    if abs(f) >= 1e6:
        return f"{f / 1e6:.2f}M"
    if abs(f) >= 1e3:
        return f"{f / 1e3:.1f}k"
    if f == int(f):
        return str(int(f))
    return f"{f:.3g}"


# ------------------------------------------------------------------- #
# snapshot sources
# ------------------------------------------------------------------- #


def _snapshot_from_jsonl(lines) -> dict:
    """Rehydrate a ``timeseries-*.jsonl`` dump artifact into the
    snapshot shape the renderer reads (timeseries section only)."""
    header: dict = {}
    series: Dict[str, dict] = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        doc = json.loads(line)
        if doc.get("kind") == "timeseries":
            header = doc
        elif "name" in doc:
            series[doc["name"]] = {"steps": doc.get("steps", []),
                                   "values": doc.get("values", [])}
    return {"timeseries": {
        "enabled": True,
        "points": header.get("points", 0),
        "steps": header.get("steps", 0),
        "series_count": len(series),
        "dropped_series": header.get("dropped_series", 0),
        "breaker_tripped": False,
        "series": series,
    }, "_artifact": {"reason": header.get("reason"),
                     "pid": header.get("pid")}}


def load_snapshot(url: Optional[str] = None, file: Optional[str] = None,
                  local: bool = False) -> dict:
    """Fetch one snapshot dict from whichever source was selected."""
    if local:
        import byteps_tpu as bps
        return bps.get_metrics()
    if file:
        with open(file) as f:
            first = f.readline()
            rest = f.read()
        text = first + rest
        if first.lstrip().startswith("{") and '"kind": "timeseries"' \
                in first:
            return _snapshot_from_jsonl(text.splitlines())
        return json.loads(text)
    if url:
        from urllib.request import urlopen
        with urlopen(url, timeout=5) as resp:
            return json.loads(resp.read().decode())
    raise ValueError("no snapshot source: pass --url, --file or --local")


# ------------------------------------------------------------------- #
# frame assembly
# ------------------------------------------------------------------- #


def _verdict(snap: dict) -> Optional[str]:
    """The classify_step bound-stage verdict for the last step:
    the steps section carries it precomputed (``last_diagnosis``);
    artifacts that only have the raw report dict get it recomputed
    through the real classifier."""
    steps = snap.get("steps") or {}
    v = steps.get("last_diagnosis")
    if v:
        return v
    last = steps.get("last")
    if not last:
        return None
    try:
        from ..core.metrics import StepReport, classify_step
        known = {f.name for f in
                 __import__("dataclasses").fields(StepReport)}
        kwargs = {k: v for k, v in last.items() if k in known}
        if kwargs.get("lane_bytes") is not None:
            kwargs["lane_bytes"] = tuple(
                tuple(e) for e in kwargs["lane_bytes"])
        return classify_step(StepReport(**kwargs))
    except Exception:  # noqa: BLE001 - a partial artifact: no verdict
        return None


def _series_groups(ts: dict):
    """(group_title, [(name, steps, values)]) buckets in render order:
    step walls first, then the per-stripe wire lanes, then counter
    deltas / gauges."""
    series = ts.get("series") or {}
    groups = [("step", []), ("stripe", []), ("counter", []),
              ("gauge", [])]
    by_prefix = dict(groups)
    for name in sorted(series):
        prefix = name.split("/", 1)[0]
        bucket = by_prefix.get(prefix)
        if bucket is None:
            continue
        s = series[name]
        bucket.append((name, s.get("steps", []), s.get("values", [])))
    return [(title, rows) for title, rows in groups if rows]


def build_frame(snap: dict, width: int = 100) -> str:
    """One rendered text frame (ANSI) from a snapshot dict."""
    ts = snap.get("timeseries") or {}
    lines = []
    art = snap.get("_artifact")
    src = f" artifact[{art['reason']}] pid={art['pid']}" if art else ""
    trip = ts.get("breaker_tripped")
    head = (f"{_BOLD}byteps-top{_RESET}  steps={ts.get('steps', 0)} "
            f"series={ts.get('series_count', 0)} "
            f"ring={ts.get('points', 0)}{src}")
    if trip:
        head += f" {_RED}[recorder breaker TRIPPED]{_RESET}"
    if ts.get("dropped_series"):
        head += f" {_YEL}dropped={ts['dropped_series']}{_RESET}"
    lines.append(head)
    verdict = _verdict(snap)
    if verdict:
        if "LANE-IMBALANCE" in verdict or "HEALTH" in verdict:
            color = _RED
        elif verdict.startswith("COMPUTE"):
            color = _GRN  # compute-bound is the healthy steady state
        else:
            color = _YEL  # wire/queue/server-bound: worth a look
        lines.append(f"{color}{verdict}{_RESET}")
    # health + flight annotations ride the same frame
    last = (snap.get("steps") or {}).get("last") or {}
    flags = last.get("health_flags")
    if flags:
        lines.append(f"{_RED}HEALTH: {','.join(flags)}{_RESET}")
    flight = snap.get("flight") or {}
    if flight:
        lines.append(
            f"{_DIM}flight: events={flight.get('events', 0)} "
            f"dropped={flight.get('dropped', 0)}{_RESET}")
    fleet = snap.get("fleet") or {}
    if fleet.get("server"):
        lines.append(f"{_DIM}fleet: {len(fleet['server'])} server(s) "
                     f"via {fleet.get('source')}{_RESET}")
    name_w = max(28, width - 44)
    for title, rows in _series_groups(ts):
        lines.append(f"{_BOLD}-- {title} {'-' * (width - len(title) - 4)}"
                     f"{_RESET}")
        for name, _steps, values in rows:
            tail = values[-1] if values else None
            lines.append(f"{name[:name_w]:<{name_w}} "
                         f"{sparkline(values)} "
                         f"{_fmt(tail):>8} n={len(values)}")
    if not ts:
        lines.append(f"{_DIM}(no timeseries section in snapshot — is "
                     f"BYTEPS_TIMESERIES on?){_RESET}")
    return "\n".join(lines)


def once_frame(snap: dict) -> dict:
    """The ``--once`` machine-readable frame (schema pinned by
    tests/test_timeseries.py): fixed top-level keys, per-series
    last/min/max/points."""
    ts = snap.get("timeseries") or {}
    series = {}
    for name, s in (ts.get("series") or {}).items():
        values = s.get("values") or []
        series[name] = {
            "points": len(values),
            "last": values[-1] if values else None,
            "min": min(values) if values else None,
            "max": max(values) if values else None,
        }
    last = (snap.get("steps") or {}).get("last") or {}
    return {
        "schema": "byteps-top/1",
        "steps": ts.get("steps", 0),
        "series_count": ts.get("series_count", len(series)),
        "breaker_tripped": bool(ts.get("breaker_tripped", False)),
        "verdict": _verdict(snap),
        "series": series,
        "health_flags": list(last.get("health_flags") or []),
        "flight": {"events": (snap.get("flight") or {}).get("events", 0),
                   "dropped": (snap.get("flight") or {}).get("dropped",
                                                             0)},
        "fleet": {"servers": len((snap.get("fleet") or {})
                                 .get("server") or {}),
                  "source": (snap.get("fleet") or {}).get("source")},
    }


# ------------------------------------------------------------------- #
# entry point
# ------------------------------------------------------------------- #


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m byteps_tpu.tools.top",
        description="live fleet console over the byteps_tpu metrics "
                    "snapshot (timeseries/steps/fleet sections)")
    ap.add_argument("--url", default=None,
                    help="snapshot JSON endpoint (default: "
                         "http://127.0.0.1:$BYTEPS_METRICS_PORT/ "
                         "when that env var is set)")
    ap.add_argument("--file", default=None,
                    help="snapshot JSON or timeseries-*.jsonl artifact")
    ap.add_argument("--local", action="store_true",
                    help="read bps.get_metrics() in-process")
    ap.add_argument("--once", action="store_true",
                    help="print one machine-readable JSON frame and "
                         "exit (CI smoke)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (live mode)")
    ap.add_argument("--width", type=int, default=100)
    args = ap.parse_args(argv)
    url = args.url
    if url is None and not args.file and not args.local:
        port = os.environ.get("BYTEPS_METRICS_PORT", "")
        if port and port != "0":
            url = f"http://127.0.0.1:{port}/"
        else:
            ap.error("no source: pass --url/--file/--local (or set "
                     "BYTEPS_METRICS_PORT)")
    if args.once:
        try:
            snap = load_snapshot(url=url, file=args.file,
                                 local=args.local)
        except Exception as e:  # noqa: BLE001 - CI smoke wants 1 line
            print(json.dumps({"schema": "byteps-top/1", "error": str(e)}))
            return 1
        print(json.dumps(once_frame(snap)))
        return 0
    try:
        while True:
            try:
                snap = load_snapshot(url=url, file=args.file,
                                     local=args.local)
                frame = build_frame(snap, width=args.width)
            except Exception as e:  # noqa: BLE001 - source flaps: show
                frame = f"{_RED}snapshot source error: {e}{_RESET}"
            # home + clear-below keeps the frame flicker-free
            sys.stdout.write("\x1b[H\x1b[J" + frame + "\n")
            sys.stdout.flush()
            if args.file:
                return 0  # artifacts are static: render once
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
