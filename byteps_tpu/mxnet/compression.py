"""Intra-node ("pre-core") gradient transforms for the MXNet adapter.

Reference parity: byteps/mxnet/compression.py:26-164 — a tiny Compressor
interface (none / fp16) plus two optimizer-math wrappers that the
DistributedTrainer stacks around the wire when ``compression_params``
asks for momentum: NAG for tensors SMALL enough to skip the server-side
codec (the codec tier applies its own momentum there), and the
weight-decay momentum used with onebit.

TPU-native note: these run on the HOST tier (the gradient is already a
host array on its way to the DCN PS), so the math is written against the
duck-typed NDArray surface (``astype`` / arithmetic) and works unchanged
on real ``mx.nd.NDArray``s and numpy arrays — no ``nd._internal``
engine-op calls.
"""

from __future__ import annotations

import numpy as np


def _numel(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _nbytes(tensor) -> int:
    """Size in BYTES — the threshold unit (BYTEPS_MIN_COMPRESS_BYTES),
    matching the codec tier's per-partition byte test
    (server/compressed.py) so a tensor is never momentum'd twice."""
    return _numel(tensor.shape) * np.dtype(tensor.dtype).itemsize


class Compressor:
    """Interface: ``compress`` before the wire, ``decompress`` after."""

    def compress(self, tensor, *args, **kwargs):
        raise NotImplementedError

    def decompress(self, tensor, ctx, *args, **kwargs):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (the default)."""

    def compress(self, tensor, *args, **kwargs):
        return tensor, None

    def decompress(self, tensor, ctx, *args, **kwargs):
        return tensor


class FP16Compressor(Compressor):
    """Ship float gradients as 16-bit halves; restore the original dtype
    on the way back (reference compression.py:50-67)."""

    def compress(self, tensor, *args, **kwargs):
        dtype = tensor.dtype
        if "float" in str(dtype):
            return tensor.astype("float16", copy=False), dtype
        return tensor, dtype

    def decompress(self, tensor, ctx, *args, **kwargs):
        dtype = ctx
        if dtype is not None and "float" in str(dtype):
            return tensor.astype(dtype, copy=False)
        return tensor


class NagAdapter(Compressor):
    """Nesterov momentum applied on the worker for tensors BELOW the
    compression threshold (reference compression.py:70-101): the
    server-side codec stack owns momentum for large tensors
    (ops/compression/host.py HostNesterovMomentum), so small/uncompressed
    ones replicate it locally to keep the optimizer math uniform after
    ``momentum`` was stripped from optimizer_params."""

    def __init__(self, compressor: Compressor, mu: float, threshold: int):
        self.compressor = compressor
        self.mu = float(mu)
        self.threshold = int(threshold)
        self.mom = None
        self._apply = False
        self._inited = False

    def compress(self, tensor, *args, **kwargs):
        return self.compressor.compress(tensor)

    def decompress(self, tensor, ctx, *args, **kwargs):
        tensor = self.compressor.decompress(tensor, ctx, *args, **kwargs)
        if not self._inited:
            self._apply = _nbytes(tensor) < self.threshold
            if self._apply:
                self.mom = tensor * 0
            self._inited = True
        if self._apply:
            # m <- mu * (m + g); g <- g + m   (NAG lookahead form)
            self.mom += tensor
            self.mom *= self.mu
            tensor += self.mom
        return tensor


class WeightDecayMomentumAdapter(Compressor):
    """Weight-decay momentum for onebit (reference compression.py:104-147):
    with ``wd`` stripped from the optimizer, the worker adds
    m_t = mu*m_{t-1} + wd*x_t to the AGGREGATED gradient after the pull
    (this wrapper runs outside the wire codec, in the reference too — the
    sign codec quantizes the undecayed gradient; the decay reaches the
    optimizer update). Needs the current weight via ``decompress(x=...)``.
    Applied only ABOVE the threshold (where onebit actually runs)."""

    def __init__(self, compressor: Compressor, mu: float, wd: float,
                 threshold: int):
        self.compressor = compressor
        self.mu = float(mu)
        self.wd = float(wd)
        self.threshold = int(threshold)
        self.mom = None
        self._apply = False
        self._inited = False

    def compress(self, tensor, *args, **kwargs):
        return self.compressor.compress(tensor)

    def decompress(self, tensor, ctx, *args, **kwargs):
        if "x" not in kwargs:
            raise ValueError("WeightDecayMomentumAdapter.decompress needs "
                             "the weight as x=")
        x = kwargs.pop("x").astype(tensor.dtype, copy=False)
        if not self._inited:
            self._apply = _nbytes(tensor) >= self.threshold
            if self._apply:
                self.mom = tensor * 0
            self._inited = True
        decay = x * self.wd
        if self._apply:
            self.mom += decay
            self.mom *= self.mu
            tensor += self.mom
        tensor += decay
        return self.compressor.decompress(tensor, ctx, *args, **kwargs)


class Compression:
    """Namespace the trainer/optimizer surface exposes
    (reference compression.py:149-164)."""

    none = NoneCompressor()
    fp16 = FP16Compressor()
    nag = NagAdapter
    wdmom = WeightDecayMomentumAdapter
