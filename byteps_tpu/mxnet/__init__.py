"""byteps_tpu.mxnet — Horovod-style MXNet adapter over the DCN PS.

Reference parity (byteps/mxnet/__init__.py:35-360):

- ``DistributedOptimizer`` — delegation wrapper around any
  ``mx.optimizer.Optimizer``: sync mode push_pulls each gradient
  (averaged) before the local update; async mode
  (``BYTEPS_ENABLE_ASYNC``) updates locally, pushes the WEIGHT DELTA
  (sum, no average) and pulls the server's authoritative weights back —
  the server-side async-PS mode byteps_tpu.server implements.
- ``DistributedTrainer`` — a ``mx.gluon.Trainer`` subclass whose
  ``_allreduce_grads`` rides the priority pipeline: grads are
  pre-scaled by 1/(batch*size), pushed as SUMs in declaration order
  (priority=-index), intra-node compressed (fp16 / NAG / wd-momentum
  wrappers from .compression), and per-parameter ``byteps_*``
  attributes route the server-side codec (onebit/topk/randomk/
  dithering with EF + momentum) exactly like the reference's
  compression_params contract.
- ``broadcast_parameters`` — zero-non-root + push_pull(sum).
- ``lr.s`` — local-rank-0 publishes the current learning rate as a
  little 8-byte double file every step (the reference's mmap channel
  for error-feedback lr rescale, mxnet/__init__.py:326-331). The TPU
  rebuild's EF rescale natively lives in the codec stack
  (ops/compression/feedback.py), so the file is a compatibility
  artifact kept for tooling parity.

TPU-native note: MXNet is a host-side framework here (no MXNet TPU
backend exists); gradients hop host->PS->host through the same
priority-scheduled pipeline the torch/TF adapters use, proving the
one-comm-stack/N-frameworks plugin boundary. MXNet itself is imported
lazily so the module can be inspected without it installed.
"""

from __future__ import annotations

import copy
import os
import struct
import warnings

from .compression import Compression
from .ops import (byteps_declare_tensor, byteps_push_pull,
                  byteps_push_pull_async, init, local_rank, local_size,
                  poll, rank, resume, shutdown, size, suspend, synchronize)

__all__ = [
    "init", "shutdown", "suspend", "resume",
    "rank", "size", "local_rank", "local_size",
    "byteps_declare_tensor", "byteps_push_pull", "byteps_push_pull_async",
    "poll", "synchronize",
    "DistributedOptimizer", "DistributedTrainer", "broadcast_parameters",
    "Compression",
]

parameter_index = 0


def _mx():
    import mxnet as mx
    return mx


def _base_trainer():
    return _mx().gluon.Trainer


class DistributedOptimizer:
    """Wrap ``optimizer`` so every update first aggregates gradients
    across workers (sync) or reconciles weights through the async PS
    (``BYTEPS_ENABLE_ASYNC=1``). KVStore-style ``update``/
    ``update_multi_precision`` hook points, delegation for everything
    else (reference mxnet/__init__.py:35-122)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._seeded = set()
        self._pool = None
        self._enable_async = (
            int(os.getenv("BYTEPS_ENABLE_ASYNC", 0)) != 0)
        if self._enable_async:
            assert int(os.getenv("DMLC_NUM_WORKER", "1")) > 1, \
                "async training requires more than one worker"

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state(self, index, weight):
        return self._optimizer.create_state(index, weight)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    # -- push_pull plumbing ------------------------------------------- #

    def _push_pull_grads(self, index, grad):
        indices = index if isinstance(index, (tuple, list)) else [index]
        grads = grad if isinstance(grad, (tuple, list)) else [grad]
        handles = []
        for i, g in zip(indices, grads):
            # own name space: a gluon DistributedTrainer in the same
            # process declares gradient_{i} for ITS params; sharing the
            # prefix would alternate two differently-shaped tensors under
            # one first-wins PS key
            nm = f"kv_gradient_{i}"
            byteps_declare_tensor(nm)
            handles.append(byteps_push_pull_async(
                g, version=0, priority=-int(i), name=nm, is_average=True))
        for h in handles:
            synchronize(h)

    def _push_pull_deltas(self, index, delta_weight, before):
        """Async mode: push weight DELTAs through the async-PS protocol
        (the server folds them into its authoritative weights, no round
        barrier) and write the pulled weights back into the arrays. The
        server store is first seeded with the PRE-update weights
        (init-push defaults to zeros, first arrival wins) — the same
        bootstrap as the JAX async path (jax/train.py init_weights +
        push_delta_pull_weights)."""
        import concurrent.futures

        import numpy as np

        from ..core.state import get_state
        from ..server.client import get_or_init_ctx

        indices = index if isinstance(index, (tuple, list)) else [index]
        deltas = (delta_weight if isinstance(delta_weight, (tuple, list))
                  else [delta_weight])
        state = get_state()
        if state.ps_client is None:
            # no PS configured: the local update stands — the arrays hold
            # deltas right now, so restore weight = before + delta
            for d, b in zip(deltas, before):
                d += b
            return

        def _host(t):
            return np.ascontiguousarray(
                t.asnumpy() if hasattr(t, "asnumpy") else t,
                np.float32).reshape(-1)

        jobs = []
        for i, d, b in zip(indices, deltas, before):
            nm = f"weight_{i}"
            byteps_declare_tensor(nm)
            host_d = _host(d)
            ctx = get_or_init_ctx(state, nm, host_d)
            if nm not in self._seeded:
                state.ps_client.init_weights(ctx, _host(b))
                self._seeded.add(nm)
            jobs.append((d, ctx, host_d))
        # overlap the per-param round trips (they'd otherwise serialize
        # the step on sum-of-RTTs); one long-lived pool — per-step
        # spawn/join would sit on the hot path
        pool = self._pool
        if pool is None:
            pool = self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="bps-mx-async")
        outs = list(pool.map(
            lambda j: state.ps_client.push_delta_pull_weights(
                j[1], j[2]), jobs))
        for (d, _, _), out in zip(jobs, outs):
            d[:] = out.reshape(d.shape)
        state.telemetry.record_round_trip(sum(j[2].nbytes for j in jobs))

    def _update_impl(self, index, weight, grad, state, multi: bool):
        upd = (self._optimizer.update_multi_precision if multi
               else self._optimizer.update)
        if self._enable_async:
            weights = (weight if isinstance(weight, (tuple, list))
                       else [weight])
            before = [w.copy() for w in weights]
            upd(index, weight, grad, state)
            # weight now holds the local post-update value; turn it into
            # the delta, push it, and the pull brings back the server's
            # authoritative weights into the same arrays
            for w, b in zip(weights, before):
                w -= b
            self._push_pull_deltas(
                index,
                weights if isinstance(weight, (tuple, list))
                else weights[0],
                before)
        else:
            self._push_pull_grads(index, grad)
            upd(index, weight, grad, state)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi=False)

    def update_multi_precision(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi=True)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Make every worker's copy equal to the root's: non-root contributions
    are zeroed and the PS sum therefore equals the root value (reference
    mxnet/__init__.py:124-161). ``params``: dict name -> NDArray (e.g.
    ``Module.get_params()[0]``). Gluon users should rely on
    ``DistributedTrainer`` instead (it broadcasts at the first step)."""
    global parameter_index
    if not isinstance(params, dict):
        raise ValueError(f"invalid params of type {type(params)}: "
                         "pass a dict of name -> NDArray (gluon parameters "
                         "are broadcast by DistributedTrainer)")
    tensors = [p for _, p in sorted(params.items())]
    handles = []
    for t in tensors:
        # own name space: the trainer declares parameter_{i} for its own
        # params; sharing the prefix (as the reference does) collides PS
        # keys between differently-shaped tensors when both surfaces are
        # used in one process
        nm = f"broadcast_parameter_{parameter_index}"
        parameter_index += 1
        byteps_declare_tensor(nm)
        if rank() != root_rank:
            t *= 0
        handles.append(byteps_push_pull_async(
            t, version=0, priority=0, name=nm, is_average=False))
    for h in handles:
        synchronize(h)
    for t in tensors:
        if hasattr(t, "wait_to_read"):
            t.wait_to_read()


class _DistributedTrainerMixin:
    """The DistributedTrainer body; mixed onto mx.gluon.Trainer lazily so
    importing this module never requires mxnet itself."""

    def _bps_setup(self, params, optimizer, optimizer_params,
                   root_rank, compression_params):
        if isinstance(optimizer, DistributedOptimizer):
            optimizer = optimizer._optimizer
            warnings.warn("DistributedTrainer takes the raw optimizer, not "
                          "DistributedOptimizer; unwrapped it for you")

        if hasattr(params, "items"):   # ParameterDict / dict
            param_list = [params[k] for k in sorted(params.keys())]
        else:
            param_list = list(params)

        optimizer_params = dict(optimizer_params or {})
        intra = self._register_compressor(param_list, optimizer_params,
                                          compression_params)
        return param_list, optimizer, optimizer_params, intra

    def _bps_finish_init(self, param_list, intra, root_rank):
        self._f = None
        self._f_path = None
        if local_rank() == 0:
            self._f_path = os.path.abspath("lr.s")
            self._f = open(self._f_path, "wb")
            self._f.truncate(8)
        self._bps_size = size()
        self.root_rank = root_rank
        self._intra_compressors = {}
        for i, param in enumerate(self._params):
            byteps_declare_tensor(f"parameter_{i}")
            self._intra_compressors[param.name] = copy.deepcopy(intra)
            if param.grad_req != "null":
                byteps_params = {
                    k: v for k, v in param.__dict__.items()
                    if k.startswith("byteps_")}
                byteps_declare_tensor(f"gradient_{i}", **byteps_params)

    def __del__(self):
        f = getattr(self, "_f", None)
        if f is not None:
            try:
                f.close()
                # absolute path recorded at open time — a later chdir
                # must not make this delete some other trainer's lr.s
                if os.path.exists(self._f_path):
                    os.remove(self._f_path)
            except Exception:
                pass  # interpreter teardown: os may already be gone

    def _register_compressor(self, param_list, optimizer_params,
                             compression_params):
        """Translate the compression_params dict into per-parameter
        ``byteps_*`` attributes (consumed by byteps_declare_tensor) plus
        the intra-node wrapper stack; strips momentum/wd from
        optimizer_params when the comm stack takes them over (reference
        mxnet/__init__.py:236-317)."""
        intra = Compression.none
        if not compression_params:
            return intra
        if compression_params.get("fp16"):
            if "compressor" in compression_params:
                # the server-side codecs are f32 transforms; an fp16 wire
                # tensor would silently fall back to the dense path and
                # lose the codec AND its momentum stage
                warnings.warn("fp16 intra-compression is incompatible "
                              "with a server-side compressor; ignoring "
                              "fp16")
            else:
                intra = Compression.fp16
        if "compressor" not in compression_params:
            if not compression_params.get("fp16"):
                warnings.warn("compression_params without a 'compressor' "
                              "entry — only intra-node fp16 applies")
            return intra

        compressor = compression_params["compressor"]
        if (compression_params.get("momentum")
                and "momentum" not in optimizer_params):
            raise ValueError(
                "compression_params momentum requires a 'momentum' value "
                "in optimizer_params (the comm stack replaces the "
                "framework momentum and needs its mu; reference "
                "mxnet/__init__.py:236-317)")
        for param in param_list:
            for item in ("compressor", "ef", "momentum"):
                val = compression_params.get(item)
                if val:
                    if not isinstance(val, str):
                        raise TypeError(f"{item} should be str")
                    setattr(param, f"byteps_{item}_type", val)
            if compressor == "onebit":
                setattr(param, "byteps_compressor_onebit_scaling",
                        str(compression_params.get("scaling", False)))
            elif compressor in ("topk", "randomk", "dithering"):
                setattr(param, "byteps_compressor_k",
                        compression_params["k"])
            if compression_params.get("momentum"):
                setattr(param, "byteps_momentum_mu",
                        optimizer_params["momentum"])
            if compression_params.get("seed") is not None:
                setattr(param, "byteps_seed", compression_params["seed"])
            partition = compression_params.get("partition")
            if partition:
                if partition not in ("linear", "natural"):
                    raise ValueError(f"unsupported partition {partition!r}")
                setattr(param, "byteps_dithering_partition", partition)
            normalize = compression_params.get("normalize")
            if normalize:
                if normalize not in ("max", "l2"):
                    raise ValueError(f"unsupported normalize {normalize!r}")
                setattr(param, "byteps_dithering_normalize", normalize)

        if compression_params.get("momentum"):
            # the SAME resolver the codec tier uses — a divergent default
            # would leave a size band with momentum from neither tier
            from ..ops.compression import _resolve_min_compress_bytes
            threshold = _resolve_min_compress_bytes(None)
            mu = optimizer_params["momentum"]
            if compressor == "onebit" and "wd" in optimizer_params:
                intra = Compression.wdmom(intra, mu,
                                          optimizer_params.pop("wd"),
                                          threshold)
            intra = Compression.nag(intra, mu, threshold)
            del optimizer_params["momentum"]
        return intra

    def step(self, batch_size, ignore_stale_grad=False):
        # gluon normalizes grads by _scale; setting it to batch_size keeps
        # the division from happening twice (we fold it into the pre-push
        # scaling below)
        self._scale = batch_size
        super().step(batch_size, ignore_stale_grad)

    def _allreduce_grads(self):
        if self._f is not None:
            self._f.seek(0)
            self._f.write(struct.pack("d", self.learning_rate))
            self._f.flush()

        # submit every gradient async in declaration order, then drain:
        # the pipeline overlaps PUSH/PULL across parameters (the engine-
        # dependency overlap the reference gets from MXEnginePushAsync)
        inflight = []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            grad = param._grad[0]
            grad *= 1.0 / (self._scale * self._bps_size)
            comp = self._intra_compressors[param.name]
            compressed, ctx = comp.compress(grad)
            h = byteps_push_pull_async(compressed, is_average=False,
                                       name=f"gradient_{i}", priority=-i)
            inflight.append((param, comp, compressed, ctx, h))
        for param, comp, compressed, ctx, h in inflight:
            synchronize(h)
            param._grad[0][:] = comp.decompress(compressed, ctx,
                                                x=param._data[0])

    def _init_params(self):
        """First-step broadcast: push root's values, zeroed elsewhere
        (reference mxnet/__init__.py:344-360); deferred-init parameters
        stay queued."""
        deferred = []
        for param in self._params_to_init:
            if getattr(param, "_deferred_init", False):
                deferred.append(param)
                continue
            idx = self._param2idx[param.name]
            arr = param._data[0]
            if rank() != self.root_rank:
                arr *= 0
            byteps_push_pull(arr, version=0, priority=0,
                             name=f"parameter_{idx}", is_average=False)
        self._params_to_init = deferred


def DistributedTrainer(params, optimizer, optimizer_params=None,
                       root_rank: int = 0, compression_params=None):
    """Build the gluon DistributedTrainer (reference
    mxnet/__init__.py:164-343). Implemented as a factory so the gluon base
    class is only resolved when MXNet is actually present."""
    Trainer = _base_trainer()

    cls = type("DistributedTrainer", (_DistributedTrainerMixin, Trainer), {})
    self = cls.__new__(cls)
    param_list, opt, opt_params, intra = self._bps_setup(
        params, optimizer, optimizer_params, root_rank, compression_params)
    Trainer.__init__(self, param_list, opt, optimizer_params=opt_params,
                     kvstore=None)
    if not hasattr(self, "_param2idx"):
        self._param2idx = {p.name: i for i, p in enumerate(self._params)}
    self._bps_finish_init(param_list, intra, root_rank)
    return self
