"""byteps_tpu.mxnet.ops — the MXNet op surface over the DCN PS.

Reference parity: byteps/mxnet/ops.py:28-123 — ``byteps_declare_tensor``
(carrying per-tensor ``byteps_*`` compression kwargs into the core) and
``byteps_push_pull`` (an in-place engine op keyed by declared name,
scheduled by declaration-order priority, mxnet/ops.cc:120-160).

TPU-native redesign: there is no ``MXEnginePushAsync`` dependency chain
to splice into — the priority-scheduled COMPRESS→PUSH→PULL→DECOMPRESS
pipeline (core/scheduler.py) IS the engine. ``byteps_push_pull_async``
submits the host array through it and returns an int handle;
``synchronize`` writes the cross-worker aggregate back INTO the NDArray
(the reference's in-place contract). The declared ``byteps_*`` kwargs
are translated to the shared codec-registry names
(ops/compression/host.make_host_codec — the same parameters the
reference's compressor_registry.cc parses from the kwargs bag,
common/__init__.py:102-135 there) and ride the compressed pipeline via
server.compressed.CompressedRegistry.

This module is framework-agnostic by design: it only touches the
duck-typed NDArray surface (``.asnumpy()`` / ``tensor[:] = ndarray`` /
``.dtype``), so real ``mx.nd.NDArray``s, the test tier's fake, and raw
numpy arrays all work.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..core.state import get_state
from ..core.types import DataType
from ..utils.logging import log

__all__ = [
    "init", "shutdown", "suspend", "resume",
    "rank", "size", "local_rank", "local_size",
    "byteps_declare_tensor", "byteps_push_pull",
    "byteps_push_pull_async", "poll", "synchronize",
]


def init(*args, **kwargs) -> None:
    get_state().init(*args, **kwargs)


def shutdown() -> None:
    get_state().shutdown()
    reset_declarations()


def suspend() -> None:
    get_state().suspend()


def resume(num_workers: int, num_servers: int,
           global_rank: Optional[int] = None) -> None:
    get_state().resume(num_workers, num_servers, global_rank)


def rank() -> int:
    return get_state().rank()


def size() -> int:
    return get_state().size()


def local_rank() -> int:
    return get_state().local_rank()


def local_size() -> int:
    return get_state().local_size()


# --------------------------------------------------------------------- #
# declaration table (mxnet/ops.py:83-101: name -> key order + comp kwargs)
# --------------------------------------------------------------------- #

_mu = threading.Lock()
_decl: Dict[str, dict] = {}        # name -> {index, comp}
_comp_regs: Dict[str, object] = {}  # name -> CompressedRegistry
_pending: Dict[int, tuple] = {}    # handle -> (kind, ndarray, shape, dtype)
_imm_next = [-1]                   # immediate-handle ids (negative space)

_DITHER_PARTITION = {"0": "linear", "1": "natural",
                     "linear": "linear", "natural": "natural"}
_DITHER_NORMALIZE = {"0": "max", "1": "l2", "max": "max", "l2": "l2"}


def reset_declarations() -> None:
    """Drop the declaration/codec tables (new PS session = new keys)."""
    with _mu:
        _decl.clear()
        _comp_regs.clear()
        _pending.clear()


def _codec_kwargs(byteps_params: dict) -> Optional[dict]:
    """byteps_* attribute bag -> shared codec-registry kwargs (the same
    translation the reference core does when the kwargs reach
    byteps_declare_tensor, mxnet/ops.cc:139-160)."""
    m: Dict[str, str] = {}
    for k, v in byteps_params.items():
        v = str(v)
        if k == "byteps_compressor_type":
            m["compressor"] = v
        elif k == "byteps_ef_type":
            m["ef"] = v
        elif k == "byteps_momentum_type":
            m["momentum"] = v
        elif k == "byteps_momentum_mu":
            m["momentum_mu"] = v
        elif k == "byteps_compressor_k":
            m["k"] = v
        elif k == "byteps_seed":
            m["seed"] = v
        elif k == "byteps_compressor_onebit_scaling":
            m["scaling"] = v
        elif k == "byteps_dithering_partition":
            m["partition_type"] = _DITHER_PARTITION[v]
        elif k == "byteps_dithering_normalize":
            m["normalize_type"] = _DITHER_NORMALIZE[v]
        elif k.startswith("byteps_"):
            log.warning("ignoring unknown compression kwarg %s", k)
    return m if "compressor" in m else None


def byteps_declare_tensor(name: str, **kwargs) -> None:
    """Declare ``name`` so its PS key is assigned in declaration order
    (deterministic across workers) and record any ``byteps_*`` compression
    kwargs for its pushes. Idempotent — the reference re-declares on every
    optimizer update (mxnet/__init__.py:53-60)."""
    state = get_state()
    if not state.initialized:
        raise RuntimeError("byteps_tpu.mxnet: init() must be called first")
    comp = _codec_kwargs(kwargs)
    with _mu:
        prev = _decl.get(name)
        if prev is not None:
            if comp is not None and prev["comp"] != comp:
                # first declaration wins (keys and codec configs must be
                # stable across workers); silent divergence would be a
                # debugging trap
                log.warning(
                    "tensor %r was already declared with different "
                    "compression kwargs; keeping the first declaration",
                    name)
            return
        _decl[name] = {"index": len(_decl), "comp": comp}
    state.registry.declare(name, DataType.FLOAT32)


def _as_host(tensor) -> np.ndarray:
    if hasattr(tensor, "asnumpy"):
        return np.ascontiguousarray(tensor.asnumpy())
    return np.ascontiguousarray(tensor)


def _write_back(tensor, arr: np.ndarray) -> None:
    if hasattr(tensor, "asnumpy"):
        tensor[:] = arr
    else:
        np.copyto(tensor, arr)


def byteps_push_pull_async(tensor, version: int = 0,
                           priority: Optional[int] = 0,
                           name: Optional[str] = None,
                           is_average: bool = True) -> int:
    """Submit an async in-place push_pull of ``tensor``; returns an int
    handle for ``synchronize``/``poll``. Compressed when the name was
    declared with compressor kwargs (f32 only — the codecs are f32
    transforms, as in the reference), dense otherwise; identity when
    single-worker with no PS."""
    if name is None:
        raise ValueError("byteps_push_pull requires a declared name "
                         "(keys must match across workers)")
    state = get_state()
    if not state.initialized:
        raise RuntimeError("byteps_tpu.mxnet: init() must be called first")
    with _mu:
        entry = _decl.get(name)
    if entry is None:
        byteps_declare_tensor(name)
        with _mu:
            entry = _decl[name]

    host = _as_host(tensor)
    flat = host.reshape(-1)

    if state.scheduler is None:
        # single worker, no PS: sum over one contributor == identity
        with _mu:
            hid = _imm_next[0]
            _imm_next[0] -= 1
            _pending[hid] = ("imm", tensor, host.shape, host.dtype)
        return hid

    if entry["comp"] is not None and flat.dtype == np.float32:
        reg = _comp_regs.get(name)
        if reg is None:
            from ..ops.compression import _resolve_min_compress_bytes
            from ..server.compressed import CompressedRegistry
            reg = CompressedRegistry(state.ps_client,
                                     state.config.num_workers,
                                     entry["comp"],
                                     _resolve_min_compress_bytes(None))
            with _mu:
                _comp_regs.setdefault(name, reg)
                reg = _comp_regs[name]
        hid = reg.push_pull_async(state, name, flat, average=is_average,
                                  priority=priority)
    else:
        from ..server.client import get_or_init_ctx
        ctx = get_or_init_ctx(state, name, flat)
        handle = state.handles.allocate(name)
        handle._shape = host.shape
        state.scheduler.submit(ctx, flat, handle, is_average,
                               state.config.num_workers,
                               version=state.next_version(name),
                               priority=priority)
        hid = handle.id
    with _mu:
        _pending[hid] = ("sched", tensor, host.shape, host.dtype)
    return hid


def poll(handle: int) -> bool:
    if handle < 0:
        return True
    return get_state().handles.poll(handle)


def synchronize(handle: int, timeout: Optional[float] = None):
    """Block until the push_pull behind ``handle`` completes and write the
    aggregate back into the submitted NDArray; returns it. The pending
    entry survives a timeout so the call can be retried."""
    with _mu:
        entry = _pending.get(handle)
    if entry is None:
        raise KeyError(f"unknown or already-synchronized push_pull "
                       f"handle {handle}")
    kind, tensor, shape, dtype = entry
    if kind == "imm":
        with _mu:
            _pending.pop(handle, None)
        return tensor
    try:
        out = get_state().handles.wait_and_clear(handle, timeout)
    except TimeoutError:
        raise  # still pending: keep the entry so the call can retry
    except Exception:
        # resolved with an error: the round is over — drop the entry
        # (keeping it would pin the NDArray for the process lifetime,
        # and a retry would hit a misleading 'unknown handle' KeyError
        # from the core manager, masking this error)
        with _mu:
            _pending.pop(handle, None)
        raise
    with _mu:
        _pending.pop(handle, None)
    arr = out.reshape(shape)
    if arr.dtype != dtype:
        arr = arr.astype(dtype)
    _write_back(tensor, arr)
    return tensor


def byteps_push_pull(tensor, version: int = 0,
                     priority: Optional[int] = 0,
                     name: Optional[str] = None,
                     is_average: bool = True):
    """Synchronous in-place push_pull (reference mxnet/ops.py:28-60
    semantics: the NDArray holds the cross-worker aggregate on return)."""
    h = byteps_push_pull_async(tensor, version=version, priority=priority,
                               name=name, is_average=is_average)
    return synchronize(h)
