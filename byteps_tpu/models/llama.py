"""Llama-3-style decoder-only transformer, TPU-first.

This is the framework's flagship model (BASELINE.json config 4: Llama-3-8B
with compressed push_pull). The reference framework has no model zoo of its
own — its models come from the example/ scripts — so this module is
green-field TPU design: pure-functional params pytree (composes directly with
shard_map/pjit and optax), bfloat16 activations for the MXU, RoPE, grouped-
query attention, RMSNorm, SwiGLU, and optional ring attention over a
sequence-parallel mesh axis (byteps_tpu.parallel.ring_attention).

Tensor-parallel sharding rules (applied via NamedSharding in
byteps_tpu.parallel.sharding): attention QKV/O and MLP in/out projections
shard over the ``tp`` axis in the Megatron pattern (column- then row-
parallel), embeddings shard over vocab.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336          # SwiGLU inner dim
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16        # activation/compute dtype (MXU-friendly)
    param_dtype: Any = jnp.float32   # master weights
    remat: bool = True               # jax.checkpoint each block
    # jax.checkpoint_policies name, e.g. "dots_with_no_batch_dims_saveable"
    # (save projection outputs, recompute elementwise + attention einsums);
    # None = full recompute. On the 125M bench both time the same; the
    # policy trades activation memory back for recompute at larger scale.
    remat_policy: Optional[str] = None
    # > 0: loss_fn computes the cross entropy per vocab chunk under a
    # nothing-saveable checkpoint, so the [B, S, V] logits are never
    # resident at once — trades an extra lm_head matmul in bwd for the
    # logits' HBM round-trips (the MFU experiment harness's chunked-xent
    # candidate, examples/mfu_experiments.py; bench.py A/Bs it). Vocab
    # must divide evenly or the dense path is used.
    xent_chunks: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab_size: int = 256, seq: int = 128) -> "LlamaConfig":
        """Test-scale config: same code path, toy sizes."""
        return LlamaConfig(vocab_size=vocab_size, dim=64, n_layers=2,
                           n_heads=4, n_kv_heads=2, hidden_dim=128,
                           max_seq_len=seq, remat=False)

    @staticmethod
    def small(vocab_size: int = 32000) -> "LlamaConfig":
        """~125M benchmark config that fits one chip comfortably.

        head_dim = 128 (6 heads), not the GPT-2-ish 64 (12 heads): the
        TPU vector registers are 128 lanes wide, so hd=64 attention wastes
        half of every lane-dim tile and measured 40% slower end-to-end on
        v5e; parameter shapes and FLOPs are identical either way (wq is
        (768, 768) and kv (768, 256) under both layouts). CAUTION: because
        the shapes are identical, a checkpoint trained under the previous
        12-head layout restores without error but is misinterpreted —
        retrain or restore with an explicit LlamaConfig(n_heads=12,
        n_kv_heads=4)."""
        return LlamaConfig(vocab_size=vocab_size, dim=768, n_layers=12,
                           n_heads=6, n_kv_heads=2, hidden_dim=2048,
                           max_seq_len=2048)


# --------------------------------------------------------------------- #
# init
# --------------------------------------------------------------------- #

def init_params(rng: jax.Array, cfg: LlamaConfig) -> Dict[str, Any]:
    """Initialize the parameter pytree. Layer params are stacked on a leading
    [n_layers] dim so the whole decoder runs as one lax.scan — one compiled
    block instead of n_layers copies (XLA-friendly, fast compiles)."""
    k_emb, k_blk, k_out = jax.random.split(rng, 3)
    d, h = cfg.dim, cfg.hidden_dim
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    L = cfg.n_layers

    def norm_init(*shape):
        return jnp.ones(shape, cfg.param_dtype)

    def dense_init(key, shape, scale=None):
        fan_in = shape[-2] if len(shape) >= 2 else shape[0]
        scale = scale if scale is not None else 1.0 / np.sqrt(fan_in)
        return (jax.random.normal(key, shape, cfg.param_dtype) * scale)

    ks = jax.random.split(k_blk, 7)
    block = {
        "attn_norm": norm_init(L, d),
        "wq": dense_init(ks[0], (L, d, nh * hd)),
        "wk": dense_init(ks[1], (L, d, nkv * hd)),
        "wv": dense_init(ks[2], (L, d, nkv * hd)),
        "wo": dense_init(ks[3], (L, nh * hd, d)),
        "mlp_norm": norm_init(L, d),
        "w_gate": dense_init(ks[4], (L, d, h)),
        "w_up": dense_init(ks[5], (L, d, h)),
        "w_down": dense_init(ks[6], (L, h, d)),
    }
    return {
        "embed": dense_init(k_emb, (cfg.vocab_size, d), scale=0.02),
        "blocks": block,
        "final_norm": norm_init(d),
        "lm_head": dense_init(k_out, (d, cfg.vocab_size)),
    }


def param_count(params: Dict[str, Any]) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


# --------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------- #

def _rmsnorm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    # compute in fp32 for stability, cast back
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms).astype(x.dtype) * w.astype(x.dtype)


def rope_cache(cfg: LlamaConfig, seq_len: int,
               offset: int = 0) -> tuple:
    hd = cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (np.arange(0, hd, 2) / hd))
    t = np.arange(offset, offset + seq_len)
    freqs = np.outer(t, inv_freq)                      # [S, hd/2]
    return (jnp.asarray(np.cos(freqs), jnp.float32),
            jnp.asarray(np.sin(freqs), jnp.float32))


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, S, H, hd]; rotate pairs (even, odd interleave as halves)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = cos[None, :, None, :].astype(x.dtype)
    sin = sin[None, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _attention(q, k, v, cfg: LlamaConfig, attn_impl=None):
    """Causal GQA attention. q:[B,S,nh,hd] k,v:[B,S,nkv,hd].

    ``attn_impl``: optional override, e.g. a ring-attention callable bound to
    a sequence-parallel axis (parallel/ring_attention.py).
    """
    if attn_impl is not None:
        return attn_impl(q, k, v)
    B, S, nh, hd = q.shape
    groups = nh // k.shape[2]
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    scale = 1.0 / np.sqrt(hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = jnp.tril(jnp.ones((S, S), bool))
    logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attn_sublayer(x, p, cos, sin, cfg: LlamaConfig, attn_impl=None):
    """Pre-norm attention sublayer with residual: x + Attn(RMSNorm(x)).
    Shared by the dense block here and the MoE block (models/moe.py)."""
    B, S, d = x.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = cfg.dtype

    h = _rmsnorm(x, p["attn_norm"], cfg.norm_eps)
    q = (h @ p["wq"].astype(dt)).reshape(B, S, nh, hd)
    k = (h @ p["wk"].astype(dt)).reshape(B, S, nkv, hd)
    v = (h @ p["wv"].astype(dt)).reshape(B, S, nkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = _attention(q, k, v, cfg, attn_impl)
    return x + attn.reshape(B, S, nh * hd) @ p["wo"].astype(dt)


def next_token_xent(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross-entropy. logits [B, S, V] (any float dtype —
    math runs in fp32), targets [B, S]. The single loss definition shared
    by llama/moe/pp paths.

    Uses the logsumexp form rather than log_softmax: log_softmax would
    materialize a full [B, S, V] fp32 normalized array only to gather one
    element per token, a pure HBM-bandwidth tax; logsumexp reduces to
    [B, S] and the fp32 cast fuses into the reduction (~3% step time on
    the 125M bench)."""
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    picked = jnp.take_along_axis(lf, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - picked)


def chunked_next_token_xent(hidden: jnp.ndarray, lm_head: jnp.ndarray,
                            targets: jnp.ndarray,
                            n_chunks: int) -> jnp.ndarray:
    """Mean next-token cross-entropy WITHOUT materializing [B, S, V]:
    per vocab chunk, project + logsumexp + pick under a nothing-saveable
    checkpoint, then combine the per-chunk partials (logsumexp over
    chunks; the picked logit lives in exactly one chunk, -inf in the
    rest, so a max recovers it). Trades one extra lm_head matmul in the
    backward for the logits' HBM round-trips — the MFU-experiment
    winner shape at V=32k (examples/mfu_experiments.py). Identical math
    to next_token_xent (a test asserts closeness)."""
    import functools

    V = lm_head.shape[1]
    Vc = V // n_chunks

    @functools.partial(jax.checkpoint,
                       policy=jax.checkpoint_policies.nothing_saveable)
    def chunk_lse_pick(h, Wc, base):
        logits = (h @ Wc.astype(h.dtype)).astype(jnp.float32)  # [B,S,Vc]
        lse_c = jax.scipy.special.logsumexp(logits, -1)
        inrange = (targets >= base) & (targets < base + Vc)
        loc = jnp.clip(targets - base, 0, Vc - 1)
        picked_c = jnp.where(
            inrange,
            jnp.take_along_axis(logits, loc[..., None], -1)[..., 0],
            -jnp.inf)
        return lse_c, picked_c

    Wr = lm_head.reshape(lm_head.shape[0], n_chunks, Vc)
    lses, picks = [], []
    for c in range(n_chunks):
        lse_c, picked_c = chunk_lse_pick(hidden, Wr[:, c], c * Vc)
        lses.append(lse_c)
        picks.append(picked_c)
    lse = jax.scipy.special.logsumexp(jnp.stack(lses, 0), 0)
    picked = jnp.max(jnp.stack(picks, 0), 0)
    return jnp.mean(lse - picked)


def split_batch(batch: Dict[str, jnp.ndarray]) -> tuple:
    """(inputs, targets) from either a pre-shifted {'inputs','targets'}
    batch or a raw {'tokens'} batch (shifted here)."""
    if "inputs" in batch:
        return batch["inputs"], batch["targets"]
    return batch["tokens"][:, :-1], batch["tokens"][:, 1:]


def _block(x, p, cos, sin, cfg: LlamaConfig, attn_impl=None):
    """One decoder block; p holds this layer's (unstacked) params."""
    dt = cfg.dtype
    x = attn_sublayer(x, p, cos, sin, cfg, attn_impl)
    h = _rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(h @ p["w_gate"].astype(dt))
    up = h @ p["w_up"].astype(dt)
    x = x + (gate * up) @ p["w_down"].astype(dt)
    return x


def forward_hidden(params: Dict[str, Any], tokens: jnp.ndarray,
                   cfg: LlamaConfig, attn_impl=None,
                   sp_axis: Optional[str] = None) -> jnp.ndarray:
    """The trunk: tokens [B, S] int32 -> final normed hidden [B, S, d]
    (cfg.dtype). ``forward`` adds the lm_head projection; chunked-vocab
    consumers (chunked_next_token_xent) project per chunk themselves.

    ``sp_axis``: when running inside shard_map with the sequence sharded
    over that mesh axis (ring attention), RoPE must use *global* positions:
    the cache covers S * axis_size positions and each device slices its
    chunk at axis_index * S.
    """
    B, S = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    if sp_axis is not None and attn_impl is None:
        # local dense attention would silently never cross shard
        # boundaries; ring attention over the same axis is the only
        # correct default here
        from ..parallel.ring_attention import make_ring_attn
        attn_impl = make_ring_attn(axis=sp_axis, causal=True)
    if sp_axis is not None:
        n_sp = jax.lax.axis_size(sp_axis)
        cos_full, sin_full = rope_cache(cfg, S * n_sp)
        start = jax.lax.axis_index(sp_axis) * S
        cos = jax.lax.dynamic_slice_in_dim(cos_full, start, S, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(sin_full, start, S, axis=0)
    else:
        cos, sin = rope_cache(cfg, S)

    blk = params["blocks"]

    def body(x, layer_params):
        fn = _block
        if cfg.remat:
            policy = (getattr(jax.checkpoint_policies, cfg.remat_policy)
                      if cfg.remat_policy else None)
            fn = jax.checkpoint(_block, static_argnums=(4, 5),
                                policy=policy)
        # attn_impl is closed over (static); layer params come from scan
        return fn(x, layer_params, cos, sin, cfg, attn_impl), None

    x, _ = jax.lax.scan(body, x, blk)
    return _rmsnorm(x, params["final_norm"], cfg.norm_eps)


def forward(params: Dict[str, Any], tokens: jnp.ndarray, cfg: LlamaConfig,
            attn_impl=None, sp_axis: Optional[str] = None) -> jnp.ndarray:
    """tokens [B, S] int32 -> logits [B, S, vocab] (cfg.dtype). See
    forward_hidden for the trunk and the sp_axis contract."""
    x = forward_hidden(params, tokens, cfg, attn_impl, sp_axis)
    # logits stay in cfg.dtype: materializing [B, S, V] fp32 costs ~2x the
    # HBM traffic of the whole lm_head matmul; consumers cast into their
    # fp32 reductions (next_token_xent), where the cast fuses
    return x @ params["lm_head"].astype(cfg.dtype)


def forward_pp(params: Dict[str, Any], tokens: jnp.ndarray, cfg: LlamaConfig,
               *, num_microbatches: int, pp_axis: str = "pp") -> jnp.ndarray:
    """Pipeline-parallel forward. Call INSIDE shard_map with
    ``params['blocks']`` leaves sharded on their leading [n_layers] dim over
    ``pp_axis`` (each stage holds n_layers/P layers) and everything else
    replicated. Returns logits valid ONLY on the last stage (zeros
    elsewhere); see loss_fn_pp for the masked-psum loss."""
    from ..parallel.pipeline import pipeline_forward

    B, S = tokens.shape
    cos, sin = rope_cache(cfg, S)
    x = params["embed"].astype(cfg.dtype)[tokens]

    def layer_fn(h, p_layer):
        return _block(h, p_layer, cos, sin, cfg, None)

    out = pipeline_forward(x, params["blocks"], layer_fn,
                           num_microbatches=num_microbatches, axis=pp_axis,
                           remat=cfg.remat)
    h = _rmsnorm(out, params["final_norm"], cfg.norm_eps)
    return h @ params["lm_head"].astype(cfg.dtype)


def loss_fn_pp(params: Dict[str, Any], batch: Dict[str, jnp.ndarray],
               cfg: LlamaConfig, *, num_microbatches: int,
               pp_axis: str = "pp") -> jnp.ndarray:
    """Pipeline-parallel next-token loss, replicated across stages.

    Gradient contract: blocks grads come out stage-local (sharded over
    ``pp_axis``); grads of the pp-replicated leaves (embed, final_norm,
    lm_head) are per-stage partials — psum them over ``pp_axis``
    (parallel.pipeline.replicated_grad_correction) before use.
    """
    from ..parallel.pipeline import last_stage_value

    inputs, targets = split_batch(batch)
    logits = forward_pp(params, inputs, cfg,
                        num_microbatches=num_microbatches, pp_axis=pp_axis)
    return last_stage_value(next_token_xent(logits, targets), pp_axis)


def loss_fn(params: Dict[str, Any], batch: Dict[str, jnp.ndarray],
            cfg: LlamaConfig, attn_impl=None,
            sp_axis: Optional[str] = None) -> jnp.ndarray:
    """Next-token cross-entropy.

    batch: {"tokens": [B, S]} — predicts tokens[:, 1:] from tokens[:, :-1];
    or pre-shifted {"inputs", "targets"} (required under sequence
    parallelism, where the shift must happen before sharding).
    """
    if "inputs" not in batch and sp_axis is not None:
        raise ValueError(
            "sequence parallelism requires a pre-shifted batch "
            "({'inputs', 'targets'}): shifting a sharded 'tokens' "
            "locally would gap the global sequence")
    inputs, targets = split_batch(batch)
    if cfg.xent_chunks > 0 and cfg.vocab_size % cfg.xent_chunks == 0:
        hidden = forward_hidden(params, inputs, cfg, attn_impl, sp_axis)
        loss = chunked_next_token_xent(hidden, params["lm_head"], targets,
                                       cfg.xent_chunks)
    else:
        logits = forward(params, inputs, cfg, attn_impl, sp_axis)
        loss = next_token_xent(logits, targets)
    if sp_axis is not None:
        loss = jax.lax.pmean(loss, sp_axis)
    return loss
