"""Model zoo for byteps_tpu benchmarks and examples.

The reference has no in-tree model code (models come from example/ scripts
and external hubs); this zoo provides the four BASELINE.json benchmark
vehicles natively: MLP/MNIST (config 1), ResNet-50 (config 2), BERT-large
(config 3), Llama-3 (config 4).
"""

from . import bert, llama, mlp, moe, resnet, vgg  # noqa: F401
