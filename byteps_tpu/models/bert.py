"""BERT encoder (MLM pretraining objective), TPU-first.

BERT-large is the reference's headline benchmark vehicle (README.md:34-40:
~90% scaling efficiency at 256 GPUs; BASELINE.json config 3 reproduces it on
v5e-256). Functional params pytree, bf16 activations, layers stacked for
lax.scan like models/llama.py. Post-LN residuals and learned positional
embeddings follow the original BERT; GELU FFN.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    ffn_dim: int = 4096
    max_seq_len: int = 512
    type_vocab: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def bert_large() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def bert_base() -> "BertConfig":
        return BertConfig(dim=768, n_layers=12, n_heads=12, ffn_dim=3072)

    @staticmethod
    def tiny(vocab_size: int = 256, seq: int = 64) -> "BertConfig":
        return BertConfig(vocab_size=vocab_size, dim=64, n_layers=2,
                          n_heads=4, ffn_dim=128, max_seq_len=seq,
                          remat=False)


def init_params(rng: jax.Array, cfg: BertConfig) -> Dict[str, Any]:
    d, f, L = cfg.dim, cfg.ffn_dim, cfg.n_layers
    keys = jax.random.split(rng, 12)

    def dense(key, shape, scale=0.02):
        return jax.random.normal(key, shape, cfg.param_dtype) * scale

    blocks = {
        "wq": dense(keys[0], (L, d, d)), "bq": jnp.zeros((L, d), cfg.param_dtype),
        "wk": dense(keys[1], (L, d, d)), "bk": jnp.zeros((L, d), cfg.param_dtype),
        "wv": dense(keys[2], (L, d, d)), "bv": jnp.zeros((L, d), cfg.param_dtype),
        "wo": dense(keys[3], (L, d, d)), "bo": jnp.zeros((L, d), cfg.param_dtype),
        "ln1_g": jnp.ones((L, d), cfg.param_dtype),
        "ln1_b": jnp.zeros((L, d), cfg.param_dtype),
        "w_in": dense(keys[4], (L, d, f)), "b_in": jnp.zeros((L, f), cfg.param_dtype),
        "w_out": dense(keys[5], (L, f, d)), "b_out": jnp.zeros((L, d), cfg.param_dtype),
        "ln2_g": jnp.ones((L, d), cfg.param_dtype),
        "ln2_b": jnp.zeros((L, d), cfg.param_dtype),
    }
    return {
        "tok_embed": dense(keys[6], (cfg.vocab_size, d)),
        "pos_embed": dense(keys[7], (cfg.max_seq_len, d)),
        "type_embed": dense(keys[8], (cfg.type_vocab, d)),
        "embed_ln_g": jnp.ones((d,), cfg.param_dtype),
        "embed_ln_b": jnp.zeros((d,), cfg.param_dtype),
        "blocks": blocks,
        "mlm_dense": dense(keys[9], (d, d)),
        "mlm_bias": jnp.zeros((d,), cfg.param_dtype),
        "mlm_ln_g": jnp.ones((d,), cfg.param_dtype),
        "mlm_ln_b": jnp.zeros((d,), cfg.param_dtype),
        "mlm_out_bias": jnp.zeros((cfg.vocab_size,), cfg.param_dtype),
    }


def _layernorm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out.astype(x.dtype) * g.astype(x.dtype)) + b.astype(x.dtype)


def _block(x, p, mask, cfg: BertConfig):
    B, S, d = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    dt = cfg.dtype

    q = (x @ p["wq"].astype(dt) + p["bq"].astype(dt)).reshape(B, S, nh, hd)
    k = (x @ p["wk"].astype(dt) + p["bk"].astype(dt)).reshape(B, S, nh, hd)
    v = (x @ p["wv"].astype(dt) + p["bv"].astype(dt)).reshape(B, S, nh, hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(dt)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, d)
    attn = attn @ p["wo"].astype(dt) + p["bo"].astype(dt)
    x = _layernorm(x + attn, p["ln1_g"], p["ln1_b"], cfg.norm_eps)

    h = jax.nn.gelu(x @ p["w_in"].astype(dt) + p["b_in"].astype(dt))
    h = h @ p["w_out"].astype(dt) + p["b_out"].astype(dt)
    return _layernorm(x + h, p["ln2_g"], p["ln2_b"], cfg.norm_eps)


def forward(params, tokens: jnp.ndarray, cfg: BertConfig,
            type_ids=None, attn_mask=None) -> jnp.ndarray:
    """tokens [B,S] -> final hidden states [B,S,d] (compute dtype)."""
    B, S = tokens.shape
    dt = cfg.dtype
    x = params["tok_embed"].astype(dt)[tokens]
    x = x + params["pos_embed"].astype(dt)[None, :S]
    if type_ids is not None:
        x = x + params["type_embed"].astype(dt)[type_ids]
    x = _layernorm(x, params["embed_ln_g"], params["embed_ln_b"], cfg.norm_eps)

    def body(carry, layer_params):
        fn = _block
        if cfg.remat:
            fn = jax.checkpoint(_block, static_argnums=(3,))
        return fn(carry, layer_params, attn_mask, cfg), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return x


def mlm_logits(params, hidden: jnp.ndarray, cfg: BertConfig) -> jnp.ndarray:
    dt = cfg.dtype
    h = jax.nn.gelu(hidden @ params["mlm_dense"].astype(dt)
                    + params["mlm_bias"].astype(dt))
    h = _layernorm(h, params["mlm_ln_g"], params["mlm_ln_b"], cfg.norm_eps)
    logits = h @ params["tok_embed"].astype(dt).T + params["mlm_out_bias"].astype(dt)
    return logits.astype(jnp.float32)


def loss_fn(params, batch: Dict[str, jnp.ndarray], cfg: BertConfig) -> jnp.ndarray:
    """Masked-LM loss. batch: tokens [B,S], labels [B,S] (-100 = unmasked),
    optional attn_mask [B,S] bool."""
    hidden = forward(params, batch["tokens"], cfg,
                     attn_mask=batch.get("attn_mask"))
    logits = mlm_logits(params, hidden, cfg)
    labels = batch["labels"]
    valid = labels >= 0
    safe_labels = jnp.where(valid, labels, 0)
    # logsumexp form: avoids materializing the [B, S, V] normalized fp32
    # array that log_softmax+gather would (see llama.next_token_xent)
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, -1)
    picked = jnp.take_along_axis(lf, safe_labels[..., None], -1)[..., 0]
    nll = (lse - picked) * valid
    return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
