"""VGG for image classification, TPU-first.

VGG-16 is the reference's second headline benchmark model — the one where
BytePS posts its largest dense-DP wins (+100% over Horovod on 20 Gbps TCP,
+17% worst case; reference: docs/performance.md:9,22) because VGG's 138M
parameters are dominated by the fc layers and stress gradient bandwidth.
That makes it the natural stress vehicle for the push_pull tier here too.

Functional params; NHWC layout (TPU-native); bf16 compute with fp32
master params; convs padded SAME, 2x2 max-pool between stages; classifier
is the classic 4096-4096-n_classes stack. No BatchNorm (matching the
torchvision ``vgg16`` the reference benchmarks with).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class VGGConfig:
    # channels per conv layer, "M" = 2x2 max-pool (torchvision config "D")
    plan: Tuple[Any, ...] = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                             512, 512, 512, "M", 512, 512, 512, "M")
    fc_width: int = 4096
    n_classes: int = 1000
    image_size: int = 224
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @staticmethod
    def vgg16() -> "VGGConfig":
        return VGGConfig()

    @staticmethod
    def vgg11() -> "VGGConfig":
        return VGGConfig(plan=(64, "M", 128, "M", 256, 256, "M",
                               512, 512, "M", 512, 512, "M"))

    @staticmethod
    def tiny(n_classes: int = 10) -> "VGGConfig":
        return VGGConfig(plan=(16, "M", 32, "M"), fc_width=64,
                         n_classes=n_classes, image_size=32)


def init_params(rng: jax.Array, cfg: VGGConfig) -> Dict[str, Any]:
    pd = cfg.param_dtype
    keys = iter(jax.random.split(rng, len(cfg.plan) + 4))
    params: Dict[str, Any] = {}
    cin = 3
    for i, c in enumerate(cfg.plan):
        if c == "M":
            continue
        fan_in = 3 * 3 * cin
        params[f"conv{i}"] = {
            "w": jax.random.normal(next(keys), (3, 3, cin, c), pd)
            * np.sqrt(2.0 / fan_in),
            "b": jnp.zeros((c,), pd),
        }
        cin = c
    # spatial extent after the pools (224 -> 7 for the full plan)
    spatial = cfg.image_size // (2 ** sum(1 for c in cfg.plan if c == "M"))
    flat = cin * spatial * spatial
    for j, (fin, fout) in enumerate(
            [(flat, cfg.fc_width), (cfg.fc_width, cfg.fc_width),
             (cfg.fc_width, cfg.n_classes)]):
        params[f"fc{j}"] = {
            "w": jax.random.normal(next(keys), (fin, fout), pd)
            * np.sqrt(2.0 / fin),
            "b": jnp.zeros((fout,), pd),
        }
    return params


def forward(params, x: jnp.ndarray, cfg: VGGConfig) -> jnp.ndarray:
    """x [B,H,W,3] -> logits [B,n_classes] fp32."""
    h = x.astype(cfg.dtype)
    for i, c in enumerate(cfg.plan):
        if c == "M":
            h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max,
                                      (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
            continue
        p = params[f"conv{i}"]
        h = jax.lax.conv_general_dilated(
            h, p["w"].astype(h.dtype), window_strides=(1, 1),
            padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = jax.nn.relu(h + p["b"].astype(h.dtype))
    h = h.reshape(h.shape[0], -1)
    for j in range(3):
        p = params[f"fc{j}"]
        h = h @ p["w"].astype(h.dtype) + p["b"].astype(h.dtype)
        if j < 2:
            h = jax.nn.relu(h)
    return h.astype(jnp.float32)


def loss_fn(params, batch, cfg: VGGConfig):
    logits = forward(params, batch["x"], cfg)
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.mean(jnp.take_along_axis(logp, batch["y"][:, None], -1))


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
