"""Mixtral-style sparse Mixture-of-Experts transformer with expert
parallelism over the ``ep`` mesh axis.

The reference has no MoE / expert parallelism (SURVEY.md §2.8 — absent);
this is green-field TPU design following the GShard/Switch SPMD recipe:

- routing is dense math (top-k gating, capacity-bounded dispatch masks) so
  everything stays static-shaped for XLA — no data-dependent gather loops;
- dispatch/combine are einsums against a [tokens, experts, capacity] mask,
  which XLA fuses onto the MXU;
- expert parallelism = shard the experts dim over ``ep`` and move tokens
  with two ``lax.all_to_all`` calls (dispatch there, combine back), the
  collective riding ICI inside shard_map;
- attention/embedding reuse the Llama building blocks (models/llama.py).

Tokens dropped beyond expert capacity pass through the residual unchanged
(standard Switch behavior). The router adds the Switch load-balancing
auxiliary loss.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import llama as L


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    n_experts: int = 8
    top_k: int = 2
    expert_hidden: int = 14336
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def as_llama(self) -> L.LlamaConfig:
        """The attention-relevant subset as a LlamaConfig (for reusing the
        llama block helpers)."""
        return L.LlamaConfig(
            vocab_size=self.vocab_size, dim=self.dim, n_layers=self.n_layers,
            n_heads=self.n_heads, n_kv_heads=self.n_kv_heads,
            hidden_dim=self.expert_hidden, max_seq_len=self.max_seq_len,
            rope_theta=self.rope_theta, norm_eps=self.norm_eps,
            dtype=self.dtype, param_dtype=self.param_dtype, remat=False)

    @staticmethod
    def tiny(vocab_size: int = 256, seq: int = 64) -> "MoEConfig":
        return MoEConfig(vocab_size=vocab_size, dim=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, n_experts=4, top_k=2,
                         expert_hidden=128, max_seq_len=seq, remat=False,
                         capacity_factor=2.0)

    @staticmethod
    def small(vocab_size: int = 32000) -> "MoEConfig":
        """Mixtral-flavored benchmark config at ~125M-active scale."""
        return MoEConfig(vocab_size=vocab_size, dim=768, n_layers=12,
                         n_heads=12, n_kv_heads=4, n_experts=8, top_k=2,
                         expert_hidden=2048, max_seq_len=2048)


def capacity(cfg: MoEConfig, n_tokens: int) -> int:
    """Static per-expert token capacity for a batch of n_tokens."""
    return max(1, int(math.ceil(
        cfg.top_k * n_tokens / cfg.n_experts * cfg.capacity_factor)))


# --------------------------------------------------------------------- #
# init
# --------------------------------------------------------------------- #

def init_params(rng: jax.Array, cfg: MoEConfig) -> Dict[str, Any]:
    k_emb, k_blk, k_out = jax.random.split(rng, 3)
    d, h, E = cfg.dim, cfg.expert_hidden, cfg.n_experts
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    Ln = cfg.n_layers

    def dense_init(key, shape, scale=None):
        fan_in = shape[-2] if len(shape) >= 2 else shape[0]
        scale = scale if scale is not None else 1.0 / np.sqrt(fan_in)
        return jax.random.normal(key, shape, cfg.param_dtype) * scale

    ks = jax.random.split(k_blk, 9)
    block = {
        "attn_norm": jnp.ones((Ln, d), cfg.param_dtype),
        "wq": dense_init(ks[0], (Ln, d, nh * hd)),
        "wk": dense_init(ks[1], (Ln, d, nkv * hd)),
        "wv": dense_init(ks[2], (Ln, d, nkv * hd)),
        "wo": dense_init(ks[3], (Ln, nh * hd, d)),
        "mlp_norm": jnp.ones((Ln, d), cfg.param_dtype),
        "router": dense_init(ks[4], (Ln, d, E), scale=0.02),
        "w_gate": dense_init(ks[5], (Ln, E, d, h)),
        "w_up": dense_init(ks[6], (Ln, E, d, h)),
        "w_down": dense_init(ks[7], (Ln, E, h, d)),
    }
    return {
        "embed": dense_init(k_emb, (cfg.vocab_size, d), scale=0.02),
        "blocks": block,
        "final_norm": jnp.ones((d,), cfg.param_dtype),
        "lm_head": dense_init(k_out, (d, cfg.vocab_size)),
    }


def param_count(params: Dict[str, Any]) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


# --------------------------------------------------------------------- #
# routing + expert layer
# --------------------------------------------------------------------- #

def _route(x_flat: jnp.ndarray, router_w: jnp.ndarray, cfg: MoEConfig,
           cap: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k capacity-bounded routing.

    x_flat: [T, d]. Returns (dispatch [T, E, C] float mask,
    combine [T, E, C] gate-weighted mask, aux_loss scalar).
    """
    T = x_flat.shape[0]
    E, k = cfg.n_experts, cfg.top_k
    logits = (x_flat.astype(jnp.float32)
              @ router_w.astype(jnp.float32))            # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)        # [T, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # Choice slots flattened k-major: slot 0 of every token claims capacity
    # before any slot 1 (Switch priority: primary routes never lose space
    # to secondary ones).
    idx_flat = gate_idx.T.reshape(-1)                    # [k*T]
    # int32 cumsum: positions are exact for any token count (a float32
    # cumsum stops representing consecutive integers past 2^24 routed
    # slots, silently corrupting capacity assignment)
    onehot_i = jax.nn.one_hot(idx_flat, E, dtype=jnp.int32)   # [k*T, E]
    onehot = onehot_i.astype(jnp.float32)
    pos_in_expert = (jnp.cumsum(onehot_i, axis=0) - onehot_i)  # exclusive
    pos = jnp.sum(pos_in_expert * onehot_i, axis=-1)     # [k*T]
    keep = pos < cap

    slot = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                          dtype=jnp.float32)             # [k*T, C]
    mask = (onehot * keep[:, None])[:, :, None] * slot[:, None, :]
    mask = mask.reshape(k, T, E, cap)                    # [k, T, E, C]
    dispatch = jnp.sum(mask, axis=0)                     # [T, E, C]
    combine = jnp.sum(mask * gate_vals.T.reshape(k, T, 1, 1), axis=0)

    # Switch aux loss: E * sum_e f_e * p_e  (f = token fraction routed to e
    # on the primary choice, p = mean router prob)
    prime = jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32)
    aux = E * jnp.sum(jnp.mean(prime, axis=0) * jnp.mean(probs, axis=0))
    return dispatch, combine, aux


def _expert_ffn(h: jnp.ndarray, w_gate, w_up, w_down,
                dtype) -> jnp.ndarray:
    """SwiGLU per expert. h: [E_local, C', d]."""
    g = jax.nn.silu(jnp.einsum("ecd,edh->ech", h, w_gate.astype(dtype)))
    u = jnp.einsum("ecd,edh->ech", h, w_up.astype(dtype))
    return jnp.einsum("ech,ehd->ecd", g * u, w_down.astype(dtype))


def moe_layer(x: jnp.ndarray, p: Dict[str, jnp.ndarray], cfg: MoEConfig,
              ep_axis: Optional[str] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The MoE FFN: route, dispatch, expert-compute, combine.

    x: [B, S, d]. ``p`` holds ONE layer's params; with ``ep_axis`` set (call
    inside shard_map), p's expert leaves (w_gate/w_up/w_down) carry only the
    E_local = E/P local experts and tokens travel via all_to_all. Returns
    (output [B, S, d], aux_loss).
    """
    B, S, d = x.shape
    dt = cfg.dtype
    x_flat = x.reshape(B * S, d)
    cap = capacity(cfg, B * S)
    dispatch, combine, aux = _route(x_flat, p["router"], cfg, cap)

    # [T, E, C] x [T, d] -> [E, C, d]
    h = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x_flat)
    if ep_axis is None:
        out_e = _expert_ffn(h, p["w_gate"], p["w_up"], p["w_down"], dt)
    else:
        # E -> E_local chunks scattered to their owner, each expert now sees
        # P*C token slots (C from every ep peer)
        h = jax.lax.all_to_all(h, ep_axis, split_axis=0, concat_axis=1,
                               tiled=True)               # [E_local, P*C, d]
        out_e = _expert_ffn(h, p["w_gate"], p["w_up"], p["w_down"], dt)
        out_e = jax.lax.all_to_all(out_e, ep_axis, split_axis=1,
                                   concat_axis=0, tiled=True)  # [E, C, d]
    out = jnp.einsum("tec,ecd->td", combine.astype(dt), out_e)
    return out.reshape(B, S, d), aux


# --------------------------------------------------------------------- #
# full model
# --------------------------------------------------------------------- #

def _moe_block(x, p, cos, sin, cfg: MoEConfig,
               ep_axis: Optional[str]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Attention (llama's shared sublayer) + MoE FFN. p: one layer's
    params."""
    x = L.attn_sublayer(x, p, cos, sin, cfg.as_llama())
    h = L._rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
    ffn, aux = moe_layer(h, p, cfg, ep_axis)
    return x + ffn, aux


def forward(params: Dict[str, Any], tokens: jnp.ndarray, cfg: MoEConfig,
            ep_axis: Optional[str] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """tokens [B, S] -> (logits [B, S, vocab] in cfg.dtype, mean aux
    loss); next_token_xent does its math in fp32 (llama.py)."""
    B, S = tokens.shape
    cos, sin = L.rope_cache(cfg.as_llama(), S)
    x = params["embed"].astype(cfg.dtype)[tokens]

    def body(x, layer_params):
        fn = _moe_block
        if cfg.remat:
            fn = jax.checkpoint(_moe_block, static_argnums=(4, 5))
        x, aux = fn(x, layer_params, cos, sin, cfg, ep_axis)
        return x, aux

    x, auxes = jax.lax.scan(body, x, params["blocks"])
    x = L._rmsnorm(x, params["final_norm"], cfg.norm_eps)
    # cfg.dtype logits; next_token_xent does the fp32 math (llama.py)
    return x @ params["lm_head"].astype(cfg.dtype), jnp.mean(auxes)


EXPERT_LEAVES = ("w_gate", "w_up", "w_down")


def ep_grad_correction(grads: Dict[str, Any], axis: str) -> Dict[str, Any]:
    """Turn per-device ``jax.grad(local loss)`` output into the gradient of
    the global (device-mean) loss under expert parallelism.

    Expert leaves already carry the cross-device sum — the transpose of the
    dispatch ``all_to_all`` routes every peer's cotangents back to the
    expert's owner — so they only need the 1/P mean scaling. Every other
    leaf is a local partial and gets the standard DP pmean.
    """

    def fix(path, leaf):
        keys = {getattr(k, "key", None) for k in path}
        if keys & set(EXPERT_LEAVES):
            return leaf / jax.lax.axis_size(axis)
        return jax.lax.pmean(leaf, axis)

    return jax.tree_util.tree_map_with_path(fix, grads)


def loss_fn(params: Dict[str, Any], batch: Dict[str, jnp.ndarray],
            cfg: MoEConfig, ep_axis: Optional[str] = None) -> jnp.ndarray:
    """Next-token cross-entropy + router aux loss."""
    inputs, targets = L.split_batch(batch)
    logits, aux = forward(params, inputs, cfg, ep_axis)
    return (L.next_token_xent(logits, targets)
            + cfg.router_aux_weight * aux)
