"""ResNet (v1.5) for image classification, TPU-first.

ResNet-50 is the reference's standard throughput benchmark
(docs/performance.md:5-29; BASELINE.json config 2 on v5e-8). Functional
params; NHWC layout (TPU-native); bf16 compute; BatchNorm uses per-device
batch statistics in training (the same local-BN semantics the reference gets
from per-GPU torch BN), with EMA running stats kept in a separate state
pytree for eval.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    n_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @staticmethod
    def resnet50() -> "ResNetConfig":
        return ResNetConfig()

    @staticmethod
    def resnet18() -> "ResNetConfig":
        # basic blocks approximated with bottlenecks at reduced width for
        # test scale; exact resnet18 basic-block variant is not needed for
        # the benchmark surface.
        return ResNetConfig(stage_sizes=(2, 2, 2, 2))

    @staticmethod
    def tiny(n_classes: int = 10) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(1, 1), width=16, n_classes=n_classes)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * np.sqrt(2.0 / fan_in)


def _bn_params(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def init_params(rng: jax.Array, cfg: ResNetConfig) -> Tuple[Dict, Dict]:
    """Returns (params, bn_state)."""
    pd = cfg.param_dtype
    keys = iter(jax.random.split(rng, 256))
    params: Dict[str, Any] = {
        "stem_conv": _conv_init(next(keys), 7, 7, 3, cfg.width, pd),
        "stem_bn": _bn_params(cfg.width, pd),
    }
    state: Dict[str, Any] = {"stem_bn": _bn_state(cfg.width)}

    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_sizes):
        cmid = cfg.width * (2 ** s)
        cout = cmid * 4
        for b in range(n_blocks):
            name = f"s{s}b{b}"
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, cmid, pd),
                "bn1": _bn_params(cmid, pd),
                "conv2": _conv_init(next(keys), 3, 3, cmid, cmid, pd),
                "bn2": _bn_params(cmid, pd),
                "conv3": _conv_init(next(keys), 1, 1, cmid, cout, pd),
                "bn3": _bn_params(cout, pd),
            }
            st = {"bn1": _bn_state(cmid), "bn2": _bn_state(cmid),
                  "bn3": _bn_state(cout)}
            if cin != cout or b == 0:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout, pd)
                blk["proj_bn"] = _bn_params(cout, pd)
                st["proj_bn"] = _bn_state(cout)
            params[name] = blk
            state[name] = st
            cin = cout
    params["fc_w"] = jax.random.normal(next(keys), (cin, cfg.n_classes), pd) * 0.01
    params["fc_b"] = jnp.zeros((cfg.n_classes,), pd)
    return params, state


def _batchnorm(x, p, st, cfg, train: bool):
    """Returns (y, new_state). Batch stats in train mode (per device)."""
    xf = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        m = cfg.bn_momentum
        new_st = {"mean": m * st["mean"] + (1 - m) * mean,
                  "var": m * st["var"] + (1 - m) * var}
    else:
        mean, var = st["mean"], st["var"]
        new_st = st
    inv = jax.lax.rsqrt(var + cfg.bn_eps)
    y = (xf - mean) * inv
    y = y.astype(x.dtype) * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)
    return y, new_st


def _conv(x, w, stride=1, dtype=None):
    w = w.astype(dtype or x.dtype)
    pad = ((w.shape[0] - 1) // 2, (w.shape[0] - 1) // 2)
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=(pad, (pad[0], pad[0])) if w.shape[0] > 1 else "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def forward(params, state, x: jnp.ndarray, cfg: ResNetConfig,
            train: bool = True):
    """x [B,H,W,3] -> (logits [B,n_classes] fp32, new_bn_state)."""
    dt = cfg.dtype
    x = x.astype(dt)
    new_state: Dict[str, Any] = {}

    h = _conv(x, params["stem_conv"], stride=2)
    h, new_state["stem_bn"] = _batchnorm(h, params["stem_bn"],
                                         state["stem_bn"], cfg, train)
    h = jax.nn.relu(h)
    h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")

    for s, n_blocks in enumerate(cfg.stage_sizes):
        for b in range(n_blocks):
            name = f"s{s}b{b}"
            blk, st = params[name], state[name]
            nst = {}
            stride = 2 if (s > 0 and b == 0) else 1
            y = _conv(h, blk["conv1"])
            y, nst["bn1"] = _batchnorm(y, blk["bn1"], st["bn1"], cfg, train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv2"], stride=stride)
            y, nst["bn2"] = _batchnorm(y, blk["bn2"], st["bn2"], cfg, train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv3"])
            y, nst["bn3"] = _batchnorm(y, blk["bn3"], st["bn3"], cfg, train)
            if "proj" in blk:
                sc = _conv(h, blk["proj"], stride=stride)
                sc, nst["proj_bn"] = _batchnorm(sc, blk["proj_bn"],
                                                st["proj_bn"], cfg, train)
            else:
                sc = h
            h = jax.nn.relu(y + sc)
            new_state[name] = nst

    h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
    logits = h @ params["fc_w"].astype(jnp.float32) + params["fc_b"].astype(jnp.float32)
    return logits, new_state


def loss_fn(params, state, batch, cfg: ResNetConfig):
    """Returns (loss, new_state) — use with jax.value_and_grad(has_aux=True)."""
    logits, new_state = forward(params, state, batch["x"], cfg, train=True)
    logp = jax.nn.log_softmax(logits, -1)
    loss = -jnp.mean(jnp.take_along_axis(logp, batch["y"][:, None], -1))
    return loss, new_state


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
