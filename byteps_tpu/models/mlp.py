"""Small MLP classifier — the MNIST parity model.

BASELINE.json config 1 mirrors the reference's
example/pytorch/train_mnist_byteps.py (a 2-conv + 2-fc net); this MLP plus
models/resnet's conv stack cover that surface. Pure-functional params pytree.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Sequence[int] = (256, 128)
    n_classes: int = 10
    dtype: Any = jnp.float32


def init_params(rng: jax.Array, cfg: MLPConfig) -> Dict[str, Any]:
    dims = [cfg.in_dim, *cfg.hidden, cfg.n_classes]
    params = {}
    keys = jax.random.split(rng, len(dims) - 1)
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"w{i}"] = jax.random.normal(keys[i], (din, dout),
                                            cfg.dtype) / np.sqrt(din)
        params[f"b{i}"] = jnp.zeros((dout,), cfg.dtype)
    return params


def forward(params: Dict[str, Any], x: jnp.ndarray, cfg: MLPConfig) -> jnp.ndarray:
    n_layers = len(cfg.hidden) + 1
    h = x.reshape(x.shape[0], -1).astype(cfg.dtype)
    for i in range(n_layers):
        h = h @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            h = jax.nn.relu(h)
    return h


def loss_fn(params, batch: Dict[str, jnp.ndarray], cfg: MLPConfig) -> jnp.ndarray:
    logits = forward(params, batch["x"], cfg)
    labels = batch["y"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def accuracy(params, batch, cfg: MLPConfig) -> jnp.ndarray:
    logits = forward(params, batch["x"], cfg)
    return jnp.mean((jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32))
