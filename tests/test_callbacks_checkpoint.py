"""Callbacks (Keras-adapter parity) + checkpoint/restore subsystem."""

import numpy as np
import optax
import pytest

from byteps_tpu import callbacks as cbs


def test_lr_schedule_window_and_staircase(bps):
    cb = cbs.LearningRateScheduleCallback(lambda e: 0.1 ** e,
                                          start_epoch=1, end_epoch=3)
    cl = cbs.CallbackList([cb])
    cl.on_epoch_begin(0, {})
    assert cl.lr_scale() == 1.0            # before window: untouched
    cl.on_epoch_begin(1, {})
    assert cl.lr_scale() == pytest.approx(0.1)
    cl.on_epoch_begin(2, {})
    assert cl.lr_scale() == pytest.approx(0.01)
    cl.on_epoch_begin(3, {})
    assert cl.lr_scale() == 1.0            # window closed


def test_lr_warmup_ramps_to_one(bps):
    cb = cbs.LearningRateWarmupCallback(warmup_epochs=4, size=8,
                                        steps_per_epoch=10)
    cl = cbs.CallbackList([cb])
    cl.on_epoch_begin(0, {})
    assert cl.lr_scale() == pytest.approx(1 / 8)
    cl.on_epoch_begin(2, {})
    assert cl.lr_scale() == pytest.approx(1 / 8 + (1 - 1 / 8) * 0.5)
    # fractional progress within an epoch
    cl.on_batch_begin(5, {})
    assert cl.lr_scale() == pytest.approx(1 / 8 + (1 - 1 / 8) * 0.625)
    cl.on_epoch_begin(4, {})
    assert cl.lr_scale() == 1.0


def test_apply_lr_requires_inject_hyperparams(bps):
    cl = cbs.CallbackList([cbs.LearningRateWarmupCallback(2, size=4)])
    tx = optax.sgd(0.1)
    state = tx.init({"w": np.zeros(3, np.float32)})
    with pytest.raises(ValueError, match="inject_hyperparams"):
        cl.apply_lr(state, base_lr=0.1)

    txh = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
    sh = txh.init({"w": np.zeros(3, np.float32)})
    cl.on_epoch_begin(0, {})
    sh = cl.apply_lr(sh, base_lr=0.1)
    assert float(sh.hyperparams["learning_rate"]) == pytest.approx(0.1 / 4)


def test_metric_average_and_broadcast_callbacks(bps):
    import jax
    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=4, hidden=(8,), n_classes=2)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    state = {"params": params, "metrics": {"loss": 1.5}}
    cl = cbs.CallbackList([
        cbs.BroadcastGlobalVariablesCallback(root_rank=0),
        cbs.MetricAverageCallback(),
    ])
    cl.on_train_begin(state)       # single worker: broadcast is identity
    for a, b in zip(jax.tree.leaves(state["params"]),
                    jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    cl.on_epoch_end(0, state)
    assert state["metrics"]["loss"] == pytest.approx(1.5)


def test_checkpoint_save_restore_roundtrip(bps, tmp_path):
    import jax
    from byteps_tpu.models import mlp
    from byteps_tpu.utils import checkpoint as ckpt

    cfg = mlp.MLPConfig(in_dim=4, hidden=(8,), n_classes=2)
    params = mlp.init_params(jax.random.PRNGKey(1), cfg)
    tx = optax.adam(1e-3)
    opt = tx.init(params)
    state = {"params": params, "opt_state": opt}

    path = str(tmp_path / "run")
    ckpt.save(path, state, step=10)
    ckpt.save(path, state, step=20)
    assert ckpt.latest_step(path) == 20

    restored = ckpt.restore(path, example=state, broadcast=True)
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)


def test_checkpoint_multisteps_state_not_permuted(bps, tmp_path):
    """optax.MultiSteps state fields do NOT sort alphabetically in
    declaration order — a leaf-order reshape would silently permute them;
    restore(item=example) must map by tree path."""
    import jax
    from byteps_tpu.utils import checkpoint as ckpt

    params = {"w": np.arange(4, dtype=np.float32)}
    tx = optax.MultiSteps(optax.adam(1e-3), every_k_schedule=4)
    opt = tx.init(params)
    # make the integer fields distinguishable from each other
    opt = opt._replace(mini_step=np.int32(3), gradient_step=np.int32(17))
    state = {"params": params, "opt_state": opt}

    path = str(tmp_path / "ms")
    ckpt.save(path, state, step=1)
    restored = ckpt.restore(path, example=state, broadcast=False)
    assert int(restored["opt_state"].mini_step) == 3
    assert int(restored["opt_state"].gradient_step) == 17


def test_checkpointer_periodic_and_keep(bps, tmp_path):
    import jax
    from byteps_tpu.utils import checkpoint as ckpt

    path = str(tmp_path / "run2")
    c = ckpt.Checkpointer(path, every_steps=5, keep=2)
    state = {"w": np.arange(6, dtype=np.float32)}
    for step in range(1, 21):
        c.maybe_save(step, state)
    assert ckpt.all_steps(path) == [15, 20]
    out = c.restore_latest(example=state)
    np.testing.assert_array_equal(out["w"], state["w"])


def test_checkpointer_async_save(bps, tmp_path):
    """async_save overlaps the disk write with the train loop: states are
    snapshotted at call time (later mutation must not leak into the
    file), ordered, pruned, and wait() surfaces completion."""
    from byteps_tpu.utils import checkpoint as ckpt

    path = str(tmp_path / "run_async")
    c = ckpt.Checkpointer(path, every_steps=2, keep=2, async_save=True)
    # mutate IN PLACE: the save must snapshot-copy at call time, not
    # alias the live buffer the loop keeps writing into
    state = {"w": np.zeros(8, np.float32)}
    for step in range(1, 9):
        state["w"] += 1.0
        c.maybe_save(step, state)
    c.wait()
    assert ckpt.all_steps(path) == [6, 8]
    out = ckpt.restore(path, step=8, broadcast=False)
    np.testing.assert_array_equal(out["w"], np.full(8, 8.0, np.float32))
    out6 = ckpt.restore(path, step=6, broadcast=False)
    np.testing.assert_array_equal(out6["w"], np.full(8, 6.0, np.float32))


def test_checkpoint_legacy_ef_state_migrates(bps, tmp_path):
    """A round-1-era checkpoint whose error-feedback state predates the
    prev_lr leaf restores against a current example: restore() retries
    with the legacy structure and reinserts prev_lr as zeros()."""
    from byteps_tpu.utils import checkpoint as ckpt

    legacy_ef = {"error": np.arange(4, dtype=np.float32),
                 "momentum": np.ones(4, np.float32)}
    legacy = {"params": {"w": np.arange(4, dtype=np.float32)},
              "comp_state": {"t0": legacy_ef}}
    path = str(tmp_path / "legacy")
    ckpt.save(path, legacy, step=1)

    current_ef = dict(legacy_ef, prev_lr=np.zeros((), np.float32))
    example = {"params": {"w": np.zeros(4, np.float32)},
               "comp_state": {"t0": current_ef}}
    restored = ckpt.restore(path, example=example, broadcast=False)
    ef = restored["comp_state"]["t0"]
    np.testing.assert_array_equal(ef["error"], legacy_ef["error"])
    np.testing.assert_array_equal(ef["momentum"], legacy_ef["momentum"])
    assert np.asarray(ef["prev_lr"]).shape == ()
    assert float(ef["prev_lr"]) == 0.0
    # round-trip of a CURRENT checkpoint is untouched by the shim
    ckpt.save(path, restored, step=2)
    again = ckpt.restore(path, example=example, broadcast=False)
    np.testing.assert_array_equal(again["comp_state"]["t0"]["error"],
                                  legacy_ef["error"])
