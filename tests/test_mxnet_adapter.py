"""byteps_tpu.mxnet adapter: KVStore-style optimizer + gluon trainer over
the DCN PS (reference: byteps/mxnet/__init__.py, tests/test_mxnet.py —
push_pull is identity at size 1, sums across workers, and the trainer
pre-scales so the sum IS the average).

MXNet itself is not in the image; _fake_mxnet provides the exact
NDArray/optimizer/gluon surface the adapter duck-types against.
"""

import struct
import threading

import numpy as np
import pytest

import _fake_mxnet

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

_PORT = [23800]


def _fresh_state():
    from byteps_tpu.core.state import GlobalState
    GlobalState._instance = None


@pytest.fixture()
def mx():
    return _fake_mxnet.install()


@pytest.fixture()
def bpm(mx, bps):
    """MXNet adapter over the plain (no-PS) initialized core."""
    import byteps_tpu.mxnet as bpm_mod
    bpm_mod.parameter_index = 0
    bpm_mod.ops.reset_declarations()
    yield bpm_mod
    bpm_mod.ops.reset_declarations()


def _ps_env(monkeypatch, port, num_workers=1, worker_id=0):
    monkeypatch.setenv("DMLC_NUM_WORKER", str(num_workers))
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", str(worker_id))
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")


@pytest.fixture()
def bpm_ps(mx, monkeypatch, tmp_path):
    """MXNet adapter over a 1-worker loopback PS (full distributed path).
    cwd is a tmp dir so the trainer's lr.s lands there."""
    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.chdir(tmp_path)
    _ps_env(monkeypatch, port)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    _fresh_state()
    import byteps_tpu.mxnet as bpm_mod
    bpm_mod.parameter_index = 0
    bpm_mod.ops.reset_declarations()
    bpm_mod.init()
    yield bpm_mod
    bpm_mod.shutdown()
    server.join(timeout=10)
    _fresh_state()


def test_push_pull_identity_single_worker(bpm, mx):
    x = np.random.RandomState(0).randn(32).astype(np.float32)
    t = mx.nd.array(x)
    bpm.byteps_declare_tensor("mx_t0")
    out = bpm.byteps_push_pull(t, name="mx_t0", is_average=True)
    np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)


def test_push_pull_requires_name(bpm, mx):
    with pytest.raises(ValueError):
        bpm.byteps_push_pull(mx.nd.zeros((4,)))


def test_async_poll_synchronize(bpm, mx):
    t = mx.nd.array(np.ones(8, np.float32))
    h = bpm.byteps_push_pull_async(t, name="mx_async")
    assert bpm.poll(h)
    out = bpm.synchronize(h)
    np.testing.assert_allclose(out.asnumpy(), 1.0)


def test_distributed_optimizer_sync_via_ps(bpm_ps, mx):
    """Sync mode: grads are push_pulled (identity at 1 worker) then the
    wrapped optimizer applies them — weights match plain SGD."""
    lr = 0.1
    opt = bpm_ps.DistributedOptimizer(mx.optimizer.SGD(learning_rate=lr))
    w = mx.nd.array(np.ones(16, np.float32))
    g = mx.nd.array(np.full(16, 0.5, np.float32))
    opt.update(0, w, g, opt.create_state(0, w))
    np.testing.assert_allclose(w.asnumpy(), 1.0 - lr * 0.5, rtol=1e-6)
    # delegation surface
    assert opt.learning_rate == lr
    opt.set_learning_rate(0.05)
    assert opt._optimizer.learning_rate == 0.05


def test_trainer_step_via_ps(bpm_ps, mx):
    """One trainer step at batch_size=4: the gradient is pre-scaled by
    1/(batch*size) and summed (identity here), so weights move by
    lr * g/4; lr.s carries the current learning rate."""
    lr = 0.2
    p0 = mx.gluon.Parameter("w0", np.ones(8, np.float32))
    p1 = mx.gluon.Parameter("w1", np.full(4, 2.0, np.float32))
    trainer = bpm_ps.DistributedTrainer(
        [p0, p1], "sgd", {"learning_rate": lr})
    p0._grad[0][:] = np.full(8, 4.0, np.float32)
    p1._grad[0][:] = np.full(4, 8.0, np.float32)
    trainer.step(4)
    np.testing.assert_allclose(p0._data[0].asnumpy(), 1.0 - lr * 1.0,
                               rtol=1e-6)
    np.testing.assert_allclose(p1._data[0].asnumpy(), 2.0 - lr * 2.0,
                               rtol=1e-6)
    with open("lr.s", "rb") as f:
        assert struct.unpack("d", f.read(8))[0] == lr


def test_trainer_two_worker_average(mx, monkeypatch, tmp_path):
    """Worker 0 = the gluon trainer; worker 1 = a raw PSClient replaying
    the same declaration order. The trainer's pre-scaled sum equals the
    cross-worker average of per-example gradients."""
    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.chdir(tmp_path)
    _ps_env(monkeypatch, port, num_workers=2, worker_id=0)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=2, num_servers=1)), daemon=True)
    server.start()
    _fresh_state()
    import byteps_tpu.mxnet as bpm
    bpm.parameter_index = 0
    bpm.ops.reset_declarations()
    bpm.init()
    try:
        w0 = np.ones(8, np.float32)
        g0 = np.full(8, 2.0, np.float32)
        g1 = np.full(8, 6.0, np.float32)
        batch = 2
        lr = 0.1

        # worker 1: same names, same order -> same keys
        reg = TensorRegistry(Config(num_workers=2, num_servers=1))
        c1 = PSClient([f"127.0.0.1:{port}"], worker_id=1)
        res = {}

        def w1():
            pctx = reg.init_tensor("parameter_0", w0.nbytes,
                                   DataType.FLOAT32)
            res["param"] = c1.push_pull(pctx, np.zeros_like(w0),
                                        average=False, num_workers=2)
            gctx = reg.init_tensor("gradient_0", g1.nbytes,
                                   DataType.FLOAT32)
            res["grad"] = c1.push_pull(gctx, g1 / (batch * 2),
                                       average=False, num_workers=2)

        th = threading.Thread(target=w1, daemon=True)
        th.start()

        p = mx.gluon.Parameter("w", w0)
        trainer = bpm.DistributedTrainer([p], "sgd",
                                         {"learning_rate": lr})
        p._grad[0][:] = g0
        trainer.step(batch)
        th.join(timeout=60)
        assert not th.is_alive()

        mean_grad = (g0 / batch + g1 / batch) / 2
        np.testing.assert_allclose(res["param"], w0, rtol=1e-6)
        np.testing.assert_allclose(res["grad"], mean_grad, rtol=1e-5)
        np.testing.assert_allclose(p._data[0].asnumpy(),
                                   w0 - lr * mean_grad, rtol=1e-5)
        c1.close(shutdown_servers=False)
    finally:
        bpm.shutdown()
        server.join(timeout=10)
        _fresh_state()


def test_distributed_optimizer_async_mode(mx, monkeypatch):
    """BYTEPS_ENABLE_ASYNC: the optimizer seeds the server store with the
    PRE-update weights, pushes the weight delta, and pulls authoritative
    weights — so the first step yields w0 - lr*g, not a bare delta
    (regression: unseeded async lost the initial weights)."""
    port = _PORT[0]
    _PORT[0] += 1
    _ps_env(monkeypatch, port, num_workers=2, worker_id=0)
    monkeypatch.setenv("BYTEPS_ENABLE_ASYNC", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=2, num_servers=1,
                           enable_async=True)), daemon=True)
    server.start()
    _fresh_state()
    import byteps_tpu.mxnet as bpm
    bpm.parameter_index = 0
    bpm.ops.reset_declarations()
    bpm.init()
    try:
        lr = 0.1
        w0 = np.arange(16, dtype=np.float32)
        g = np.full(16, 2.0, np.float32)

        reg = TensorRegistry(Config(num_workers=2, num_servers=1))
        c1 = PSClient([f"127.0.0.1:{port}"], worker_id=1)

        def w1():
            ctx = reg.init_tensor("weight_5", w0.nbytes, DataType.FLOAT32)
            c1.init_weights(ctx, w0.copy())   # init barrier participant
            c1.push_delta_pull_weights(ctx, np.zeros_like(w0))

        th = threading.Thread(target=w1, daemon=True)
        th.start()

        opt = bpm.DistributedOptimizer(mx.optimizer.SGD(learning_rate=lr))
        w = mx.nd.array(w0.copy())
        opt.update(5, w, mx.nd.array(g), None)
        th.join(timeout=60)
        assert not th.is_alive()
        np.testing.assert_allclose(w.asnumpy(), w0 - lr * g, rtol=1e-5)
        c1.close(shutdown_servers=False)
    finally:
        bpm.shutdown()
        server.join(timeout=10)
        _fresh_state()


def test_broadcast_parameters_two_workers(mx, monkeypatch):
    """broadcast_parameters: non-root pushes zeros, so everyone ends up
    with the root's values."""
    port = _PORT[0]
    _PORT[0] += 1
    _ps_env(monkeypatch, port, num_workers=2, worker_id=0)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=2, num_servers=1)), daemon=True)
    server.start()
    _fresh_state()
    import byteps_tpu.mxnet as bpm
    bpm.parameter_index = 0
    bpm.ops.reset_declarations()
    bpm.init()
    try:
        vals = np.arange(16, dtype=np.float32)
        t = mx.nd.array(vals)

        reg = TensorRegistry(Config(num_workers=2, num_servers=1))
        c1 = PSClient([f"127.0.0.1:{port}"], worker_id=1)
        res = {}

        def w1():
            ctx = reg.init_tensor("broadcast_parameter_0", vals.nbytes,
                                  DataType.FLOAT32)
            res["w1"] = c1.push_pull(ctx, np.zeros_like(vals),
                                     average=False, num_workers=2)

        th = threading.Thread(target=w1, daemon=True)
        th.start()
        bpm.broadcast_parameters({"w": t}, root_rank=0)
        th.join(timeout=60)
        assert not th.is_alive()
        np.testing.assert_allclose(t.asnumpy(), vals)
        np.testing.assert_allclose(res["w1"], vals)
        c1.close(shutdown_servers=False)
    finally:
        bpm.shutdown()
        server.join(timeout=10)
        _fresh_state()


def test_compression_params_routing(bpm_ps, mx, monkeypatch):
    """compression_params sets byteps_* attributes, strips momentum/wd
    from the optimizer (the comm stack owns them), and builds the
    nag(wdmom(none)) intra stack — the reference's contract
    (mxnet/__init__.py:236-317)."""
    monkeypatch.setenv("BYTEPS_MIN_COMPRESS_BYTES", "0")
    params = [mx.gluon.Parameter("a", np.ones(64, np.float32)),
              mx.gluon.Parameter("b", np.ones(8, np.float32))]
    trainer = bpm_ps.DistributedTrainer(
        params, "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        compression_params={"compressor": "onebit", "scaling": True,
                            "ef": "vanilla", "momentum": "nesterov"})
    for p in params:
        assert p.byteps_compressor_type == "onebit"
        assert p.byteps_ef_type == "vanilla"
        assert p.byteps_momentum_type == "nesterov"
        assert p.byteps_compressor_onebit_scaling == "True"
        assert p.byteps_momentum_mu == 0.9
    # stripped from the optimizer
    assert trainer._optimizer.momentum == 0.0
    assert trainer._optimizer.wd == 0.0
    from byteps_tpu.mxnet.compression import (NagAdapter,
                                              WeightDecayMomentumAdapter)
    stack = trainer._intra_compressors["a"]
    assert isinstance(stack, NagAdapter)
    assert isinstance(stack.compressor, WeightDecayMomentumAdapter)
    # a full step runs through the compressed PS path
    params[0]._grad[0][:] = np.random.RandomState(0).randn(64).astype(
        np.float32)
    params[1]._grad[0][:] = np.random.RandomState(1).randn(8).astype(
        np.float32)
    trainer.step(1)
    from byteps_tpu.mxnet import ops as mxops
    assert "gradient_0" in mxops._comp_regs  # codec tier engaged
    assert not np.allclose(params[0]._data[0].asnumpy(), 1.0)


def test_trainer_compressed_randomk_roundtrip(bpm_ps, mx, monkeypatch):
    """randomk+EF through the real server codec mirror: training signal
    survives (EF accumulates what the sparsifier drops)."""
    monkeypatch.setenv("BYTEPS_MIN_COMPRESS_BYTES", "0")
    p = mx.gluon.Parameter("w", np.zeros(32, np.float32))
    trainer = bpm_ps.DistributedTrainer(
        [p], "sgd", {"learning_rate": 0.5},
        compression_params={"compressor": "randomk", "k": 8, "seed": 3})
    g = np.random.RandomState(2).randn(32).astype(np.float32)
    moved = np.zeros(32, np.float32)
    for _ in range(8):
        p._grad[0][:] = g
        before = p._data[0].asnumpy()
        trainer.step(1)
        moved += before - p._data[0].asnumpy()
    # over 8 steps the randomk samples cover most coordinates; the total
    # movement must correlate strongly with the true gradient direction
    cos = np.dot(moved, g) / (np.linalg.norm(moved) * np.linalg.norm(g))
    assert cos > 0.5


def test_nag_adapter_math(mx):
    """NAG wrapper recurrence: m <- mu*(m+g); g <- g+m (below threshold
    only)."""
    from byteps_tpu.mxnet.compression import Compression, NoneCompressor
    mu = 0.9
    nag = Compression.nag(NoneCompressor(), mu, threshold=1000)
    g = np.full(4, 1.0, np.float32)
    mom = np.zeros(4, np.float32)
    for _ in range(3):
        t, ctx = nag.compress(mx.nd.array(g))
        out = nag.decompress(t, ctx).asnumpy()
        mom = mu * (mom + g)
        np.testing.assert_allclose(out, g + mom, rtol=1e-6)


def test_wdmom_adapter_math(mx):
    """wd-momentum wrapper: m <- mu*(m + wd*x); g <- g + m + wd*x (above
    threshold)."""
    from byteps_tpu.mxnet.compression import Compression, NoneCompressor
    mu, wd = 0.9, 0.01
    wdm = Compression.wdmom(NoneCompressor(), mu, wd, threshold=0)
    x = np.full(4, 2.0, np.float32)
    g = np.full(4, 1.0, np.float32)
    mom = np.zeros(4, np.float32)
    for _ in range(3):
        t, ctx = wdm.compress(mx.nd.array(g))
        out = wdm.decompress(t, ctx, x=mx.nd.array(x)).asnumpy()
        mom = mu * (mom + wd * x)
        np.testing.assert_allclose(out, g + mom + wd * x, rtol=1e-5)


def test_fp16_compressor(mx):
    from byteps_tpu.mxnet.compression import Compression
    x = mx.nd.array(np.random.RandomState(0).randn(16).astype(np.float32))
    c, ctx = Compression.fp16.compress(x)
    assert c.dtype == np.float16
    out = Compression.fp16.decompress(c, ctx)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), atol=1e-2)


def test_distributed_trainer_unwraps_distributed_optimizer(bpm_ps, mx):
    with pytest.warns(UserWarning):
        trainer = bpm_ps.DistributedTrainer(
            [mx.gluon.Parameter("w", np.ones(4, np.float32))],
            bpm_ps.DistributedOptimizer(
                mx.optimizer.SGD(learning_rate=0.1)))
    assert not isinstance(trainer._optimizer, bpm_ps.DistributedOptimizer)
