"""Adaptive codec control plane + lossless byte-plane wire tier.

Three layers of evidence (docs/compression.md):

- lossless codec property suite: BITWISE round-trip over fp32 (NaN
  payloads, inf, subnormals, -0.0, odd lengths, empty) and bf16 byte
  planes — "compressed" must not mean "lossy";
- controller determinism: the ladder walker is a pure function of
  (plan state, signal) — two instances fed identical round signals
  emit identical plans, the invariant server-side folding relies on;
- end-to-end plane behavior on the loopback PS: pinned-lossless rounds
  bitwise-equal dense rounds, signal-driven escalation/de-escalation
  re-installs the server codec only at quiescent boundaries, and a
  mis-tagged fold is rejected loudly, never silently mis-summed.
"""

import contextlib
import threading

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.codec_plane import (
    CodecController, CodecPlane, CodecPlan, RoundSignal, WIRE_CODEC_IDS,
)
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.scheduler import HandleManager, PipelineScheduler
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.ops.compression.lossless import (
    HostLossless, LosslessCodec, decode_planes, encode_planes,
)
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


# --------------------------------------------------------------------- #
# lossless codec property suite
# --------------------------------------------------------------------- #


def _nasty_f32(n: int, seed: int = 0) -> np.ndarray:
    """fp32 payloads that break anything not bitwise: quiet/signaling
    NaN bit patterns, +-inf, subnormals, -0.0, huge/tiny magnitudes."""
    rng = np.random.RandomState(seed)
    x = (rng.randn(n) * 10.0 ** rng.randint(-40, 38, n)).astype(np.float32)
    specials = np.array([
        np.float32(np.nan), np.float32(-np.nan), np.inf, -np.inf,
        -0.0, 0.0, np.float32(1e-42), np.float32(-1e-42),
        np.finfo(np.float32).max, np.finfo(np.float32).min,
        np.finfo(np.float32).tiny,
    ], np.float32)
    for i, v in enumerate(specials):
        if i < n:
            x[i] = v
    if n > len(specials):
        # a non-canonical (signaling) NaN bit pattern must survive
        # byte-for-byte — float round-trips through compute would
        # quiet it, byte planes must not
        x.view(np.uint32)[len(specials)] = 0x7F800001
    return x


@pytest.mark.parametrize("n", [1, 2, 3, 7, 31, 255, 1000, 4096, 65537])
def test_lossless_roundtrip_bitwise_fp32(n):
    x = _nasty_f32(n, seed=n)
    c = HostLossless(n)
    wire = c.compress(x)
    assert len(wire) <= c.wire_bytes(), "wire exceeded the declared bound"
    back = c.decompress(np.frombuffer(wire, np.uint8))
    assert back.tobytes() == x.tobytes()


def test_lossless_roundtrip_bitwise_bf16():
    import jax.numpy as jnp
    rng = np.random.RandomState(1)
    for n in (1, 5, 1000):
        b = jnp.asarray(rng.randn(n) * 1e3, jnp.bfloat16)
        raw = np.asarray(b).view(np.uint8).reshape(-1)
        c = LosslessCodec(itemsize=2)
        assert bytes(c.decompress_bytes(c.compress_bytes(raw))) \
            == raw.tobytes()


def test_lossless_empty_and_wire_validation():
    c = LosslessCodec(itemsize=4)
    empty = c.compress_bytes(np.zeros(0, np.uint8))
    assert bytes(c.decompress_bytes(empty)) == b""
    # truncated / corrupted wires must raise, not misparse
    x = np.arange(64, dtype=np.float32)
    wire = bytearray(HostLossless(64).compress(x))
    with pytest.raises(ValueError):
        decode_planes(bytes(wire[:10]), 4)
    wire[5] = 3  # nplanes=3 on an fp32 wire
    with pytest.raises(ValueError):
        decode_planes(bytes(wire), 4)


def test_lossless_compresses_low_entropy_planes():
    # gradient-shaped data: tightly clustered exponents, noisy mantissa
    # — the sign/exponent plane must shrink the wire below dense
    rng = np.random.RandomState(2)
    x = (rng.randn(65536) * 1e-3).astype(np.float32)
    wire = HostLossless(65536).compress(x)
    assert len(wire) < x.nbytes, "lossless tier failed to compress"
    # incompressible worst case: the raw-passthrough mode caps the wire
    noise = rng.randint(0, 2 ** 32, 4096, np.uint32).view(np.float32)
    c = HostLossless(4096)
    assert len(c.compress(noise)) <= c.wire_bytes()
    assert c.decompress(np.frombuffer(c.compress(noise), np.uint8)
                        ).tobytes() == noise.tobytes()


def test_lossless_plane_transform_inverse():
    raw = np.arange(48, dtype=np.uint8)
    assert bytes(decode_planes(encode_planes(raw, 4), 4)) == raw.tobytes()
    assert bytes(decode_planes(encode_planes(raw, 2), 2)) == raw.tobytes()


# --------------------------------------------------------------------- #
# controller determinism + hysteresis
# --------------------------------------------------------------------- #


def _signals(pattern, ratio_hi=100.0, ratio_lo=0.1):
    """PULL-bound ('P') / COMPUTE-bound ('C') signal sequence."""
    out = []
    for i, ch in enumerate(pattern):
        pull = ratio_hi if ch == "P" else ratio_lo
        out.append(RoundSignal(step=i + 1, compute_ms=1.0, pull_ms=pull))
    return out


def test_controller_hysteresis_ladder():
    c = CodecController(up_rounds=3, down_rounds=5)
    plan = CodecPlan()
    tiers = [c.decide(plan, s) for s in _signals("PP")]
    assert tiers == [None, None], "escalated before the streak filled"
    assert c.decide(plan, _signals("P")[0]) == "lossless"
    # streak resets after a switch: two more PULL-bound rounds hold
    tiers = [c.decide(plan, s) for s in _signals("PP")]
    assert tiers == [None, None]
    assert c.decide(plan, _signals("P")[0]) == "onebit"
    # at the top of the ladder: further pressure holds
    assert all(c.decide(plan, s) is None for s in _signals("PPPP"))
    # recovery: down_rounds consecutive COMPUTE-bound rounds per rung
    tiers = [c.decide(plan, s) for s in _signals("CCCCC")]
    assert tiers[:4] == [None] * 4 and tiers[4] == "lossless"
    tiers = [c.decide(plan, s) for s in _signals("CCCCC")]
    assert tiers[4] == "dense" and plan.rung == 0
    # a PULL-bound blip resets the de-escalation streak
    c.decide(plan, _signals("P")[0])
    plan2 = CodecPlan(rung=1)
    mixed = [c.decide(plan2, s) for s in _signals("CCCCPCCCC")]
    assert all(t is None for t in mixed), "blip failed to reset streak"


def test_controller_determinism_identical_signal_streams():
    """The aggregation-safety invariant: two independent controllers
    (two workers) fed the same round signals walk identical plans."""
    sigs = _signals("PPPPPCCPPPCCCCCCCCCCPPPPPP")
    a, b = (CodecController(up_rounds=2, down_rounds=4) for _ in range(2))
    pa, pb = CodecPlan(), CodecPlan()
    trace_a = [(a.decide(pa, s), pa.rung) for s in sigs]
    trace_b = [(b.decide(pb, s), pb.rung) for s in sigs]
    assert trace_a == trace_b
    assert dataclass_tuple(pa) == dataclass_tuple(pb)


def dataclass_tuple(p: CodecPlan):
    return (p.rung, p.epoch, p.up_streak, p.down_streak, p.applied)


def test_wire_codec_ids_are_stable():
    # wire contract with native/ps.cc enum WireCodec — renumbering
    # breaks rolling upgrades
    assert WIRE_CODEC_IDS == {"dense": 1, "lossless": 2, "onebit": 3,
                              "topk": 4, "randomk": 5, "dithering": 6}


# --------------------------------------------------------------------- #
# end-to-end: plane + scheduler + loopback server
# --------------------------------------------------------------------- #


_PORT = [24310]


@contextlib.contextmanager
def _stack(monkeypatch=None, num_workers=1, **plane_env):
    """Loopback server + client + scheduler + plane, manually wired the
    way GlobalState.init does it."""
    import os
    port = _PORT[0]
    _PORT[0] += 1
    cfg = Config(num_workers=num_workers, num_servers=1)
    t = threading.Thread(target=run_server, args=(port, cfg), daemon=True)
    t.start()
    client = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    reg = TensorRegistry(cfg)
    sched = PipelineScheduler(client, registry=reg)
    prior = {k: os.environ.get(k) for k in plane_env}
    os.environ.update(plane_env)
    try:
        plane = CodecPlane(client, reg, None, None, num_workers,
                           scheduler=sched)
        sched.attach_codec_plane(plane)
        handles = HandleManager()
        yield client, reg, sched, plane, handles
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        sched.stop()
        client.close()
        t.join(timeout=10)


def _round(reg, sched, handles, name, x, timeout=60):
    ctx = reg.init_tensor(name, x.nbytes, DataType.FLOAT32)
    h = handles.allocate(name)
    sched.submit(ctx, x, h, False, 1)
    return h.wait(timeout)


def test_plane_pinned_lossless_is_bitwise_dense():
    n = 32768  # >= BYTEPS_CODEC_MIN_BYTES
    with _stack(BYTEPS_CODEC_PIN="lossless") as (c, reg, sched, plane,
                                                 handles):
        for r in range(3):
            xr = _nasty_f32(n, seed=100 + r)
            out = _round(reg, sched, handles, "pin", xr)
            assert out.tobytes() == xr.tobytes()
        snap = plane.plan_snapshot()
        assert snap["pin"]["tier"] == "lossless"
        assert snap["pin"]["epoch"] >= 1


def test_plane_small_and_non_f32_leaves_stay_dense():
    with _stack(BYTEPS_CODEC_PIN="lossless") as (c, reg, sched, plane,
                                                 handles):
        small = np.arange(64, dtype=np.float32)       # < min bytes
        out = _round(reg, sched, handles, "small", small)
        np.testing.assert_array_equal(out, small)
        ints = np.arange(32768, dtype=np.int32)       # not f32
        out = _round(reg, sched, handles, "ints", ints)
        np.testing.assert_array_equal(out, ints)
        assert "small" not in plane.plan_snapshot()
        assert "ints" not in plane.plan_snapshot()


@pytest.mark.slow
def test_plane_signal_driven_escalation_and_recovery():
    """The adaptive loop end-to-end: injected PULL-bound signals walk a
    live leaf dense -> lossless -> onebit (server re-installed at each
    quiescent boundary, numerics correct for each tier), COMPUTE-bound
    signals walk it back down, and the de-escalated key folds dense
    again (the compressor=none clear path)."""
    n = 32768
    rng = np.random.RandomState(3)
    x = rng.randn(n).astype(np.float32)
    with _stack(BYTEPS_CODEC_UP_ROUNDS="2", BYTEPS_CODEC_DOWN_ROUNDS="3") \
            as (c, reg, sched, plane, handles):
        out = _round(reg, sched, handles, "leaf", x)       # dense round
        assert out.tobytes() == x.tobytes()
        step = [0]

        def push_signals(kind, count):
            for _ in range(count):
                step[0] += 1
                plane.observe(RoundSignal(
                    step=step[0], compute_ms=1.0,
                    pull_ms=100.0 if kind == "P" else 0.1))

        push_signals("P", 2)
        out = _round(reg, sched, handles, "leaf", x * 2)   # lossless now
        assert out.tobytes() == (x * 2).tobytes()
        assert plane.plan_snapshot()["leaf"]["tier"] == "lossless"

        push_signals("P", 2)
        out = np.asarray(_round(reg, sched, handles, "leaf", x * 3))
        assert plane.plan_snapshot()["leaf"]["tier"] == "onebit"
        # onebit semantics: sign * mean|x| (scaled), not identity
        expect = np.sign(x * 3).astype(np.float32) * np.float32(
            np.mean(np.abs((x * 3).astype(np.float32))))
        np.testing.assert_allclose(out, expect, rtol=1e-6)

        push_signals("C", 6)  # two rungs down: onebit -> lossless -> dense
        out = _round(reg, sched, handles, "leaf", x * 4)
        assert plane.plan_snapshot()["leaf"]["tier"] == "dense"
        assert out.tobytes() == (x * 4).tobytes()


def test_two_plane_instances_identical_plans():
    """Two independent scheduler+plane stacks (two 'workers') fed the
    same submissions and the same round signals emit identical codec
    plans — the cross-worker determinism the wire tag enforces."""
    n = 32768
    x = np.arange(n, dtype=np.float32)
    sigs = _signals("PPP" + "CCCC")
    snaps = []
    for _ in range(2):
        with _stack(BYTEPS_CODEC_UP_ROUNDS="2",
                    BYTEPS_CODEC_DOWN_ROUNDS="3") \
                as (c, reg, sched, plane, handles):
            trace = []
            _round(reg, sched, handles, "det", x)
            for s in sigs:
                plane.observe(s)
                _round(reg, sched, handles, "det", x)
                trace.append(plane.plan_snapshot()["det"])
            snaps.append(trace)
    assert snaps[0] == snaps[1]


def test_server_rejects_mistagged_fold_loudly():
    """A push whose codec tag disagrees with the store's active codec
    must error-reply (the client raises) and must NOT fold — the
    published aggregate stays the previous round's."""
    with _stack() as (client, reg, sched, plane, handles):
        x = np.arange(1024, dtype=np.float32)
        ctx = reg.init_tensor("tag", x.nbytes, DataType.FLOAT32)
        client.ensure_init(ctx, x.nbytes)
        p = ctx.partitions[0]
        client.zpush(p.server, p.key, x, CMD_F32, epoch=(1 << 16),
                     codec=(0 << 8) | WIRE_CODEC_IDS["dense"])
        out = np.empty(1024, np.float32)
        client.zpull(p.server, p.key, out, CMD_F32)
        np.testing.assert_array_equal(out, x)
        with pytest.raises(RuntimeError):
            client.zpush(p.server, p.key, x * 9, CMD_F32,
                         epoch=(2 << 16),
                         codec=(0 << 8) | WIRE_CODEC_IDS["lossless"])
        client.zpull(p.server, p.key, out, CMD_F32)
        np.testing.assert_array_equal(
            out, x), "mis-tagged payload silently folded"


def test_comp_init_none_clears_server_codec():
    with _stack() as (client, reg, sched, plane, handles):
        x = np.arange(2048, dtype=np.float32)
        ctx = reg.init_tensor("clr", x.nbytes, DataType.FLOAT32)
        client.ensure_init(ctx, x.nbytes)
        p = ctx.partitions[0]
        client.comp_init(p.server, p.key, "compressor=lossless;n=2048")
        # dense push against a compressed store: mode gate rejects
        with pytest.raises(RuntimeError):
            client.zpush(p.server, p.key, x, CMD_F32, epoch=(1 << 16))
        client.comp_init(p.server, p.key, "compressor=none;n=2048")
        client.zpush(p.server, p.key, x, CMD_F32, epoch=(2 << 16))
        out = np.empty(2048, np.float32)
        client.zpull(p.server, p.key, out, CMD_F32)
        np.testing.assert_array_equal(out, x)


def test_lossless_two_workers_exact_sum():
    """Multi-worker lossless fold: decode-then-fold of exact payloads
    is the exact f32 sum — identical to what the dense path produces
    for the same arrival order (1 partition, 2 workers: sum of two
    floats is order-free)."""
    port = _PORT[0]
    _PORT[0] += 1
    cfg = Config(num_workers=2, num_servers=1)
    t = threading.Thread(target=run_server, args=(port, cfg), daemon=True)
    t.start()
    addr = [f"127.0.0.1:{port}"]
    from byteps_tpu.server.compressed import CompressedTensor
    c0, c1 = PSClient(addr, 0), PSClient(addr, 1)
    rng = np.random.RandomState(5)
    x0 = rng.randn(4096).astype(np.float32)
    x1 = rng.randn(4096).astype(np.float32)

    def reg_ctx():
        return TensorRegistry(cfg).init_tensor("two", x0.nbytes,
                                               DataType.FLOAT32)
    ct0 = CompressedTensor(c0, reg_ctx(), {"compressor": "lossless"}, 2)
    ct1 = CompressedTensor(c1, reg_ctx(), {"compressor": "lossless"}, 2)
    res = {}
    th = threading.Thread(
        target=lambda: res.setdefault("w1", ct1.push_pull(x1,
                                                          average=False)),
        daemon=True)
    th.start()
    res["w0"] = ct0.push_pull(x0, average=False)
    th.join(timeout=30)
    assert not th.is_alive()
    expect = x0 + x1
    assert res["w0"].tobytes() == expect.tobytes()
    assert res["w1"].tobytes() == expect.tobytes()
    # both workers announce SHUTDOWN so the server exits promptly (a
    # single shutdown of a 2-worker server leaves it listening and the
    # join below would burn its full timeout)
    c0.close()
    c1.close()
    t.join(timeout=10)
    assert not t.is_alive()
