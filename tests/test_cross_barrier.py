"""CrossBarrier (barrier-crossing scheduled optimizer) tests against a
loopback PS: per-parameter updates applied by the poller must match a
plain single-process torch run exactly (1 worker => push_pull identity),
for SGD-with-momentum and Adam (reference: torch/cross_barrier.py)."""

import threading

import numpy as np
import pytest
import torch

from byteps_tpu.config import Config
from byteps_tpu.server import run_server

_PORT = [26700]


def _mk_model(seed):
    torch.manual_seed(seed)
    return torch.nn.Sequential(
        torch.nn.Linear(12, 24), torch.nn.ReLU(),
        torch.nn.Linear(24, 4))


def _data(n=48):
    rng = np.random.RandomState(0)
    x = torch.from_numpy(rng.randn(n, 12).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 4, n).astype(np.int64))
    return x, y


def _train_plain(make_opt, steps):
    model = _mk_model(7)
    opt = make_opt(model.parameters())
    x, y = _data()
    for _ in range(steps):
        opt.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
    return model


@pytest.fixture()
def bps_torch(monkeypatch):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu.torch as bpt
    bpt.init()
    yield bpt
    bpt.shutdown()
    server.join(timeout=10)
    GlobalState._instance = None


@pytest.mark.parametrize("make_opt", [
    lambda ps: torch.optim.SGD(ps, lr=0.1, momentum=0.9),
    lambda ps: torch.optim.Adam(ps, lr=0.01),
], ids=["sgd_momentum", "adam"])
def test_cross_barrier_matches_plain(bps_torch, make_opt):
    from byteps_tpu.torch.cross_barrier import CrossBarrier

    steps = 8
    ref = _train_plain(make_opt, steps)

    model = _mk_model(7)
    inner = make_opt(model.parameters())
    dopt = bps_torch.DistributedOptimizer(
        inner, named_parameters=model.named_parameters())
    opt = CrossBarrier(model, dopt, num_steps=steps)
    opt.step()  # broadcast-time init step (reference convention: step 0
    #             fires during broadcast_optimizer_state, before training)
    x, y = _data()
    for _ in range(steps):
        opt.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
    opt.drain()

    for (n1, p1), (n2, p2) in zip(ref.named_parameters(),
                                  model.named_parameters()):
        np.testing.assert_allclose(
            p1.detach().numpy(), p2.detach().numpy(),
            rtol=2e-5, atol=2e-5, err_msg=n1)


def test_cross_barrier_forward_waits_for_updates(bps_torch):
    """The forward pre-hook must block until the poller released the
    parameter's lock — run many steps and check the loss is finite and
    decreasing (a lost-update race shows up as NaN/explosion)."""
    from byteps_tpu.torch.cross_barrier import CrossBarrier

    model = _mk_model(3)
    dopt = bps_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    opt = CrossBarrier(model, dopt, num_steps=10 ** 6)
    opt.step()  # broadcast-time init step
    x, y = _data()
    losses = []
    for _ in range(30):
        opt.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    opt.drain()
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_cross_barrier_rejects_unsupported_optimizer(bps_torch):
    """A poller-side failure (here: unsupported optimizer class) must
    surface in drain()/step(), not die silently on the poller thread."""
    from byteps_tpu.torch.cross_barrier import CrossBarrier

    model = _mk_model(1)
    dopt = bps_torch.DistributedOptimizer(
        torch.optim.AdamW(model.parameters(), lr=0.01),
        named_parameters=model.named_parameters())
    opt = CrossBarrier(model, dopt, num_steps=4)
    x, y = _data(8)
    opt._step = 1  # past the eager step-0 path
    loss = torch.nn.functional.cross_entropy(model(x), y)
    loss.backward()              # hooks submit; poller hits _update_one
    with pytest.raises(ValueError, match="supports SGD"):
        opt.drain()


def test_cross_barrier_rejects_unreplicated_flags(bps_torch):
    """Option flags that change the update math (maximize/amsgrad/
    centered) must fail at wrap time, not silently step differently
    (round-4 review regression)."""
    from byteps_tpu.torch.cross_barrier import CrossBarrier

    model = _mk_model(3)
    opt = bps_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1, maximize=True),
        named_parameters=model.named_parameters())
    with pytest.raises(ValueError, match="maximize"):
        CrossBarrier(model, opt, num_steps=5)


def test_cross_barrier_sparse_embedding(bps_torch):
    """Sparse embedding grads ride the row-sparse wire through the
    barrier-crossing hook (previously crashed in .numpy() inside
    backward) and training still converges."""
    from byteps_tpu.torch.cross_barrier import CrossBarrier

    torch.manual_seed(11)
    model = torch.nn.Sequential(
        torch.nn.Embedding(40, 6, sparse=True),
        torch.nn.Flatten(), torch.nn.Linear(6 * 4, 4))
    opt = bps_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.2),
        named_parameters=model.named_parameters())
    cb = CrossBarrier(model, opt, num_steps=12)
    cb.step()  # step 0: broadcast-time eager step
    rng = np.random.RandomState(0)
    x = torch.from_numpy(rng.randint(0, 40, (32, 4)))
    y = torch.from_numpy(rng.randint(0, 4, 32).astype(np.int64))
    losses = []
    for _ in range(10):
        cb.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        cb.step()
        losses.append(float(loss))
    cb.drain()
    assert cb._poller_error is None
    assert losses[-1] < losses[0], losses
