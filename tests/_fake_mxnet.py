"""Minimal MXNet stand-in for adapter tests.

MXNet has no TPU build and is not in this image, so the adapter tests
exercise byteps_tpu.mxnet against this shim: it implements exactly the
NDArray / optimizer / gluon.Trainer surface the adapter touches (the
same duck-typed contract real mx.nd.NDArray satisfies). Mirrors the
reference's test approach of running adapter logic on one host
(reference tests/test_mxnet.py) without requiring a GPU build.
"""

from __future__ import annotations

import sys
import types

import numpy as np


def _raw(x):
    return x.arr if isinstance(x, NDArray) else x


class NDArray:
    def __init__(self, arr, dtype=None):
        self.arr = np.array(arr, dtype=dtype)

    def asnumpy(self):
        return self.arr.copy()

    @property
    def shape(self):
        return self.arr.shape

    @property
    def dtype(self):
        return self.arr.dtype

    def astype(self, dtype, copy=True):
        return NDArray(self.arr.astype(dtype))

    def copy(self):
        return NDArray(self.arr.copy())

    def reshape(self, *shape):
        return NDArray(self.arr.reshape(*shape))

    def wait_to_read(self):
        pass

    def __len__(self):
        return len(self.arr)

    def __getitem__(self, k):
        return NDArray(self.arr[k])

    def __setitem__(self, k, v):
        self.arr[k] = _raw(v)

    def __imul__(self, o):
        self.arr *= _raw(o)
        return self

    def __iadd__(self, o):
        self.arr += _raw(o)
        return self

    def __isub__(self, o):
        self.arr -= _raw(o)
        return self

    def __mul__(self, o):
        return NDArray(self.arr * _raw(o))

    def __rmul__(self, o):
        return NDArray(_raw(o) * self.arr)

    def __add__(self, o):
        return NDArray(self.arr + _raw(o))

    def __sub__(self, o):
        return NDArray(self.arr - _raw(o))


def array(data, dtype=None):
    return NDArray(data, dtype=dtype)


def zeros(shape, dtype="float32"):
    return NDArray(np.zeros(shape, dtype))


def zeros_like(t):
    return NDArray(np.zeros_like(_raw(t)))


class Optimizer:
    def __init__(self, learning_rate=0.01, **kwargs):
        self.learning_rate = learning_rate

    def set_learning_rate(self, lr):
        self.learning_rate = lr

    def set_lr_mult(self, m):
        self.lr_mult = m

    def set_wd_mult(self, m):
        self.wd_mult = m

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.0, wd=0.0, **kw):
        super().__init__(learning_rate)
        self.momentum = momentum
        self.wd = wd

    def update(self, index, weight, grad, state):
        g = _raw(grad).astype(_raw(weight).dtype)
        if self.wd:
            g = g + self.wd * _raw(weight)
        _raw(weight)[...] -= self.learning_rate * g


_OPTIMIZERS = {"sgd": SGD}


def create(name, **kwargs):
    return _OPTIMIZERS[name](**kwargs)


class Parameter:
    def __init__(self, name, data, grad_req="write"):
        self.name = name
        self.grad_req = grad_req
        self._data = [NDArray(np.asarray(data, np.float32))]
        self._grad = [NDArray(np.zeros_like(np.asarray(data, np.float32)))]
        self._deferred_init = False

    def list_data(self):
        return self._data

    def list_grad(self):
        return self._grad


class ParameterDict(dict):
    pass


class Trainer:
    """Just enough of mx.gluon.Trainer: param bookkeeping, optimizer
    creation, and a step() that runs init -> allreduce -> update. No
    gradient rescaling here (the distributed subclass owns it)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        self._params = list(params)
        self._param2idx = {p.name: i for i, p in enumerate(self._params)}
        self._params_to_init = list(self._params)
        if isinstance(optimizer, str):
            optimizer = create(optimizer, **(optimizer_params or {}))
        elif optimizer_params:
            for k, v in optimizer_params.items():
                setattr(optimizer, k, v)
        self._optimizer = optimizer
        self._scale = 1.0

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def _init_params(self):
        self._params_to_init = []

    def _allreduce_grads(self):
        pass

    def _update(self):
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                self._optimizer.update(i, p._data[0], p._grad[0], None)

    def step(self, batch_size, ignore_stale_grad=False):
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()
        self._update()


def install():
    """Install the shim as ``mxnet`` in sys.modules (idempotent)."""
    if "mxnet" in sys.modules and not getattr(
            sys.modules["mxnet"], "_byteps_tpu_fake", False):
        return sys.modules["mxnet"]
    mx = types.ModuleType("mxnet")
    mx._byteps_tpu_fake = True
    nd = types.ModuleType("mxnet.ndarray")
    nd.NDArray = NDArray
    nd.array = array
    nd.zeros = zeros
    nd.zeros_like = zeros_like
    opt_mod = types.ModuleType("mxnet.optimizer")
    opt_mod.Optimizer = Optimizer
    opt_mod.SGD = SGD
    opt_mod.create = create
    gluon = types.ModuleType("mxnet.gluon")
    gluon.Trainer = Trainer
    gluon.Parameter = Parameter
    gluon.ParameterDict = ParameterDict
    param_mod = types.ModuleType("mxnet.gluon.parameter")
    param_mod.Parameter = Parameter
    param_mod.ParameterDict = ParameterDict
    gluon.parameter = param_mod
    mx.nd = nd
    mx.ndarray = nd
    mx.optimizer = opt_mod
    mx.gluon = gluon
    sys.modules["mxnet"] = mx
    sys.modules["mxnet.ndarray"] = nd
    sys.modules["mxnet.optimizer"] = opt_mod
    sys.modules["mxnet.gluon"] = gluon
    sys.modules["mxnet.gluon.parameter"] = param_mod
    return mx
