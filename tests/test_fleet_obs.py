"""Fleet-wide observability plane (PR 12): NTP-style clock-offset
estimation under synthetic skew, the crash flight recorder's ring
semantics, the fused worker+server Chrome trace (clock-aligned,
rid-linked), `bps.get_fleet_metrics()` / the labeled Prometheus fleet
series, classify_step's server attribution, and the slot-layout
runtime manifest — with a SUBPROCESS-server integration tier proving
the whole plane works when the server is genuinely out-of-process
(the black-box case the plane exists for)."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.flight import FlightRecorder
from byteps_tpu.core.metrics import (
    MetricsRegistry, StepReport, classify_step, prometheus_text,
    server_attribution,
)
from byteps_tpu.utils.tracing import Tracer, estimate_clock_offset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- #
# clock-offset estimation under synthetic skew (satellite: error bound)
# --------------------------------------------------------------------- #


def _echo(true_offset_ns, send_delay_ns, recv_delay_ns, t0):
    """Synthesize one probe: the server's clock reads client_clock +
    true_offset; the request takes send_delay on the way out and
    recv_delay on the way back."""
    t1 = t0 + send_delay_ns + true_offset_ns
    t2 = t1 + 1000  # 1us of server handling
    t3 = (t2 - true_offset_ns) + recv_delay_ns
    return (t0, t1, t2, t3)


def test_offset_symmetric_delay_is_exact():
    # symmetric path delay: the classic estimate is exact
    off, err = estimate_clock_offset(
        [_echo(5_000_000, 20_000, 20_000, t0=10**9)])
    assert off == 5_000_000
    assert err <= 20_001 + 1  # rtt/2 + handling share


def test_offset_asymmetric_delay_stays_inside_bound():
    # fully asymmetric: all 40us of rtt on one leg. The estimate is
    # biased by (send-recv)/2 but must stay inside the reported bound.
    true = -3_000_000
    off, err = estimate_clock_offset([_echo(true, 40_000, 0, t0=10**9)])
    assert off != true  # asymmetry biases the single estimate...
    assert abs(off - true) <= err, (off, err)  # ...within the bound


def test_offset_jittered_rtt_min_probe_wins():
    # jittered rtt: the min-rtt probe decides; the winning probe's
    # bound covers the truth even though jittery probes are way off
    true = 7_777_000
    rng = np.random.RandomState(0)
    samples = []
    for i in range(20):
        jit = int(rng.randint(0, 500_000))
        d_out = 10_000 + jit + int(rng.randint(0, jit + 1))
        d_back = 10_000 + int(rng.randint(0, jit + 1))
        samples.append(_echo(true, d_out, d_back, t0=10**9 + i * 10**6))
    samples.append(_echo(true, 9_000, 9_000, t0=2 * 10**9))  # clean
    off, err = estimate_clock_offset(samples)
    assert abs(off - true) <= err
    assert err <= 9_002  # the clean probe's envelope, not the jitter's


def test_offset_rejects_empty_and_broken_probes():
    with pytest.raises(ValueError):
        estimate_clock_offset([])
    with pytest.raises(ValueError):
        # negative rtt on every probe (clock stepped mid-echo)
        estimate_clock_offset([(100, 0, 10**9, 50)])


# --------------------------------------------------------------------- #
# flight recorder ring semantics
# --------------------------------------------------------------------- #


def test_flight_ring_bounded_drop_oldest():
    rec = FlightRecorder(capacity=16, enabled=True)
    for i in range(40):
        rec.record("k", key=i)
    evs = rec.events()
    assert len(evs) == 16
    assert [e["key"] for e in evs] == list(range(24, 40))  # oldest gone
    snap = rec.snapshot()
    assert snap["events"] == 40 and snap["dropped"] == 24
    assert snap["capacity"] == 16
    ts = [e["ts_ns"] for e in evs]
    assert ts == sorted(ts)


def test_flight_disabled_is_a_noop():
    rec = FlightRecorder(capacity=16, enabled=False)
    rec.record("k")
    assert rec.events() == []
    assert rec.snapshot()["events"] == 0


def test_flight_dump_merges_and_aligns(tmp_path):
    from byteps_tpu.core import flight as flight_mod
    rec = flight_mod.configure(capacity=64, enabled=True,
                               dump_dir=str(tmp_path))
    rec.record("wire_retry", key=3, detail="attempt=1")
    # a server whose clock runs 1ms AHEAD: its event at local+1ms
    # happened 0.5ms after the worker's, and alignment must order it so
    worker_ts = rec.events()[0]["ts_ns"]
    flight_mod.set_server_collector(lambda: [{
        "server": 0, "offset_ns": 1_000_000,
        "events": [{"ts_ns": worker_ts + 1_000_000 + 500_000,
                    "kind": "chaos_drop", "key": 3, "rid": 9,
                    "sender": 0, "detail": 0}],
    }])
    try:
        path = flight_mod.dump(str(tmp_path / "f.json"), reason="test")
        with open(path) as f:
            doc = json.load(f)
        assert doc["reason"] == "test"
        assert [e["source"] for e in doc["merged"]] == \
            ["worker", "server0"]
        delta = doc["merged"][1]["ts_ns"] - doc["merged"][0]["ts_ns"]
        assert delta == 500_000  # offset removed, causal gap preserved
    finally:
        flight_mod.set_server_collector(None)
        flight_mod.configure(enabled=False)


# --------------------------------------------------------------------- #
# fused trace assembly (synthetic collector: no wire needed)
# --------------------------------------------------------------------- #


def _cfg(tmp_path):
    return Config(trace_on=True, trace_start_step=0, trace_end_step=100,
                  trace_dir=str(tmp_path))


def test_fused_dump_aligns_and_links(tmp_path):
    tr = Tracer(_cfg(tmp_path))
    tr.step()
    tr.begin("t0", "PUSHPULL.0")
    tr.annotate("t0", "PUSHPULL.0", rid=42, server=0)
    time.sleep(0.002)
    tr.end("t0", "PUSHPULL.0")
    # synthetic server record INSIDE the worker span, on a server clock
    # 2s ahead of ours
    offset = 2 * 10**9
    now = time.monotonic_ns()
    t0 = now - 1_500_000 + offset  # 1.5ms ago, server clock
    rec = {"key": 7, "t0": t0, "t1": t0 + 100_000, "t2": t0 + 300_000,
           "t3": t0 + 900_000, "rid": 42, "sender": 0, "op": 11,
           "kind": 0}
    rep = {"key": 0, "t0": t0 + 1_200_000, "t1": 0, "t2": 0, "t3": 0,
           "rid": 42, "sender": 0, "kind": 1, "op": 7}
    tr.set_server_collector(lambda: [
        {"server": 0, "offset_ns": offset, "err_ns": 1500,
         "records": [rec, rep]}])
    path = tr.dump(str(tmp_path / "fused.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    srv = [e for e in evs if e.get("cat") == "server"]
    names = {e["name"] for e in srv}
    assert names == {"recv", "queue-wait", "fold", "reply"}
    # clock alignment: mapped server ts sits inside the worker span
    wspan = next(e for e in evs if e.get("ph") == "X"
                 and e.get("args", {}).get("rid") == 42)
    recv = next(e for e in srv if e["name"] == "recv")
    assert wspan["ts"] <= recv["ts"] <= wspan["ts"] + wspan["dur"]
    # server rows are their own pid, named via metadata
    metas = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("ph") == "M"}
    assert metas[recv["pid"]] == "bps-server 0"
    assert recv["pid"] != wspan["pid"]
    # rid flow link: a start on the worker span, a finish server-side
    flows = [e for e in evs if e.get("cat") == "bps-rid"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert all(e["id"] == 42 for e in flows)
    assert doc["metadata"]["rid_flow_links"] == 1


def test_fused_dump_without_servers_still_writes(tmp_path):
    tr = Tracer(_cfg(tmp_path))
    tr.step()
    tr.begin("t0", "PUSH.0")
    tr.end("t0", "PUSH.0")
    path = tr.dump(str(tmp_path / "fused.json"))
    with open(path) as f:
        doc = json.load(f)
    assert doc["metadata"]["rid_flow_links"] == 0


def test_fused_dump_nothing_returns_none(tmp_path):
    tr = Tracer(Config(trace_on=False, trace_dir=str(tmp_path)))
    assert tr.dump(str(tmp_path / "x.json")) is None


# --------------------------------------------------------------------- #
# classify_step server attribution
# --------------------------------------------------------------------- #


def _pull_bound(**kw):
    return StepReport(step=1, wall_ms=90.0, compute_ms=10.0,
                      pull_p95_ms=70.0, pull_wait_ms=5.0, **kw)


def test_classify_without_probe_is_unchanged():
    msg = classify_step(_pull_bound())
    assert msg.startswith("PULL-bound:")
    assert "queue-wait" not in msg


def test_classify_splits_pull_bound_queue_wait():
    r = _pull_bound(pull_total_ms=120.0, server_recv_ms=2.0,
                    server_queue_ms=80.0, server_fold_ms=10.0,
                    server_reply_ms=3.0)
    msg = classify_step(r)
    assert msg.startswith("PULL-bound/queue-wait-bound:")
    assert "server queue-wait 80.0ms" in msg
    sub, queue, fold, wire = server_attribution(r)
    assert sub == "queue-wait-bound"
    assert queue == 80.0 and fold == 10.0
    assert wire == pytest.approx(2.0 + 3.0 + 25.0)  # recv+reply+residual


def test_classify_splits_pull_bound_wire():
    # a throttled transport: the server accounts recv/reply walls and
    # the residual rides the network — all three land on "wire"
    r = _pull_bound(pull_total_ms=200.0, server_recv_ms=60.0,
                    server_queue_ms=5.0, server_fold_ms=8.0,
                    server_reply_ms=50.0)
    msg = classify_step(r)
    assert msg.startswith("PULL-bound/wire-bound:"), msg


def test_classify_splits_pull_bound_fold():
    r = _pull_bound(pull_total_ms=100.0, server_recv_ms=1.0,
                    server_queue_ms=4.0, server_fold_ms=90.0,
                    server_reply_ms=2.0)
    assert classify_step(r).startswith("PULL-bound/fold-bound:")


def test_compute_bound_never_attributes():
    r = StepReport(step=1, wall_ms=50.0, compute_ms=45.0,
                   pull_p95_ms=2.0, pull_total_ms=10.0,
                   server_queue_ms=9.0, server_fold_ms=0.5,
                   server_recv_ms=0.1, server_reply_ms=0.1)
    assert classify_step(r).startswith("COMPUTE-bound:")


# --------------------------------------------------------------------- #
# Prometheus fleet labels (unit: synthetic section)
# --------------------------------------------------------------------- #


def test_prometheus_fleet_labeled_series():
    reg = MetricsRegistry()
    reg.section("fleet", lambda: {
        "workers": 1, "servers": 2, "source": "wire",
        "server": {"0": {"fold_ms": 1.5, "queue_wait_ms": 0.25},
                   "1": {"fold_ms": 4.0, "queue_wait_ms": 2.0}}})
    text = prometheus_text(reg)
    assert 'byteps_fleet_fold_ms{server="0"} 1.5' in text
    assert 'byteps_fleet_fold_ms{server="1"} 4.0' in text
    assert 'byteps_fleet_queue_wait_ms{server="1"} 2.0' in text
    # the scalar fleet keys flatten like any section; strings skipped
    assert "byteps_fleet_servers 2" in text
    assert "wire" not in text.split("byteps_fleet_servers")[0].split(
        "byteps_fleet")[-1]


# --------------------------------------------------------------------- #
# slot-layout manifest: the LOADED .so agrees with the Python mirror
# --------------------------------------------------------------------- #


def test_native_stat_slot_manifest_matches_mirror():
    from byteps_tpu.server import _STAT_SLOTS, native_stat_slot_names
    names = native_stat_slot_names()
    assert names, "stat-name ABI missing from the built .so"
    assert tuple(names) == _STAT_SLOTS


# --------------------------------------------------------------------- #
# integration: SUBPROCESS server — the out-of-process fleet the plane
# exists for (trace fusion within the rtt envelope, wire fleet metrics,
# the labeled Prometheus scrape)
# --------------------------------------------------------------------- #


def _wait_ports(ports, timeout=60):
    import socket

    deadline = time.monotonic() + timeout
    for port in ports:
        while True:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=1):
                    break
            except OSError:
                if time.monotonic() > deadline:
                    raise RuntimeError(f"server on :{port} never came up")
                time.sleep(0.2)


@pytest.mark.slow
def test_subprocess_fleet_trace_metrics_prometheus(tmp_path):
    """One subprocess-server run proving the acceptance criteria
    end-to-end: the fused trace contains clock-aligned server-stage
    spans rid-linked to worker spans and landing within the measured
    rtt envelope of their worker parents; get_fleet_metrics() returns
    the out-of-process server's registry section over the wire; and
    the Prometheus endpoint serves it with a server label."""
    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.utils.net import free_port

    port = free_port()
    metrics_port = free_port()
    env_keys = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        "BYTEPS_TRACE_ON": "1", "BYTEPS_TRACE_START_STEP": "0",
        "BYTEPS_TRACE_END_STEP": "1000000000",
        "BYTEPS_TRACE_DIR": str(tmp_path),
        "BYTEPS_METRICS_PORT": str(metrics_port),
    }
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    code = (f"from byteps_tpu.server import run_server; "
            f"from byteps_tpu.config import Config; "
            f"run_server({port}, Config(num_workers=1, num_servers=1))")
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        env={**os.environ, "BYTEPS_TRACE_SAMPLE": "1",
             "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                 "PYTHONPATH", "")})
    bps = None
    try:
        _wait_ports([port])
        GlobalState._instance = None
        import byteps_tpu as bps
        bps.init()
        from byteps_tpu.core.state import get_state
        state = get_state()

        rng = np.random.RandomState(2)
        grads = [rng.randn(8192).astype(np.float32) for _ in range(4)]
        for r in range(3):
            hs = [bps.push_pull_async(g * (r + 1), f"fo{i}",
                                      average=False)
                  for i, g in enumerate(grads)]
            for h, g in zip(hs, grads):
                np.testing.assert_array_equal(
                    np.array(bps.synchronize(h, timeout=60)),
                    g * (r + 1))

        # -- fleet metrics over the wire --------------------------------
        fm = bps.get_fleet_metrics()
        assert fm["fleet"]["source"] == "wire"
        srv0 = fm["fleet"]["server"]["0"]
        assert srv0["fold_count"] > 0
        assert srv0["trace_records"] > 0, "server never sampled a span"

        # -- Prometheus: the same fleet, labeled ------------------------
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics",
            timeout=10).read().decode()
        assert 'byteps_fleet_fold_count{server="0"}' in body, \
            body[:2000]

        # -- fused trace: aligned + rid-linked + inside the envelope ----
        probe = state.ps_client.clock_probe(0)
        assert probe is not None
        _off, err_ns = probe
        path = bps.dump_fused_trace(str(tmp_path / "fused.json"))
        with open(path) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        assert doc["metadata"]["rid_flow_links"] > 0, \
            "no rid flow links fused"
        wspans = {e["args"]["rid"]: e for e in evs
                  if e.get("ph") == "X"
                  and isinstance(e.get("args"), dict)
                  and e["args"].get("rid")}
        srv_spans = [e for e in evs if e.get("cat") == "server"
                     and e.get("ph") == "X"]
        assert srv_spans, "no server stage spans in the fused trace"
        # every rid-matched server span must land within the measured
        # rtt envelope of its worker parent: the server's work happens
        # strictly inside the worker's submit->completion window, so
        # after clock alignment only the offset error + a small
        # bookkeeping slack can leak past the edges
        margin_us = err_ns / 1e3 + 2000.0
        matched = 0
        for e in srv_spans:
            w = wspans.get(e["args"]["rid"])
            if w is None:
                continue
            matched += 1
            assert e["ts"] >= w["ts"] - margin_us, (e, w, err_ns)
            assert e["ts"] + e["dur"] <= w["ts"] + w["dur"] + margin_us, \
                (e, w, err_ns)
        assert matched > 0, "no server span matched a worker rid"
    finally:
        try:
            if bps is not None:
                bps.shutdown()
        except Exception:
            pass
        GlobalState._instance = None
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --------------------------------------------------------------------- #
# in-process: per-step server attribution lands on real StepReports
# --------------------------------------------------------------------- #


def test_step_report_carries_server_attribution():
    """A real loopback PS round under the profiler: the StepReport's
    server-attribution fields are populated from the in-process fleet
    probe (deltas over the step), and classify_step accepts them."""
    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.server import run_server as _run
    from byteps_tpu.utils.net import free_port

    port = free_port()
    env_keys = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    }
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    server = threading.Thread(
        target=_run, args=(port, Config(num_workers=1, num_servers=1)),
        daemon=True)
    server.start()
    GlobalState._instance = None
    bps = None
    try:
        import byteps_tpu as bps
        bps.init()
        from byteps_tpu.core.state import get_state
        state = get_state()
        g = np.random.RandomState(0).randn(65536).astype(np.float32)
        for r in range(2):
            b = state.profiler.begin_step()
            out = bps.synchronize(
                bps.push_pull_async(g, "attr0", average=False),
                timeout=60)
            np.testing.assert_array_equal(out, g)
            rep = state.profiler.end_step(b)
        assert rep is not None
        # the in-process probe ran: fields are numbers, not None
        assert rep.server_fold_ms is not None
        assert rep.server_queue_ms is not None
        assert rep.pull_total_ms is not None
        assert rep.server_fold_ms >= 0.0
        classify_step(rep)  # must not raise with the new fields
    finally:
        try:
            if bps is not None:
                bps.shutdown()
        except Exception:
            pass
        GlobalState._instance = None
        server.join(timeout=15)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
