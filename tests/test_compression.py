"""Compression codec tests against NumPy golden models.

Mirrors the reference's strategy (tests/test_onebit.py, test_topk.py,
test_randomk.py, test_dithering.py): each codec is checked bit-/value-exact
against an independent numpy implementation sharing the same xorshift128+
stream (tests/utils.py:31-51 in the reference), plus end-to-end training
with EF and the compressed allreduce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.core.state import get_state
from byteps_tpu.jax import distributed_optimizer, init_opt_state
from byteps_tpu.jax.train import make_train_step
from byteps_tpu.models import mlp
from byteps_tpu.ops.compression import (
    CompressorStack, DitheringCodec, OnebitCodec, RandomkCodec, TopkCodec,
    make_compressor, NO_COMPRESS, default_stacks,
)
from byteps_tpu.ops.compression import rng as bps_rng


# ------------------------------------------------------------------ #
# RNG parity
# ------------------------------------------------------------------ #

def test_xorshift_bit_exact():
    for seed in (0, 1, 42, 2**31):
        golden = bps_rng.np_xorshift128p(seed, 64)
        hi, lo = jax.jit(lambda s=seed: bps_rng.jnp_xorshift128p(s, 64))()
        rec = (np.asarray(hi).astype(np.uint64) << np.uint64(32)) \
            | np.asarray(lo).astype(np.uint64)
        np.testing.assert_array_equal(golden, rec)


def test_xorshift_mix_traced():
    """mix (the step counter) can be a traced scalar and matches golden."""
    golden = bps_rng.np_uniform(7, 32, mix=5)
    got = jax.jit(lambda m: bps_rng.jnp_uniform(7, 32, mix=m))(jnp.int32(5))
    np.testing.assert_allclose(golden, np.asarray(got))


# ------------------------------------------------------------------ #
# codec golden models
# ------------------------------------------------------------------ #

def golden_onebit(x: np.ndarray, scaled: bool):
    scale = np.abs(x).mean() if scaled else 1.0
    return np.where(x >= 0, scale, -scale).astype(np.float32)


@pytest.mark.parametrize("n", [7, 32, 100, 1000])
@pytest.mark.parametrize("scaled", [True, False])
def test_onebit_roundtrip(n, scaled):
    rng = np.random.RandomState(n)
    x = rng.randn(n).astype(np.float32)
    codec = OnebitCodec(size=n, scaled=scaled)
    payload = jax.jit(codec.compress)(x)
    out = np.asarray(jax.jit(codec.decompress)(payload))
    np.testing.assert_allclose(out, golden_onebit(x, scaled), rtol=1e-6)
    # wire size: 1 bit/elem packed
    assert payload["bits"].size == (n + 31) // 32


def test_onebit_layout_latched(monkeypatch):
    """pallas-vs-portable is resolved ONCE and reused: a later call under
    a different device context must not re-derive the layout, or the
    pull buffer gets sized for the wrong payload (round-5 advisor
    finding — the server's oversized-reply check makes that a hard
    error)."""
    from byteps_tpu.ops.compression import codecs

    codec = OnebitCodec(size=100)
    before = codec.wire_bytes()  # latches the portable layout (CPU here)
    monkeypatch.setattr(codecs, "_on_tpu", lambda: True)
    assert codec.wire_bytes() == before
    # a FRESH codec constructed under the faked context latches pallas
    fresh = OnebitCodec(size=100)
    assert fresh.wire_bytes() != before


@pytest.mark.parametrize("k", [1, 5, 50])
def test_topk_matches_golden(k):
    rng = np.random.RandomState(k)
    x = rng.randn(128).astype(np.float32)
    codec = TopkCodec(size=128, k=k)
    payload = jax.jit(codec.compress)(x)
    out = np.asarray(jax.jit(codec.decompress)(payload))
    # golden: zero all but top-k |x|
    golden = np.zeros_like(x)
    top = np.argsort(-np.abs(x))[:k]
    golden[top] = x[top]
    # POSITION- and SIGN-exact: a decompress that scattered the right
    # values to wrong coordinates (or negated them) must fail, not just
    # preserve the |value| multiset
    np.testing.assert_allclose(out, golden, rtol=1e-6)
    # wire payload faithfulness: indices point at x's own values
    idx = np.asarray(payload["indices"])
    np.testing.assert_allclose(np.asarray(payload["values"]), x[idx],
                               rtol=1e-6)


def test_topk_approx_mode():
    """approx=True (TPU ApproxTopK hardware path) keeps the wire contract:
    k (index, value) pairs, values faithful to x at those indices, and
    high recall of the true top-k on well-separated magnitudes."""
    rng = np.random.RandomState(0)
    signs = np.where(rng.rand(512) < 0.5, -1.0, 1.0)
    # exactly log-spaced |x| (no randn factor that could collapse the
    # separation): recall must be near-perfect, but NOT exact-set — the
    # hardware op guarantees ~95% recall, not 100% (bucketed reduction)
    x = (signs * np.logspace(-3, 3, 512)).astype(np.float32)
    codec = TopkCodec(size=512, k=16, approx=True)
    payload = jax.jit(codec.compress)(x)
    idx = np.asarray(payload["indices"])
    vals = np.asarray(payload["values"])
    assert idx.shape == (16,) and vals.shape == (16,)
    np.testing.assert_allclose(vals, x[idx], rtol=1e-6)
    true_top = set(np.argsort(-np.abs(x))[:16].tolist())
    recall = len(true_top & set(idx.tolist())) / 16
    assert recall >= 0.8, (recall, sorted(idx.tolist()))
    out = np.asarray(jax.jit(codec.decompress)(payload))
    assert int((out != 0).sum()) <= 16
    # registry plumbs the kwarg through
    from byteps_tpu.ops.compression import make_compressor
    st = make_compressor({"compressor": "topk", "k": "16", "approx": "1"},
                         512)
    assert st.codec.approx is True


def test_randomk_matches_golden():
    n, k, seed, step = 256, 16, 3, 4
    rng = np.random.RandomState(0)
    x = rng.randn(n).astype(np.float32)
    codec = RandomkCodec(size=n, k=k, seed=seed)
    payload = jax.jit(lambda x, s: codec.compress(x, s))(x, jnp.int32(step))
    # golden indices from the shared counter-based stream (32-bit hash
    # mod n: the float-uniform form capped distinct indices at 2^24)
    golden_idx = bps_rng.np_index_parallel(seed, k, n, mix=step)
    np.testing.assert_array_equal(np.asarray(payload["indices"]), golden_idx)
    np.testing.assert_allclose(np.asarray(payload["values"]), x[golden_idx])
    out = np.asarray(codec.decompress(payload))
    golden = np.zeros_like(x)
    golden[golden_idx] = x[golden_idx]  # same dup-overwrite order
    np.testing.assert_allclose(out, golden)


@pytest.mark.parametrize("partition", ["linear", "natural"])
@pytest.mark.parametrize("normalize", ["max", "l2"])
def test_dithering_golden(partition, normalize):
    n, s, seed, step = 512, 16, 11, 2
    rng = np.random.RandomState(1)
    x = rng.randn(n).astype(np.float32)
    codec = DitheringCodec(size=n, s=s, partition=partition,
                           normalize=normalize, seed=seed)
    payload = jax.jit(lambda x, st: codec.compress(x, st))(x, jnp.int32(step))
    out = np.asarray(jax.jit(codec.decompress)(payload))

    # golden model
    absx = np.abs(x)
    norm = absx.max() if normalize == "max" else np.linalg.norm(x)
    scaled = absx / norm
    u = bps_rng.np_uniform_parallel(seed, n, mix=step)
    if partition == "linear":
        pos = scaled * s
        fl = np.floor(pos)
        level = fl + (u < pos - fl)
        golden = np.sign(x) * level / s * norm
    else:
        safe = np.maximum(scaled, 1e-30)
        j = np.clip(np.floor(-np.log2(safe)), 0, 30)
        low, high = 2.0 ** (-j - 1), 2.0 ** (-j)
        frac = (scaled - low) / (high - low)
        exp = np.where(u < frac, j, j + 1)
        level = np.where(scaled < 2.0 ** -31, 0.0, exp + 1.0)
        mag = np.where(level == 0, 0.0, 2.0 ** (-(level - 1.0)))
        golden = np.sign(x) * mag * norm
    np.testing.assert_allclose(out, golden.astype(np.float32),
                               rtol=1e-5, atol=1e-6)
    # quantization error bounded (unbiased rounding, 1 level max off)
    if partition == "linear" and normalize == "max":
        assert np.max(np.abs(out - x)) <= norm / s + 1e-6
    if partition == "natural":
        # independent property check (the golden above mirrors the
        # implementation's derivation, so it alone cannot catch a shared
        # mis-derivation): each reconstructed magnitude is a power of
        # two bracketing the input within one octave, or zero only for
        # tiny inputs
        nz = np.abs(out) > 0
        mag_in = np.abs(x[nz]) / norm
        mag_out = np.abs(out[nz]) / norm
        np.testing.assert_array_equal(np.sign(out[nz]), np.sign(x[nz]))
        # power-of-two levels: log2 is integral
        log2m = np.log2(mag_out)
        np.testing.assert_allclose(log2m, np.round(log2m), atol=1e-5)
        # within one octave of the input (rounding moves at most one
        # power step)
        assert np.all(mag_out <= 2.0 * mag_in + 1e-12)
        assert np.all(mag_out >= mag_in / 2.0 - 1e-12)


# ------------------------------------------------------------------ #
# EF + momentum
# ------------------------------------------------------------------ #

def test_error_feedback_accumulates():
    n = 64
    codec = TopkCodec(size=n, k=8)
    stack = CompressorStack(codec=codec, use_ef=True)
    state = stack.init_state(n)
    rng = np.random.RandomState(0)
    g = rng.randn(n).astype(np.float32)

    payload, state = jax.jit(stack.compress)(g, state)
    dec = np.asarray(codec.decompress(payload))
    # error = what was lost
    np.testing.assert_allclose(np.asarray(state["error"]), g - dec,
                               rtol=1e-5, atol=1e-6)
    # next round: corrected gradient includes the residual
    payload2, state2 = jax.jit(stack.compress)(g, state)
    corrected = g + np.asarray(state["error"])
    dec2 = np.asarray(codec.decompress(payload2))
    np.testing.assert_allclose(np.asarray(state2["error"]), corrected - dec2,
                               rtol=1e-5, atol=1e-6)


def test_momentum_stage():
    n, mu = 16, 0.9
    codec = OnebitCodec(size=n, scaled=True)
    stack = CompressorStack(codec=codec, momentum_mu=mu)
    state = stack.init_state(n)
    g = np.ones(n, np.float32)
    _, state = stack.compress(g, state)
    np.testing.assert_allclose(np.asarray(state["momentum"]), g)  # mu*0 + g
    _, state2 = stack.compress(g, state)
    np.testing.assert_allclose(np.asarray(state2["momentum"]), mu * g + g)


# ------------------------------------------------------------------ #
# registry + end-to-end compressed training
# ------------------------------------------------------------------ #

def test_registry_parses_kwargs():
    st = make_compressor({"compressor": "onebit", "ef": "vanilla",
                          "momentum": "nesterov", "momentum_mu": "0.8"}, 100)
    assert isinstance(st.codec, OnebitCodec) and st.use_ef
    assert st.momentum_mu == pytest.approx(0.8)
    st = make_compressor({"compressor": "topk", "k": "0.1"}, 200)
    assert st.codec.k == 20
    with pytest.raises(ValueError):
        make_compressor({"compressor": "nope"}, 10)


def test_min_compress_bytes_threshold():
    params = {"big": np.zeros((1000,)), "small": np.zeros((10,))}
    stacks = default_stacks(params, {"compressor": "onebit"},
                            min_compress_bytes=1024)
    assert isinstance(stacks["big"], CompressorStack)
    assert stacks["small"] is NO_COMPRESS


def test_compressed_training_converges(bps):
    """End-to-end: MLP trains with onebit+EF through the compressed
    allreduce (the reference's test_onebit.py analog)."""
    mesh = get_state().mesh
    cfg = mlp.MLPConfig(in_dim=64, hidden=(32,), n_classes=4)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    tx = distributed_optimizer(
        optax.sgd(0.05),
        compression={"compressor": "onebit", "ef": "vanilla",
                     "scaling": "true"},
        params_example=params,
        min_compress_bytes=0,   # compress everything (meta_test.py:27-58)
    )
    # per-replica EF state must be initialized/declared sharded over dp
    opt_state, opt_specs = init_opt_state(tx, params, mesh)
    step = make_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx, mesh,
                           opt_specs=opt_specs)

    rng = np.random.RandomState(0)
    x = rng.randn(256, 64).astype(np.float32)
    w = rng.randn(64, 4).astype(np.float32)
    y = np.argmax(x @ w, -1).astype(np.int32)
    batch = {"x": x, "y": y}

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_uniform_fast_matches_golden():
    """host._uniform_fast (in-place hot-path generator) must stay
    bit-identical to rng.np_uniform_parallel (the golden model)."""
    import numpy as np
    from byteps_tpu.ops.compression.host import _uniform_fast
    from byteps_tpu.ops.compression.rng import np_uniform_parallel
    for seed, n, mix in ((0, 100, 0), (11, 4096, 7), (123, 1 << 16, 42)):
        np.testing.assert_array_equal(
            _uniform_fast(seed, n, mix).view(np.uint32),
            np_uniform_parallel(seed, n, mix).view(np.uint32))


def test_ef_lr_rescale():
    """EF residual rescaling under an LR change: the residual is 'gradient
    still owed', so when the LR halves, the carried residual must double
    in gradient units to conserve the owed parameter delta (reference:
    VanillaErrorFeedbackCompressor's pre_lr/cur_lr mmap scaling,
    impl/vanilla_error_feedback.cc:44-67)."""
    from byteps_tpu.ops.compression import make_compressor

    st_stack = make_compressor({"compressor": "topk", "k": "2",
                                "ef": "vanilla"}, 8)
    g = jnp.asarray(np.array([4, 3, 0.5, 0.25, 0.2, 0.1, 0.05, 0.01],
                             np.float32))
    state = st_stack.init_state(8)

    # step 0 at lr=0.1: top-2 ships {4,3}; residual carries the rest
    p0, state = st_stack.compress(g, state, step=0, lr=0.1)
    resid0 = np.asarray(state["error"])
    assert float(state["prev_lr"]) == np.float32(0.1)

    # step 1 with the SAME lr: corrected = g + resid0 (scale 1)
    p1, st_same = st_stack.compress(g, state, step=1, lr=0.1)
    # step 1 with lr halved: corrected = g + 2*resid0
    p2, st_halved = st_stack.compress(g, state, step=1, lr=0.05)
    dec = st_stack.codec.decompress
    np.testing.assert_allclose(
        np.asarray(dec(p1)) + np.asarray(st_same["error"]),
        np.asarray(g) + resid0, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(dec(p2)) + np.asarray(st_halved["error"]),
        np.asarray(g) + 2 * resid0, rtol=1e-6)
    assert float(st_halved["prev_lr"]) == np.float32(0.05)

    # no lr passed: scale 1 (constant-LR contract) — the corrected
    # gradient must be exactly g + resid0, not a stale-prev_lr rescale
    p3, st_nolr = st_stack.compress(g, state, step=1)
    np.testing.assert_allclose(
        np.asarray(dec(p3)) + np.asarray(st_nolr["error"]),
        np.asarray(g) + resid0, rtol=1e-6)
    assert set(st_nolr) == set(state)


def test_ef_lr_rescale_zero_lr_boundary():
    """A schedule touching lr=0 (warm restarts) must not destroy the
    residual: scale stays 1 and prev_lr keeps the last nonzero LR."""
    from byteps_tpu.ops.compression import make_compressor

    st_stack = make_compressor({"compressor": "topk", "k": "2",
                                "ef": "vanilla"}, 8)
    g = jnp.asarray(np.array([4, 3, 0.5, 0.25, 0.2, 0.1, 0.05, 0.01],
                             np.float32))
    state = st_stack.init_state(8)
    _, state = st_stack.compress(g, state, step=0, lr=0.1)
    resid = np.asarray(state["error"])
    # lr -> 0: residual reused unscaled, prev_lr retains 0.1
    p, state = st_stack.compress(g, state, step=1, lr=0.0)
    np.testing.assert_allclose(
        np.asarray(st_stack.codec.decompress(p))
        + np.asarray(state["error"]),
        np.asarray(g) + resid, rtol=1e-6)
    assert float(state["prev_lr"]) == np.float32(0.1)
    # back to a nonzero LR: rescales from the last REAL lr (0.1 -> 0.05)
    resid1 = np.asarray(state["error"])
    p, state = st_stack.compress(g, state, step=2, lr=0.05)
    np.testing.assert_allclose(
        np.asarray(st_stack.codec.decompress(p))
        + np.asarray(state["error"]),
        np.asarray(g) + 2 * resid1, rtol=1e-6)


def test_native_codec_parity():
    """The C ABI native codec tier (ops/compression/native.py) must be
    bit-compatible with the numpy golden: signs/indices/values/levels
    identical, reduction scalars within an ulp. Dithering routes native
    only in its bit-stable default config (linear+max; dense AND varint
    wires byte-identical)."""
    import numpy as np
    from byteps_tpu.ops.compression import host
    from byteps_tpu.ops.compression.native import NativeCodec, maybe_native

    if maybe_native({"compressor": "onebit"},
                    host.HostOnebit(n=8).kwargs_wire(), 8) is None:
        import pytest
        pytest.skip("native codec library unavailable")

    rng = np.random.RandomState(3)
    n = 4096
    x = rng.randn(n).astype(np.float32)

    hb = host.HostOnebit(n=n)
    nb = NativeCodec(hb.kwargs_wire(), n)
    w_np = np.frombuffer(hb.compress(x), np.uint8)
    w_na = np.asarray(nb.compress(x))
    np.testing.assert_array_equal(w_np[:-4], w_na[:-4])  # sign bits
    np.testing.assert_allclose(w_np[-4:].view(np.float32)[0],
                               w_na[-4:].view(np.float32)[0], rtol=1e-6)

    # golden = the numpy classes DIRECTLY (env kill switches can't help
    # here: the loaded native library is process-cached, so a
    # make_host_codec golden could silently also be native)
    k = n // 20
    for golden, kwargs in (
            (host.HostTopk(n=n, k=k), {"compressor": "topk", "k": str(k)}),
            (host.HostRandomk(n=n, k=k, seed=3),
             {"compressor": "randomk", "k": str(k), "seed": "3"})):
        nk = NativeCodec(golden.kwargs_wire(), n)
        for step in (0, 7):
            np.testing.assert_array_equal(
                np.frombuffer(golden.compress(x, step), np.uint8),
                np.asarray(nk.compress(x, step)))

    for coding in ("dense", "varint"):
        hd = host.HostDithering(n=n, s=31, seed=5, index_coding=coding)
        nd = NativeCodec(hd.kwargs_wire(), n)
        for step in (0, 9):
            np.testing.assert_array_equal(
                np.frombuffer(hd.compress(x.copy(), step), np.uint8),
                np.asarray(nd.compress(x.copy(), step)))
    # non-default dithering configs stay numpy (ulp-sensitive rounding)
    assert maybe_native({"compressor": "dithering",
                         "normalize_type": "l2"}, "", 16) is None
    assert maybe_native({"compressor": "dithering",
                         "partition_type": "natural"}, "", 16) is None


def test_dithering_levels_from_k_alias():
    """The reference passes dithering's level count as compressor_k
    (dithering.cc:31), so adapter attribute bags (e.g. the mxnet
    compression_params path, mxnet/ops.py _codec_kwargs) arrive with
    "k" — both codec tiers must honor it rather than silently running
    at the default 127 levels."""
    from byteps_tpu.ops.compression import make_compressor
    from byteps_tpu.ops.compression.host import make_host_codec

    # device tier: the parsed level count is inspectable
    assert make_compressor({"compressor": "dithering", "k": "4"},
                           64).codec.s == 4

    # host tier (may be numpy or the native C ABI mirror): behavioral —
    # "k" must produce the same wire as an explicit "s", and differ
    # from the 127-level default
    x = np.random.RandomState(0).randn(64).astype(np.float32)
    via_k = make_host_codec({"compressor": "dithering", "k": "4"},
                            64).compress(x.copy())
    via_s = make_host_codec({"compressor": "dithering", "s": "4"},
                            64).compress(x.copy())
    default = make_host_codec({"compressor": "dithering"},
                              64).compress(x.copy())
    assert bytes(via_k) == bytes(via_s)
    assert bytes(via_k) != bytes(default)


def test_randomk_indices_cover_beyond_24_bits():
    """The float-uniform index derivation had 24-bit granularity: for
    size = 2^25 every index was even (multiples of size/2^24), leaving
    half the coordinates permanently unselected — and far worse at
    Llama-embedding sizes. The 32-bit-hash-mod-n form reaches every
    coordinate (round-4 review regression)."""
    idx = bps_rng.np_index_parallel(0, 4096, 2 ** 25, mix=1)
    assert (idx % 2 == 1).any(), "odd indices unreachable: 24-bit cap"
    assert idx.min() >= 0 and idx.max() < 2 ** 25
    # jnp twin stays bit-exact
    import jax
    jidx = np.asarray(bps_rng.jnp_index_parallel(0, 4096, 2 ** 25, mix=1))
    np.testing.assert_array_equal(idx, jidx)


def test_rng_known_answer_vectors():
    """Pin the RNG streams to FIXED values: every stochastic-codec golden
    in this file compares implementation against implementation (np vs
    jnp vs C++ all written from one spec), so a constant mis-transcribed
    identically everywhere would pass silently. These vectors are the
    protocol — the C++ server derives the same streams — and any change
    to them is a wire-compatibility break, not a refactor."""
    np.testing.assert_array_equal(
        bps_rng.np_xorshift128p(3, 4),
        np.array([10333293571365141443, 9690660739800497082,
                  1691254868487681236, 7146614285803205816], np.uint64))
    np.testing.assert_allclose(
        bps_rng.np_uniform_parallel(7, 4, mix=2),
        np.array([0.96777027845, 0.05058240890,
                  0.56154388189, 0.41550177335], np.float32), rtol=1e-7)
    np.testing.assert_array_equal(
        bps_rng.np_index_parallel(5, 4, 1000, mix=1),
        np.array([520, 522, 405, 924], np.int32))
    np.testing.assert_allclose(
        bps_rng.np_uniform(7, 4, mix=2),
        np.array([0.60142952203, 0.56164777278,
                  0.02488988637, 0.14523035287], np.float32), rtol=1e-7)
