"""Ulysses all-to-all sequence parallelism: exact match vs dense causal
attention (it is an exact algorithm), GQA via group expansion, and
composition with the Llama forward under sequence sharding — the same
contract ring attention satisfies (test_ring_attention.py)."""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from byteps_tpu.core.state import get_state
from byteps_tpu.models import llama
from byteps_tpu.parallel.ulysses import make_ulysses_attn, ulysses_attention

from test_ring_attention import dense_causal


@pytest.mark.parametrize("hkv", [8, 2])   # MHA and GQA
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(bps, hkv, causal):
    mesh = get_state().mesh      # 8 devices on "dp"; reuse as the sp axis
    B, S, H, D = 2, 64, 8, 16
    rng = np.random.RandomState(0)
    q = rng.randn(B, S, H, D).astype(np.float32)
    k = rng.randn(B, S, hkv, D).astype(np.float32)
    v = rng.randn(B, S, hkv, D).astype(np.float32)

    if causal:
        ref = dense_causal(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    else:
        kk = jnp.repeat(jnp.asarray(k), H // hkv, axis=2)
        vv = jnp.repeat(jnp.asarray(v), H // hkv, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", jnp.asarray(q), kk) / np.sqrt(D)
        p = jax.nn.softmax(scores, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, vv)

    uly = jax.jit(jax.shard_map(
        functools.partial(ulysses_attention, axis="dp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "dp"), P(None, "dp"), P(None, "dp")),
        out_specs=P(None, "dp"), check_vma=False))
    out = uly(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads(bps):
    mesh = get_state().mesh
    B, S, H, D = 1, 16, 4, 8   # 4 heads over 8 devices
    x = jnp.zeros((B, S, H, D), jnp.float32)
    f = jax.shard_map(
        functools.partial(ulysses_attention, axis="dp"),
        mesh=mesh, in_specs=(P(None, "dp"),) * 3,
        out_specs=P(None, "dp"), check_vma=False)
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(f)(x, x, x)


def test_llama_forward_ulysses_matches_dense(bps):
    """Llama forward with Ulysses sequence sharding == unsharded."""
    mesh = get_state().mesh
    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=64, seq=64),
        dtype=jnp.float32, n_heads=8, n_kv_heads=2, dim=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 64)), jnp.int32)

    ref = llama.forward(params, tokens, cfg)

    fwd_sp = jax.jit(jax.shard_map(
        lambda p, t: llama.forward(p, t, cfg,
                                   attn_impl=make_ulysses_attn(axis="dp"),
                                   sp_axis="dp"),
        mesh=mesh, in_specs=(P(), P(None, "dp")), out_specs=P(None, "dp"),
        check_vma=False))
    out = fwd_sp(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_llama_ulysses_trains(bps):
    """End-to-end: tiny llama trains with Ulysses sequence sharding."""
    mesh = get_state().mesh
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=32, seq=64),
                              dtype=jnp.float32, n_heads=8, n_kv_heads=2,
                              dim=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    def local_loss(p, b):
        return llama.loss_fn(p, b, cfg,
                             attn_impl=make_ulysses_attn(axis="dp"),
                             sp_axis="dp")

    def step(p, o, b):
        loss, g = jax.value_and_grad(local_loss)(p, b)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    stepj = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(None, "dp")),
        out_specs=(P(), P(), P()), check_vma=False))

    seq = (np.arange(65)[None, :] + np.arange(4)[:, None]) % 13
    batch = {"inputs": jnp.asarray(seq[:, :-1], jnp.int32),
             "targets": jnp.asarray(seq[:, 1:], jnp.int32)}
    losses = []
    for _ in range(25):
        params, opt, loss = stepj(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_ulysses_flash_local_attention(bps):
    """Ulysses with the flash/blockwise local attention (the long-
    context composition): exact match vs the dense local path."""
    import functools

    mesh = get_state().mesh
    B, S, H, D = 2, 64, 8, 16
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    from byteps_tpu.ops.flash_attention import make_flash_attn

    def run(local):
        f = jax.shard_map(
            functools.partial(ulysses_attention, axis="dp", causal=True,
                              local_attn=local),
            mesh=mesh, in_specs=(P(None, "dp"),) * 3,
            out_specs=P(None, "dp"), check_vma=False)
        return jax.jit(f)(q, k, v)

    with jax.default_matmul_precision("float32"):
        dense = run(None)
        flash = run(make_flash_attn(block_q=16, block_k=16))
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)
