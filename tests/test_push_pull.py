"""push_pull numeric correctness on the 8-device CPU mesh.

Modeled on the reference's numeric tests (tests/test_mxnet.py:60-125):
push_pull is identity at size 1, sums/averages correctly for 1-3D tensors
across dtypes, broadcast propagates the root's value.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from byteps_tpu.ops.push_pull import (
    psum_tree, reduce_scatter_tree, all_gather_tree,
)


@pytest.mark.parametrize("shape", [(8,), (4, 3), (2, 3, 4)])
@pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int32])
def test_push_pull_sums(bps, shape, dtype):
    n = 8
    rng = np.random.RandomState(0)
    if np.issubdtype(dtype, np.integer):
        x = rng.randint(-10, 10, size=(n,) + shape).astype(dtype)
        out = bps.push_pull(x, name=f"sum_{shape}_{np.dtype(dtype).name}",
                            average=False, stacked=True)
        np.testing.assert_array_equal(np.asarray(out), x.sum(axis=0))
    else:
        x = rng.randn(n, *shape).astype(dtype)
        out = bps.push_pull(x, name=f"avg_{shape}_{np.dtype(dtype).name}",
                            average=True, stacked=True)
        rtol = 1e-3 if dtype == np.float16 else 1e-5
        np.testing.assert_allclose(np.asarray(out), x.mean(axis=0), rtol=rtol,
                                   atol=rtol)


def test_push_pull_replicated_input(bps):
    x = np.ones((4, 4), np.float32)
    out = bps.push_pull(x, average=True)   # same value on all devices
    np.testing.assert_allclose(np.asarray(out), x)
    out = bps.push_pull(x, average=False)
    np.testing.assert_allclose(np.asarray(out), x * 8)


def test_broadcast_root_value(bps):
    x = np.arange(8 * 5, dtype=np.float32).reshape(8, 5)
    out = bps.broadcast(x, root_rank=3, stacked=True)
    np.testing.assert_array_equal(np.asarray(out), x[3])


def test_reduce_scatter_all_gather_roundtrip(bps):
    """RS+AG == allreduce, with each device owning a 1/N shard in between
    (the reference's hierarchical layout, core_loops.cc:216-268)."""
    mesh = bps.get_state().mesh if hasattr(bps, "get_state") else None
    from byteps_tpu.core.state import get_state
    mesh = get_state().mesh

    tree = {"a": jnp.arange(24, dtype=jnp.float32).reshape(4, 6),
            "b": jnp.ones((7,), jnp.float32)}

    def f(t):
        shards = reduce_scatter_tree(t, axis="dp", average=False)
        return all_gather_tree(shards, t, axis="dp")

    # all_gather output is numerically replicated but the vma system can't
    # infer it, hence check_vma=False.
    out = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                                check_vma=False))(tree)
    np.testing.assert_allclose(np.asarray(out["a"]),
                               np.asarray(tree["a"]) * 8, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["b"]),
                               np.asarray(tree["b"]) * 8, rtol=1e-6)


def test_telemetry_records(bps):
    from byteps_tpu.core.state import get_state

    tel = get_state().telemetry
    before = tel._window_bytes
    x = np.ones((8, 1024), np.float32)
    for _ in range(3):
        bps.push_pull(x, name="telemetry_t")
    # the 10s speed window hasn't closed, but the byte counter must
    # have advanced — a dead recording path returns the API shape
    # forever while counting nothing
    assert tel._window_bytes - before >= 3 * x[0].nbytes
    ts, mbps = bps.get_pushpull_speed()
    assert isinstance(ts, float) and isinstance(mbps, float)

    # and the documented off-switch actually gates recording
    tel.enabled = False
    try:
        mid = tel._window_bytes
        bps.push_pull(x, name="telemetry_t")
        assert tel._window_bytes == mid
    finally:
        tel.enabled = True


def test_rank_size_defaults(bps):
    assert bps.rank() == 0
    assert bps.size() == 1
    assert bps.local_rank() == 0
    assert bps.local_size() == 1


def test_int_average_truncates_toward_zero(bps):
    """Integer averaging must truncate toward zero (the reference's C++
    div_(size) semantics): floor division would skew every negative
    element by one (round-4 review regression)."""
    from byteps_tpu.core.state import get_state
    mesh = get_state().mesh

    # per-device contributions summing to (-3, 3) over n=8: trunc(-3/8)
    # is 0 where floor(-3/8) would be -1 — the distinguishing case
    x = np.zeros((8, 2), np.int32)
    x[0] = (-3, 3)  # sum over devices: (-3, 3); /8 trunc -> (0, 0)
    out = np.asarray(bps.push_pull(x, average=True, stacked=True))
    np.testing.assert_array_equal(out, np.array([0, 0], np.int32))
    assert out.dtype == np.int32

    # in-jit reduce_scatter keeps int dtype and truncating semantics
    def f(t):
        shards = reduce_scatter_tree(t, axis="dp", average=True)
        return all_gather_tree(shards, t, axis="dp")

    # replicated -3 per device: psum=-24, /8 trunc = -3 exactly; the
    # point here is int dtype preservation through scatter/gather
    tree = {"g": jnp.full((8,), -3, jnp.int32)}
    out2 = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                                 check_vma=False))(tree)
    assert out2["g"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out2["g"]),
                                  np.full((8,), -3, np.int32))


def test_zero_size_tensor_passes_through(bps):
    """Zero-element tensors skip the collectives and the PS tier (the
    registry rejects zero-size declarations) — round-4 review fix."""
    out = bps.push_pull(np.zeros((0, 4), np.float32), name="zempty")
    assert np.asarray(out).shape == (0, 4)
