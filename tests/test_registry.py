"""Unit tests for tensor declaration, key encoding, partitioning, hashing.

Covers the reference behaviors at byteps/common/global.cc:412-429 (monotonic
declared keys), operations.cc:140-180,306-311 (partitioning and key space),
global.cc:566-677 (server hashing + load accounting).
"""

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry, decode_key, KEY_SHIFT
from byteps_tpu.core.types import (
    DataType, RequestType, get_command_type, decode_command_type, align,
)


def make_registry(**kw):
    defaults = dict(num_servers=4, partition_bytes=4096)
    defaults.update(kw)
    return TensorRegistry(Config(**defaults))


def test_declared_keys_monotonic():
    reg = make_registry()
    keys = [reg.declare(f"t{i}").declared_key for i in range(10)]
    assert keys == list(range(10))
    # re-declaration returns the same context
    assert reg.declare("t3").declared_key == 3


def test_partitioning_covers_tensor():
    reg = make_registry(partition_bytes=4096)
    ctx = reg.init_tensor("grad", nbytes=10000, dtype=DataType.FLOAT32)
    assert len(ctx.partitions) == 3
    assert sum(p.length for p in ctx.partitions) == 10000
    offsets = [p.offset for p in ctx.partitions]
    assert offsets == [0, 4096, 8192]
    # key encoding: declared_key << 16 | index
    for i, p in enumerate(ctx.partitions):
        dk, idx = decode_key(p.key)
        assert dk == ctx.declared_key and idx == i


def test_partition_bytes_page_rounded():
    reg = make_registry(partition_bytes=5000)  # rounds up to 8192
    ctx = reg.init_tensor("g", nbytes=9000)
    assert ctx.partitions[0].length == 8192
    assert ctx.partitions[1].length == 9000 - 8192


def test_single_partition_small_tensor():
    reg = make_registry()
    ctx = reg.init_tensor("small", nbytes=100)
    assert len(ctx.partitions) == 1
    assert ctx.partitions[0].key == ctx.declared_key << KEY_SHIFT


def test_server_assignment_deterministic_and_balanced():
    rega = make_registry(key_hash_fn="djb2")
    regb = make_registry(key_hash_fn="djb2")
    for i in range(20):
        ca = rega.init_tensor(f"t{i}", nbytes=4096 * 4)
        cb = regb.init_tensor(f"t{i}", nbytes=4096 * 4)
        assert [p.server for p in ca.partitions] == [p.server for p in cb.partitions]
    assert all(0 <= p.server < 4 for c in rega.contexts_in_order()
               for p in c.partitions)


def test_mixed_hash_balances_load():
    reg = make_registry(key_hash_fn="mixed", num_servers=4)
    for i in range(16):
        reg.init_tensor(f"t{i}", nbytes=4096)
    loads = reg.server_loads()
    assert max(loads) - min(loads) <= 4096  # near-perfect balance


def test_redeclare_preserves_keys():
    reg = make_registry(num_servers=2)
    for i in range(5):
        reg.init_tensor(f"t{i}", nbytes=8192)
    old_keys = {c.name: c.key_list for c in reg.contexts_in_order()}
    reg.redeclare_all(Config(num_servers=3, partition_bytes=4096))
    new_keys = {c.name: c.key_list for c in reg.contexts_in_order()}
    assert old_keys == new_keys  # elastic resume: identical key assignment


def test_command_type_roundtrip():
    for req in RequestType:
        for dt in DataType:
            cmd = get_command_type(req, dt)
            assert decode_command_type(cmd) == (req, dt)


def test_align():
    assert align(0) == 0
    assert align(1) == 16
    assert align(16) == 16
    assert align(17, 8) == 24


def test_dtype_roundtrip():
    for dt in [DataType.FLOAT32, DataType.FLOAT16, DataType.INT32]:
        assert DataType.from_np(dt.np_dtype) == dt
    assert DataType.from_np(np.float32) == DataType.FLOAT32


def test_hash_naive_matches_reference_formula():
    """BYTEPS_KEY_HASH_FN=naive must reproduce the reference's
    Hash_Naive(key) = ((key>>16) + (key%65536)) * 9973 (global.cc:598-600)
    so mixed-implementation deployments pick the same servers."""
    from byteps_tpu.core.registry import _hash_naive
    for key in (0, 1, 65535, 65536, 1 << 16 | 5, 123456789):
        want = (((key >> 16) + (key % 65536)) * 9973)
        assert _hash_naive(str(key)) == want, key


# --------------------------------------------------------------------- #
# locality-shard subranges + server-load balance hardening
# --------------------------------------------------------------------- #


def _live_load(reg):
    """Sum of live contexts' partition lengths per server — the ground
    truth the _server_load table must equal at all times."""
    want = [0] * max(1, reg._config.num_servers)
    for ctx in reg.contexts_in_order():
        for p in ctx.partitions:
            want[p.server] += p.length
    return want


def test_declare_shards_spread_and_naming():
    reg = make_registry(key_hash_fn="mixed", num_servers=4)
    ctxs = reg.declare_shards("grad/w1", shard_nbytes=2048, num_shards=8,
                              dtype=DataType.FLOAT32)
    assert [c.name for c in ctxs] == [
        TensorRegistry.shard_name("grad/w1", k, 8) for k in range(8)]
    # distinct declared keys, deterministic order
    assert [c.declared_key for c in ctxs] == list(range(8))
    # least-loaded assignment spreads one leaf's shards ACROSS servers
    servers = {c.partitions[0].server for c in ctxs}
    assert len(servers) == 4, "shards of one leaf pinned to one server"
    assert reg.server_loads() == _live_load(reg)
    # idempotent re-declaration: same contexts, load unchanged
    again = reg.declare_shards("grad/w1", 2048, 8, DataType.FLOAT32)
    assert [c.declared_key for c in again] == [c.declared_key
                                              for c in ctxs]
    assert reg.server_loads() == _live_load(reg)


def test_free_retires_load_and_declaration_order():
    reg = make_registry(key_hash_fn="mixed", num_servers=3)
    reg.init_tensor("a", nbytes=8192)
    reg.declare_shards("b", 4096, 4)
    assert reg.server_loads() == _live_load(reg)
    for k in range(4):
        assert reg.free(TensorRegistry.shard_name("b", k, 4))
    assert not reg.free("never-declared")
    assert reg.server_loads() == _live_load(reg)
    assert sum(reg.server_loads()) == 8192  # only "a" remains
    # a freed name re-declares under a NEW key (monotonic, never reused)
    nk = reg.declare(TensorRegistry.shard_name("b", 0, 4)).declared_key
    assert nk == 5  # a=0, b shards 1..4, then the re-declaration


def test_free_redeclare_balances_under_changed_server_count():
    """The satellite's declare -> free -> re-declare audit: after an
    elastic resume onto a DIFFERENT server count, the load table must
    equal the live partition lengths exactly — no negative entries, no
    stale load from freed shard subranges, no dropped retirements."""
    reg = make_registry(key_hash_fn="mixed", num_servers=3,
                        partition_bytes=4096)
    reg.init_tensor("w", nbytes=12000)
    reg.declare_shards("w#s", 4096, 6)
    reg.init_tensor("v", nbytes=5000)
    # free half the shard subranges (shard plan shrank)
    for k in (0, 2, 4):
        assert reg.free(TensorRegistry.shard_name("w#s", k, 6))
    assert reg.server_loads() == _live_load(reg)
    # elastic resume with FEWER servers: table resets + repartition
    reg.redeclare_all(Config(num_servers=2, partition_bytes=4096))
    loads = reg.server_loads()
    assert loads == _live_load(reg)
    assert all(v >= 0 for v in loads)
    # freed names stayed freed across the redeclare
    for k in (0, 2, 4):
        assert not reg.is_declared(TensorRegistry.shard_name("w#s", k, 6))
    # ... and more servers again, with churn on top
    reg.redeclare_all(Config(num_servers=5, partition_bytes=4096))
    reg.free("v")
    reg.init_tensor("v", nbytes=7000)   # re-declare, new size
    reg.init_tensor("w", nbytes=16000)  # resize (retire + reassign)
    loads = reg.server_loads()
    assert loads == _live_load(reg)
    assert all(v >= 0 for v in loads)
    assert sum(loads) == sum(_live_load(reg))


def test_single_server_load_accounting_never_negative():
    """The audit's single-server fix: _assign_server_locked used to skip
    the load add for num_servers==1 while every retire path subtracted
    unconditionally — re-init/free walked the accumulated load negative."""
    reg = make_registry(num_servers=1, partition_bytes=4096)
    reg.init_tensor("g", nbytes=10000)
    assert reg.server_loads() == [10000]
    reg.init_tensor("g", nbytes=6000)   # resize: retire + reassign
    assert reg.server_loads() == [6000]
    assert reg.free("g")
    assert reg.server_loads() == [0]
