"""Sharded input pipeline: disjoint per-rank coverage, deterministic
epoch shuffling shared by ranks, batching edge cases, device prefetch."""

import numpy as np
import pytest

from byteps_tpu.data import ShardedDataset, prefetch_to_device


def _all_rows(ds_cls_kwargs, n_ranks, epoch):
    seen = []
    for r in range(n_ranks):
        ds = ShardedDataset(rank=r, size=n_ranks, **ds_cls_kwargs)
        for batch in ds.epoch(epoch):
            seen.append(batch["x"])
    return np.concatenate(seen) if seen else np.empty((0,))


def test_shards_disjoint_and_cover():
    n = 64
    data = {"x": np.arange(n), "y": np.arange(n) * 10}
    kw = dict(data=data, batch_size=4, seed=3, drop_last=False)
    for epoch in (0, 1):
        rows = _all_rows(kw, 4, epoch)
        assert sorted(rows.tolist()) == list(range(n))  # exact cover
    # different epochs shuffle differently
    assert not np.array_equal(_all_rows(kw, 4, 0), _all_rows(kw, 4, 1))


def test_same_seed_same_order_across_constructions():
    data = {"x": np.arange(40)}
    a = ShardedDataset(data, 5, rank=1, size=2, seed=9)
    b = ShardedDataset(data, 5, rank=1, size=2, seed=9)
    for ba, bb in zip(a.epoch(4), b.epoch(4)):
        np.testing.assert_array_equal(ba["x"], bb["x"])


def test_drop_last_and_len():
    # 23 rows over 2 ranks -> every rank truncated to 11 (equal shard
    # lengths keep synchronous push_pull rounds in lockstep)
    data = {"x": np.arange(23)}
    ds = ShardedDataset(data, 4, rank=0, size=2, drop_last=True)
    batches = list(ds.epoch(0))
    assert len(batches) == len(ds) == 2           # 8 of this rank's 11 rows
    assert all(len(b["x"]) == 4 for b in batches)
    ds2 = ShardedDataset(data, 4, rank=0, size=2, drop_last=False)
    batches2 = list(ds2.epoch(0))
    assert len(batches2) == len(ds2) == 3
    assert sum(len(b["x"]) for b in batches2) == 11


def test_equal_batches_across_ranks_when_indivisible():
    """Every rank must produce the SAME number of batches even when the
    dataset size is not divisible by the rank count (a rank with one
    extra batch would desynchronize the sync PS rounds)."""
    data = {"x": np.arange(149)}
    counts = {r: len(list(ShardedDataset(data, 25, rank=r, size=2,
                                         drop_last=True).epoch(0)))
              for r in range(2)}
    assert counts[0] == counts[1], counts


def test_single_array_source():
    ds = ShardedDataset(np.arange(16), 4, rank=0, size=1, shuffle=False)
    first = next(iter(ds.epoch(0)))
    np.testing.assert_array_equal(first, np.arange(4))


def test_rejects_unequal_dims_and_tiny_datasets():
    with pytest.raises(ValueError, match="unequal"):
        ShardedDataset({"x": np.arange(4), "y": np.arange(5)}, 2,
                       rank=0, size=1)
    with pytest.raises(ValueError, match="cannot shard"):
        ShardedDataset({"x": np.arange(2)}, 1, rank=0, size=4)


def test_prefetch_to_device(devices):
    import jax

    data = {"x": np.arange(32).reshape(8, 4).astype(np.float32)}
    ds = ShardedDataset(data, 2, rank=0, size=1, shuffle=False)
    got = list(prefetch_to_device(ds.epoch(0), depth=2))
    assert len(got) == 4
    for b in got:
        assert isinstance(b["x"], jax.Array)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["x"]) for b in got]), data["x"])


def test_prefetch_with_sharding(bps, devices):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from byteps_tpu.core.state import get_state

    mesh = get_state().mesh
    sharding = NamedSharding(mesh, P("dp"))
    data = {"x": np.arange(64).reshape(16, 4).astype(np.float32)}
    ds = ShardedDataset(data, 8, rank=0, size=1, shuffle=False)
    for b in prefetch_to_device(ds.epoch(0), sharding=sharding):
        assert b["x"].sharding == sharding


def test_prefetch_propagates_source_errors():
    def bad():
        yield {"x": np.zeros(2)}
        raise RuntimeError("source exploded")

    it = prefetch_to_device(bad(), depth=1)
    next(it)
    with pytest.raises(RuntimeError, match="source exploded"):
        next(it)


def test_prefetch_propagates_base_exceptions():
    """A SystemExit escaping the source must surface on the consumer
    (as a RuntimeError) — not end the producer thread sentinel-less and
    deadlock the consumer's blocking q.get()."""
    def bad():
        yield {"x": np.zeros(2)}
        raise SystemExit(3)

    it = prefetch_to_device(bad(), depth=1)
    next(it)
    with pytest.raises(RuntimeError, match="SystemExit"):
        next(it)
