"""Locality-sharded export/import (BYTEPS_LOCAL_SHARD_EXPORT,
jax/train.py + jax/optim.py make_shard_apply + core/registry.py shard
subranges): bitwise parity of shard-export on vs off vs the
single-process baseline for dense, fused-bucket and
compression-fallback configs; odd (non-divisible) shapes with padding;
the pad-threshold and local_size==1 fallbacks; shard keys sharing the
parent's production ordinal; and a slow mixed-traffic churn asserting
no arena-lease or handle leaks under per-shard checkouts.

Bitwise parity relies on the conftest's
``--xla_cpu_enable_fast_math=false`` pin: XLA CPU fast-math
reassociates FMA contraction per shape, which would put 1-ULP noise on
exactly the property these tests guard (TPU codegen has no such
reassociation)."""

import contextlib
import os
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.server import run_server

_PORT = [23700]


@contextlib.contextmanager
def _ps_env(extra_env: dict = None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        # the mlp fixture's weights are 1-48KB: drop the shard floor so
        # they shard on the 8-device mesh
        "BYTEPS_SHARD_MIN_BYTES": "1024",
        **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _setup():
    import jax
    import jax.numpy as jnp

    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=(48, 32), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    return cfg, params, batch


def _run_steps(params, batch, cfg, steps=3, tx=None, mesh=None, **kw):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    params = jax.tree.map(jnp.array, params)  # private copy (donation)
    tx = tx or optax.adam(1e-2)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              mesh or get_state().mesh, **kw)
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    jax.block_until_ready(jax.tree.leaves(params))
    return ([np.asarray(x) for x in jax.tree.leaves(params)],
            float(loss))


def _local_steps(params, batch, cfg, steps=3, tx=None):
    import jax

    from byteps_tpu.models import mlp

    tx = tx or optax.adam(1e-2)
    p, o = params, tx.init(params)

    def local(p, o, b):
        loss, g = jax.value_and_grad(lambda q: mlp.loss_fn(q, b, cfg))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    lj = jax.jit(local)
    for _ in range(steps):
        p, o, _ = lj(p, o, batch)
    return [np.asarray(x) for x in jax.tree.leaves(p)]


# --------------------------------------------------------------------- #
# parity: shard on vs off vs single-process baseline, per codec class
# --------------------------------------------------------------------- #


# fusion 0 = every leaf rides its own key (all weights shard, biases
# export whole); fusion 4096 = biases ride the fused bucket while the
# weights shard ("fused-bucket"); the compression config must FALL BACK
# entirely — the codec unit is the declared key, so host-compressed
# rounds keep whole-leaf keys ("compressed-fallback")
@pytest.mark.parametrize("fusion,kw,want_shards", [
    ("0", {}, True),
    ("4096", {}, True),
    ("0", dict(compression={"compressor": "onebit", "ef": "vanilla"},
               min_compress_bytes=0, device_compress=False), False),
], ids=["dense", "fused-bucket", "compressed-fallback"])
def test_shard_on_off_parity(fusion, kw, want_shards):
    """Shard-export on and off produce IDENTICAL params after 3 steps —
    reduce-scatter + per-shard PS exchange + shard update + all-gather
    is bitwise the psum + whole-leaf exchange + full-leaf update — and
    the lossless configs track the single-process baseline."""
    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_FUSION_BYTES": fusion}) as bps:
        on, _ = _run_steps(params, batch, cfg,
                           local_shard_export=True, **kw)
        stats = bps.get_arena_stats()
        if want_shards:
            assert stats["export_shard_leaves"] > 0, \
                "shard export never engaged — the on-arm is vacuous"
            assert stats["shard_checkouts"] > 0
        else:
            assert stats["export_shard_leaves"] == 0, \
                "host-compressed leaves must keep whole-leaf keys"
    with _ps_env({"BYTEPS_FUSION_BYTES": fusion}) as bps:
        off, _ = _run_steps(params, batch, cfg,
                            local_shard_export=False, **kw)
        assert bps.get_arena_stats()["export_shard_leaves"] == 0
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)
    if not kw:  # lossless transports also track the local baseline
        base = _local_steps(params, batch, cfg)
        for a, b in zip(on, base):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_odd_shapes_pad_parity():
    """Non-divisible leaves (350 = 8*44 - 2, 1000 = 8*125) shard with
    padding and stay bitwise identical to the whole-leaf path: the pad
    travels the wire as zeros and is trimmed before the update's result
    re-enters the params."""
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step

    rng = np.random.RandomState(0)
    params = {"odd": jnp.asarray(rng.randn(50, 7).astype(np.float32)),
              "even": jnp.asarray(rng.randn(1000).astype(np.float32)),
              "tiny": jnp.asarray(rng.randn(16).astype(np.float32))}
    batch = {"x": jnp.asarray(rng.rand(32, 50), np.float32)}

    def loss_fn(p, b):
        return (jnp.mean((b["x"] @ p["odd"]) ** 2)
                + jnp.sum(p["even"] ** 2) * 1e-3
                + jnp.sum(p["tiny"] ** 2) * 1e-3)

    tx = optax.adam(1e-2)

    def run(shard):
        p = jax.tree.map(jnp.array, params)
        opt = tx.init(p)
        step = make_ps_train_step(loss_fn, tx, get_state().mesh,
                                  local_shard_export=shard)
        for _ in range(3):
            p, opt, _ = step(p, opt, batch)
        jax.block_until_ready(jax.tree.leaves(p))
        return [np.asarray(x) for x in jax.tree.leaves(p)]

    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}) as bps:
        on = run(True)
        assert bps.get_arena_stats()["export_shard_leaves"] > 0
    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}):
        off = run(False)
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)


def test_pad_threshold_falls_back():
    """A leaf whose padding would exceed 1/8 of its size keeps the
    whole-leaf path (with 8 shards that can only happen to sub-56-elem
    leaves, so the floor is dropped to expose the gate)."""
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step

    rng = np.random.RandomState(0)
    # 1024 elems: shards cleanly; 7 elems: pad 1, 8*1 > 7 -> fallback
    params = {"big": jnp.asarray(rng.randn(1024).astype(np.float32)),
              "frag": jnp.asarray(rng.randn(7).astype(np.float32))}
    batch = {"x": jnp.asarray(rng.rand(8, 4), np.float32)}

    def loss_fn(p, b):
        return (jnp.sum(p["big"] ** 2) + jnp.sum(p["frag"] ** 2)
                + 0.0 * jnp.sum(b["x"]))

    tx = optax.sgd(1e-2)
    with _ps_env({"BYTEPS_FUSION_BYTES": "0",
                  "BYTEPS_SHARD_MIN_BYTES": "8"}) as bps:
        p = jax.tree.map(jnp.array, params)
        opt = tx.init(p)
        step = make_ps_train_step(loss_fn, tx, get_state().mesh)
        for _ in range(2):
            p, opt, _ = step(p, opt, batch)
        stats = bps.get_arena_stats()
        # exactly ONE leaf per step sharded (big); frag exported whole
        assert stats["export_shard_leaves"] == 2
        assert stats["export_streamed_leaves"] == 4


def test_local_size_one_degenerate_is_whole_leaf():
    """A single-device mesh has no locality axis: shard on must equal
    shard off byte-for-byte AND never declare a shard key."""
    import jax

    cfg, params, batch = _setup()
    from jax.sharding import Mesh

    def run(shard):
        mesh1 = Mesh(np.array(jax.devices()[:1]), ("dp",))
        return _run_steps(params, batch, cfg, mesh=mesh1,
                          local_shard_export=shard)[0]

    with _ps_env() as bps:
        on = run(True)
        assert bps.get_arena_stats()["export_shard_leaves"] == 0
        from byteps_tpu.core.state import get_state
        assert not any("@shard" in n
                       for n in get_state().registry._contexts)
    with _ps_env():
        off = run(False)
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)


def test_shard_keys_share_parent_production_ordinal():
    """All shard subranges of one leaf are ONE production event: they
    share the parent's first-export ordinal, so the queue's
    key-ascending tie-break keeps a leaf's shards adjacent instead of
    interleaving racing devices' fires across leaves."""
    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}) as bps:
        from byteps_tpu.core.state import get_state

        _run_steps(params, batch, cfg, local_shard_export=True)
        state = get_state()
        order = state.scheduler.export_order()
        reg = state.registry
        by_parent = {}
        for name in list(reg._contexts):
            if "@shard" not in name:
                continue
            parent = name.split("@shard")[0]
            ctx = reg.get(name)
            if ctx.declared_key in order:
                by_parent.setdefault(parent, set()).add(
                    order[ctx.declared_key])
        assert by_parent, "no shard keys reached the scheduler"
        for parent, ordinals in by_parent.items():
            assert len(ordinals) == 1, \
                f"{parent}: shards carry ordinals {ordinals}"
        # distinct leaves still get distinct ordinals
        all_ords = [next(iter(o)) for o in by_parent.values()]
        assert len(set(all_ords)) == len(all_ords)


def test_shard_apply_unavailable_still_shards_wire():
    """A per-leaf-separable but NOT shard-separable transform
    (block-RMS clipping mixes elements within a leaf) keeps the
    whole-leaf UPDATE while the wire still moves shards — and stays
    bitwise with the whole-leaf path."""
    cfg, params, batch = _setup()
    tx = optax.chain(optax.clip_by_block_rms(1.0), optax.sgd(1e-2))
    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}) as bps:
        on, _ = _run_steps(params, batch, cfg, tx=tx,
                           local_shard_export=True)
        assert bps.get_arena_stats()["export_shard_leaves"] > 0
    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}):
        off, _ = _run_steps(params, batch, cfg, tx=tx,
                            local_shard_export=False)
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)


def test_broken_taps_still_push_shard_keys(monkeypatch):
    """Cross-worker key-set consistency: a worker whose io_callback
    taps are dead (build failure -> the post-jit fallback latch) must
    STILL push the per-shard subrange keys — a whole-leaf submit would
    desynchronize its key set from healthy peers and stall every
    worker's server aggregation. The fallback slices the host copy
    into the same padded subranges the taps would have pushed, bitwise
    identical to the streamed shard path."""
    import jax
    import jax.experimental

    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}) as bps:
        on, _ = _run_steps(params, batch, cfg, local_shard_export=True)

    def _dead_tap(*a, **k):
        raise RuntimeError("io_callback disabled for this test")

    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}) as bps:
        monkeypatch.setattr(jax.experimental, "io_callback", _dead_tap)
        broken, _ = _run_steps(params, batch, cfg,
                               local_shard_export=True)
        stats = bps.get_arena_stats()
        assert stats["export_streamed_leaves"] == 0, \
            "taps should be dead in this arm"
        c = bps.get_metrics()["counters"]
        assert c["export/shard_bytes"] > 0, \
            "fallback abandoned the shard keys"
        from byteps_tpu.core.state import get_state
        assert any("@shard" in n
                   for n in get_state().registry._contexts)
    for a, b in zip(on, broken):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------------- #
# churn: no lease/handle leaks under per-shard checkouts
# --------------------------------------------------------------------- #


@pytest.mark.slow
def test_mixed_traffic_churn_no_leaks():
    """Many rounds of mixed traffic — sharded weights, fused-bucket
    biases, a rowsparse-routed embedding — then drain the deferred
    releases and assert: no busy arena slots, no live handles, and the
    per-shard checkout counter actually moved (the leases under test
    existed)."""
    import time

    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step

    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(64, 48).astype(np.float32)),
              "w2": jnp.asarray(rng.randn(48, 32).astype(np.float32)),
              "b1": jnp.asarray(rng.randn(48).astype(np.float32)),
              "embed": jnp.asarray(rng.randn(64, 16).astype(np.float32)),
              "odd": jnp.asarray(rng.randn(50, 7).astype(np.float32))}
    batch = {"x": jnp.asarray(rng.rand(32, 64), np.float32),
             "ids": jnp.asarray(rng.randint(0, 8, 32), np.int32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
        e = jnp.take(p["embed"], b["ids"], axis=0)
        return (jnp.mean((h @ p["w2"]) ** 2) + jnp.mean(e * e)
                + jnp.sum(p["odd"] ** 2) * 1e-3)

    tx = optax.adam(1e-3)
    with _ps_env({"BYTEPS_FUSION_BYTES": "1024"}) as bps:
        state = get_state()
        p = jax.tree.map(jnp.array, params)
        opt = tx.init(p)
        step = make_ps_train_step(loss_fn, tx, state.mesh,
                                  rowsparse_params=("embed",),
                                  local_shard_export=True)
        for _ in range(25):
            p, opt, _ = step(p, opt, batch)
        jax.block_until_ready(jax.tree.leaves(p))
        stats = bps.get_arena_stats()
        assert stats["export_shard_leaves"] > 0
        assert stats["shard_checkouts"] > 0
        # the deferred releases ride the release worker: give it a
        # bounded beat to observe the last round's import readiness
        deadline = time.time() + 30
        while time.time() < deadline:
            with state.arena._mu:
                busy = [k for k, s in state.arena._slots.items()
                        if s.busy]
            if not busy and not state.handles._handles:
                break
            time.sleep(0.1)
        assert not busy, f"leaked busy arena slots: {busy[:8]}"
        assert not state.handles._handles, \
            f"leaked handles: {list(state.handles._handles)[:8]}"
