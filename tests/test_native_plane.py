"""Native data-plane tests: SIMD fold parity, zero-copy recv tiers,
per-stage server stats, and byte-balanced engine placement.

The wire-rate rebuild of ``native/ps.cc`` added three things this file
pins down:

- **SIMD fold** (``BYTEPS_SIMD``): runtime-dispatched AVX-512/AVX2
  accumulate kernels whose contract is BITWISE identity with the scalar
  loops — fp32 elementwise, bf16 widen-fold-narrow. Checked both at the
  kernel level (``bps_fold_probe`` over odd lengths and
  NaN/inf/subnormal payloads) and end-to-end (a scalar-forced server vs
  an auto server must publish identical aggregates for dense fp32/bf16,
  rowsparse and fused traffic).
- **Zero-copy recv tiers**: TCP/ring payloads land straight in the
  key's reserved buffer (``direct_recvs``), shm payloads >= 64KB ride
  the descriptor ring and are folded in place from the shared arena
  (``oob_msgs``), and single-worker fused dense replies come back as an
  8-byte echo descriptor instead of a payload copy.
- **Stage stats + engine balance**: recv/queue/fold/reply accounting
  over the C ABI, and key->engine placement driven by CUMULATIVE folded
  bytes (the old assignment-time-only accounting tied on equal init
  lengths and could co-locate a new heavy key with the hot engine).
"""

import ctypes
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.native.build import build
from byteps_tpu.server import engine_stats, run_server, stage_stats
from byteps_tpu.server.client import PSClient

from test_ps import start_servers

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)
CMD_BF16 = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                            DataType.BFLOAT16)


def _lib():
    lib = ctypes.CDLL(build())
    lib.bps_simd_best.restype = ctypes.c_int
    lib.bps_fold_probe.restype = ctypes.c_int
    lib.bps_fold_probe.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_int]
    return lib


def _bf16(x: np.ndarray) -> np.ndarray:
    """Truncate f32 -> bf16 bit patterns (test inputs only)."""
    return (np.ascontiguousarray(x, np.float32).view(np.uint32)
            >> 16).astype(np.uint16)


# --------------------------------------------------------------------- #
# kernel-level parity (bps_fold_probe)
# --------------------------------------------------------------------- #


def _special_f32(n: int, seed: int) -> np.ndarray:
    x = np.random.RandomState(seed).randn(n).astype(np.float32)
    if n >= 8:
        x[0] = np.nan
        x[1] = np.inf
        x[2] = -np.inf
        x[3] = np.float32(1e-42)   # subnormal
        x[4] = np.float32(-0.0)
        x[5] = np.float32(3.4e38)  # near-max: exercises overflow rounding
    return x


@pytest.mark.parametrize("tier", [2, 3])
@pytest.mark.parametrize("n", [1, 7, 8, 15, 16, 17, 31, 33, 255, 4097,
                               100003])
def test_fold_parity_f32(tier, n):
    lib = _lib()
    if lib.bps_simd_best() < tier:
        pytest.skip(f"tier {tier} unsupported on this host/build")
    a = _special_f32(n, seed=n)
    b = _special_f32(n, seed=n + 1)
    d_s, d_v = a.copy(), a.copy()
    assert lib.bps_fold_probe(0, d_s.ctypes.data, b.ctypes.data,
                              n * 4, 0) == 0
    assert lib.bps_fold_probe(0, d_v.ctypes.data, b.ctypes.data,
                              n * 4, tier) == tier
    # BITWISE: NaN payloads compare equal as uint32, never as float
    np.testing.assert_array_equal(d_s.view(np.uint32),
                                  d_v.view(np.uint32))


@pytest.mark.parametrize("tier", [2, 3])
@pytest.mark.parametrize("n", [1, 7, 15, 16, 17, 31, 32, 33, 255, 4097,
                               65537])
def test_fold_parity_bf16(tier, n):
    lib = _lib()
    if lib.bps_simd_best() < tier:
        pytest.skip(f"tier {tier} unsupported on this host/build")
    rng = np.random.RandomState(n)
    a = _bf16(rng.randn(n) * 8)
    b = _bf16(rng.randn(n) * 8)
    if n >= 8:
        # quiet/signaling NaN patterns, +-inf, subnormal, -0.0: the
        # widen-fold-narrow kernels must reproduce float_to_bf16's NaN
        # quieting and round-to-nearest-even EXACTLY
        a[0] = 0x7FC0
        a[1] = 0x7F81
        a[2] = 0x7F80
        a[3] = 0xFF80
        a[4] = 0x0001
        a[5] = 0x8000
        b[0] = 0x0001
        b[2] = 0xFF80  # inf + -inf -> NaN, both arms identically
    d_s, d_v = a.copy(), a.copy()
    assert lib.bps_fold_probe(7, d_s.ctypes.data, b.ctypes.data,
                              n * 2, 0) == 0
    assert lib.bps_fold_probe(7, d_v.ctypes.data, b.ctypes.data,
                              n * 2, tier) == tier
    np.testing.assert_array_equal(d_s, d_v)


def test_fold_probe_rejects_unsupported_tier():
    lib = _lib()
    x = np.zeros(8, np.float32)
    # 99 is no tier; must refuse rather than silently run some kernel
    assert lib.bps_fold_probe(0, x.ctypes.data, x.ctypes.data, 32,
                              99) == -1


# --------------------------------------------------------------------- #
# end-to-end SIMD-vs-scalar parity (dense/bf16/rowsparse/fused)
# --------------------------------------------------------------------- #


def _two_worker_aggregates(monkeypatch, simd: str) -> dict:
    """Run a 2-worker aggregation round over every fold path against a
    fresh server under BYTEPS_SIMD=``simd``; returns the pulled
    aggregate bytes per path."""
    monkeypatch.setenv("BYTEPS_SIMD", simd)
    addrs, threads = start_servers(1, num_workers=2)
    cs = [PSClient(addrs, worker_id=w) for w in range(2)]
    rng = np.random.RandomState(7)
    out: dict = {}

    dense = [_special_f32(3001, seed=w) for w in range(2)]
    big = [rng.randn(32768).astype(np.float32) for _ in range(2)]  # OOB
    bf = [_bf16(rng.randn(4097) * 4) for _ in range(2)]
    fused = [rng.randn(8193).astype(np.float32) for _ in range(2)]

    regs = [TensorRegistry(Config(num_workers=2, num_servers=1))
            for _ in range(2)]
    rs_ctx = [r.init_tensor("rs", 64 * 32 * 4, DataType.FLOAT32,
                            align_bytes=32 * 4) for r in regs]
    rs_grad = np.zeros((64, 32), np.float32)
    rs_grad[5] = 1.5
    rs_grad[40] = -2.25

    def init_all(w):
        c = cs[w]
        c.init_key(0, 1, np.zeros_like(dense[0]), CMD_F32)
        c.init_key(0, 2, np.zeros_like(big[0]), CMD_F32)
        c.init_key(0, 3, np.zeros(4097, np.uint16), CMD_BF16)
        c.init_key(0, 4, np.zeros_like(fused[0]), CMD_F32)

    its = [threading.Thread(target=init_all, args=(w,)) for w in range(2)]
    for t in its:
        t.start()
    for t in its:
        t.join(60)

    res = [dict() for _ in range(2)]

    def rounds(w):
        c = cs[w]
        for key, arr, cmd, out_dt in ((1, dense[w], CMD_F32, np.float32),
                                      (2, big[w], CMD_F32, np.float32),
                                      (3, bf[w], CMD_BF16, np.uint16)):
            c.zpush(0, key, arr, cmd)
            buf = np.empty(arr.shape, out_dt)
            c.zpull(0, key, buf, cmd, exact=True)
            res[w][key] = buf
        done = threading.Event()
        fout = np.empty(fused[w].nbytes, np.uint8)
        c.zpushpull_async(0, 4, fused[w], fout, CMD_F32,
                          lambda n, err, d=done: d.set())
        assert done.wait(60), "fused completion never fired"
        res[w][4] = fout.copy()
        res[w]["rs"] = c.push_pull_rowsparse(rs_ctx[w], rs_grad,
                                            average=False)

    ts = [threading.Thread(target=rounds, args=(w,)) for w in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(120)
    for key in (1, 2, 3, 4, "rs"):
        np.testing.assert_array_equal(
            np.asarray(res[0][key]).view(np.uint8),
            np.asarray(res[1][key]).view(np.uint8))
        out[key] = np.asarray(res[0][key]).tobytes()
    for c in cs:
        c.close()
    for t in threads:
        t.join(timeout=10)
    return out


def test_server_simd_vs_scalar_bitwise(monkeypatch):
    """The whole server fold surface — dense fp32 (specials included),
    an OOB-sized dense key, bf16, the fused PUSHPULL path and the
    rowsparse row folds — must publish BITWISE identical aggregates
    whether the fold runs vectorized or scalar."""
    lib = _lib()
    if lib.bps_simd_best() == 0:
        pytest.skip("no SIMD tier on this host/build")
    scalar = _two_worker_aggregates(monkeypatch, "scalar")
    simd = _two_worker_aggregates(monkeypatch, "auto")
    assert set(scalar) == set(simd)
    for key in scalar:
        assert scalar[key] == simd[key], f"path {key!r} diverged"


# --------------------------------------------------------------------- #
# zero-copy recv tiers + stage stats
# --------------------------------------------------------------------- #


def test_direct_recv_tier_engages_on_tcp(monkeypatch):
    """Dense steady-state pushes over TCP land straight in the key's
    reserved buffer (the recv-into-accumulator tier): direct_recvs
    advances and numerics are unchanged."""
    monkeypatch.setenv("BYTEPS_ENABLE_IPC", "0")
    before = stage_stats()["direct_recvs"]
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    assert c.ipc_conns == 0
    x = np.arange(4096, dtype=np.float32)
    c.init_key(0, 5, np.zeros_like(x), CMD_F32)
    out = np.empty_like(x)
    for _ in range(4):
        c.zpush(0, 5, x, CMD_F32)
        c.zpull(0, 5, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, x)
    # the init push creates the store; every steady push after it is
    # direct-eligible (sync, dense, matching length)
    assert stage_stats()["direct_recvs"] - before >= 4
    c.close()
    for t in threads:
        t.join(timeout=10)


def test_oob_descriptor_tier_and_echo(monkeypatch):
    """Payloads >= 64KB over the shm transport ride the descriptor
    ring: the server folds them in place from the arena (oob_msgs), and
    the single-worker fused dense reply comes back as an echo
    descriptor (client oob_recvd advances, bytes exact)."""
    before = stage_stats()["oob_msgs"]  # leaked-server history cancels
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    assert c.ipc_conns > 0
    x = np.random.RandomState(3).randn(1 << 16).astype(np.float32)  # 256KB
    c.init_key(0, 6, np.zeros_like(x), CMD_F32)
    done = threading.Event()
    out = np.empty(x.nbytes, np.uint8)
    c.zpushpull_async(0, 6, x, out, CMD_F32,
                      lambda n, err, d=done: d.set())
    assert done.wait(60)
    np.testing.assert_array_equal(out.view(np.float32), x)
    st = c.transport_stats()
    assert st["oob_sent"] >= 1, st   # push rode the descriptor tier
    assert st["oob_recvd"] >= 1, st  # reply came back as a descriptor
    assert stage_stats()["oob_msgs"] - before >= 1
    # blocking pull of the same key: served from pub, still exact
    out2 = np.empty_like(x)
    c.zpull(0, 6, out2, CMD_F32, exact=True)
    np.testing.assert_array_equal(out2, x)
    c.close()
    for t in threads:
        t.join(timeout=10)


def test_oob_arena_wrap_and_reclaim(monkeypatch):
    """A tiny arena forces the block ring to wrap and reclaim many
    times over a burst of descriptor-tier messages; every round trip
    stays exact (the version-fence: blocks are immutable until the
    consumer releases, retries allocate fresh)."""
    monkeypatch.setenv("BYTEPS_IPC_ARENA_BYTES", str(256 << 10))
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    assert c.ipc_conns > 0
    rng = np.random.RandomState(11)
    n = 24 * 1024  # 96KB: descriptor-tier, several blocks per arena lap
    c.init_key(0, 8, np.zeros(n, np.float32), CMD_F32)
    out = np.empty(n, np.float32)
    for i in range(20):
        x = rng.randn(n).astype(np.float32)
        c.zpush(0, 8, x, CMD_F32)
        c.zpull(0, 8, out, CMD_F32, exact=True)
        np.testing.assert_array_equal(out, x)
    assert c.transport_stats()["oob_sent"] >= 20
    c.close()
    for t in threads:
        t.join(timeout=10)


def test_stage_stats_live_and_accounted():
    """The per-stage counters move with traffic and fold_bytes accounts
    exactly the payload bytes folded (the fold_ab proof counter).
    Delta-based throughout: in the full suite, earlier test files leave
    daemon server threads parked in bps_server_run forever, so the
    aggregate registry is never empty — but those stragglers have no
    clients left, so their counters are static and cancel in deltas."""
    before = stage_stats()
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    x = np.ones(2048, np.float32)
    c.init_key(0, 9, np.zeros_like(x), CMD_F32)
    out = np.empty_like(x)
    for _ in range(5):
        c.zpush(0, 9, x, CMD_F32)
        c.zpull(0, 9, out, CMD_F32, exact=True)
    after = stage_stats()
    assert after["fold_count"] - before["fold_count"] == 5
    assert after["fold_bytes"] - before["fold_bytes"] == 5 * x.nbytes
    assert after["queue_count"] > before["queue_count"]
    assert after["reply_count"] - before["reply_count"] >= 5
    assert after["live"] > before["live"]
    assert after["engine_threads"] >= 1
    c.close()
    for t in threads:
        t.join(timeout=10)


# --------------------------------------------------------------------- #
# byte-balanced engine placement
# --------------------------------------------------------------------- #


def test_engine_placement_balances_by_cumulative_bytes():
    """The one-hot pathology: equal-sized keys fill the engines, ONE of
    them then carries almost all the traffic, and a new heavy key
    arrives. With assignment-time-only accounting every engine looked
    equally loaded (the init lengths tied), so the newcomer landed on
    the first — the same engine as the hot key — and the two serialized
    on one thread. Placement by cumulative queued bytes must put the
    newcomer elsewhere: the hot engine's byte counter stays flat while
    another engine absorbs the new key's traffic."""
    # earlier test files leak daemon servers that stay registered for
    # the whole session; OUR server is the row appended after this point
    idx = len(engine_stats())
    addrs, threads = start_servers(1, num_workers=1)  # 4 engine threads
    c = PSClient(addrs, worker_id=0)
    tiny = np.ones(256, np.float32)  # 1KB
    for key in range(4):
        c.init_key(0, key, np.zeros_like(tiny), CMD_F32)
    out = np.empty_like(tiny)
    # one-hot traffic: key 0 carries ~300x the bytes of its peers
    for _ in range(300):
        c.zpush(0, 0, tiny, CMD_F32)
        c.zpull(0, 0, out, CMD_F32)
    snap = engine_stats()
    assert len(snap) > idx and len(snap[idx]) >= 2, (idx, snap)
    hot = int(np.argmax(snap[idx]))
    hot_before = snap[idx][hot]
    # the newcomer: a heavy key, init + traffic
    big = np.ones(1 << 18, np.float32)  # 1MB
    c.init_key(0, 99, np.zeros_like(big), CMD_F32)
    bout = np.empty_like(big)
    for _ in range(3):
        c.zpush(0, 99, big, CMD_F32)
        c.zpull(0, 99, bout, CMD_F32)
    hot_after = engine_stats()[idx][hot]
    # the hot engine must NOT have absorbed the ~4MB of new-key traffic
    assert hot_after - hot_before < big.nbytes, (
        f"new heavy key landed on the hot engine "
        f"({hot_after - hot_before} bytes grew on engine {hot})")
    c.close()
    for t in threads:
        t.join(timeout=10)


# --------------------------------------------------------------------- #
# shm descriptor tier under the PR 6 chaos knobs
# --------------------------------------------------------------------- #

# Subprocess (the native client timeout is latched per process and the
# chaos knobs are read per server instance): descriptor-tier payloads
# with forced reply drops + injected delay. A dropped reply means the
# scheduler replays the push with its idempotent epoch stamp — for the
# OOB tier that is a FRESH arena block while the server may still hold
# (or have echoed) the previous one, so the block release/reclaim
# machinery and the replay dedup race exactly where the zero-copy fast
# path lives. Aggregates must stay bitwise exact throughout.
_SHM_CHAOS_SCRIPT = r"""
import os, sys, threading
sys.path.insert(0, os.environ["BPS_REPO"])
import numpy as np
from byteps_tpu.config import Config
from byteps_tpu.server import run_server
from byteps_tpu.utils.net import free_port

port = free_port()
os.environ.update({
    "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
    "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
    "BYTEPS_FORCE_DISTRIBUTED": "1",
})
server = threading.Thread(
    target=run_server, args=(port, Config(num_workers=1, num_servers=1)),
    daemon=True)
server.start()
import byteps_tpu as bps
bps.init()
rng = np.random.RandomState(5)
# 128KB per tensor: descriptor-tier (>= 64KB), several blocks live at
# once across the 4 in-flight keys inside the small arena
grads = [rng.randn(32768).astype(np.float32) for _ in range(4)]
for r in range(4):
    hs = [bps.push_pull_async(g * (r + 1), f"big{i}", average=False)
          for i, g in enumerate(grads)]
    for h, g in zip(hs, grads):
        out = bps.synchronize(h, timeout=60)
        assert np.array_equal(out, g * (r + 1)), (r, "oob double-fold?")
snap = bps.get_metrics()
retries = int(snap["counters"].get("wire/retries", 0))
oob = int(snap["server"]["oob_msgs"])
assert retries > 0, "chaos produced no retries - knob dead?"
assert oob > 0, "no descriptor-tier traffic - shm fast path not engaged?"
# flight recorder (PR 12): the server ring holds the chaos injections,
# the worker ring the retries they forced — key-matched and in causal
# order (server thread shares this process's steady clock, so the
# timestamps compare directly: a drop must precede some retry)
from byteps_tpu.core import flight as flight_mod
from byteps_tpu.core.state import get_state
state = get_state()
drops = [e for e in state.ps_client.drain_flight(0)
         if e["kind"] == "chaos_drop"]
assert drops, "server flight ring recorded no chaos_drop events"
wevs = flight_mod.get_recorder().events()
retry_evs = [e for e in wevs if e["kind"] == "wire_retry"]
assert retry_evs, "worker flight ring recorded no wire_retry events"
wts = [e["ts_ns"] for e in wevs]
assert wts == sorted(wts), "worker flight events out of causal order"
assert min(d["ts_ns"] for d in drops) < max(r["ts_ns"] for r in retry_evs), \
    "no chaos drop precedes any retry - causality broken?"
# rid/key-matched: the dropped replies name partition keys the worker
# actually retried
drop_keys = {d["key"] for d in drops if d["key"]}
retry_keys = {r["key"] for r in retry_evs}
assert drop_keys & retry_keys, (drop_keys, retry_keys)
bps.shutdown()
server.join(timeout=15)
print("SHM_CHAOS_OK retries=", retries, "oob=", oob,
      "drops=", len(drops), "flight_retries=", len(retry_evs))
"""


@pytest.mark.chaos
def test_shm_oob_round_trip_under_chaos():
    """Zero-copy shm large-message round trip under the PR 6 chaos
    knobs: 30% dropped replies (echo descriptors included) + 2ms
    injected delay over a deliberately tiny arena (forces wrap +
    reclaim while replays are in flight). Every aggregate bitwise
    exact, with the descriptor tier proven engaged."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "BPS_REPO": repo,
           "BYTEPS_CLIENT_TIMEOUT_S": "2",
           "BYTEPS_WIRE_RETRY": "5",
           "BYTEPS_WIRE_BACKOFF_MS": "25",
           "BYTEPS_CHAOS_DROP_REPLY_RATE": "0.3",
           "BYTEPS_CHAOS_DELAY_MS": "2",
           "BYTEPS_IPC_ARENA_BYTES": str(1 << 20),
           "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run([sys.executable, "-c", _SHM_CHAOS_SCRIPT],
                          env=env, capture_output=True, text=True,
                          timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    assert "SHM_CHAOS_OK" in out, out[-4000:]
