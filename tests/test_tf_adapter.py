"""byteps_tpu.tensorflow adapter: Horovod-style TF2 surface over the DCN
PS (reference: byteps/tensorflow/__init__.py + keras/callbacks.py —
push_pull is identity at size 1, averages across workers, tapes and
optimizers reduce before applying)."""

import threading

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

# 2 logical CPU devices for the MirroredStrategy test — must be set at
# import (collection) time, before ANY test in this process runs a TF op
# and freezes the device topology
try:
    tf.config.set_logical_device_configuration(
        tf.config.list_physical_devices("CPU")[0],
        [tf.config.LogicalDeviceConfiguration(),
         tf.config.LogicalDeviceConfiguration()])
except RuntimeError:
    pass

from byteps_tpu.config import Config  # noqa: E402
from byteps_tpu.server import run_server  # noqa: E402

_PORT = [24800]


def _fresh_state():
    from byteps_tpu.core.state import GlobalState
    GlobalState._instance = None


@pytest.fixture()
def bptf(bps):
    """TF adapter over the plain (no-PS) initialized core."""
    import byteps_tpu.tensorflow as mod
    yield mod


@pytest.fixture()
def bptf_ps(monkeypatch):
    """TF adapter over a 1-worker loopback PS (full distributed path)."""
    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    _fresh_state()
    import byteps_tpu.tensorflow as mod
    mod.init()
    yield mod
    mod.shutdown()
    server.join(timeout=10)
    _fresh_state()


def test_push_pull_identity_single_worker(bptf):
    x = tf.constant(np.random.RandomState(0).randn(4, 5).astype(np.float32))
    out = bptf.push_pull(x, name="tf_id")
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_push_pull_through_ps(bptf_ps):
    rng = np.random.RandomState(1)
    x = tf.constant(rng.randn(64).astype(np.float32))
    out = bptf_ps.push_pull(x, name="tf_ps", average=False)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-6)
    # async handle api
    h = bptf_ps.push_pull_async(x, name="tf_async", average=False)
    out = bptf_ps.synchronize(h)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-6)


def test_push_pull_fp16_wire(bptf_ps):
    x = tf.constant(np.linspace(-2, 2, 32).astype(np.float32))
    out = bptf_ps.push_pull(x, name="tf_fp16", average=False,
                            compression=bptf_ps.Compression.fp16)
    np.testing.assert_allclose(out.numpy(), x.numpy().astype(np.float16)
                               .astype(np.float32))


def test_push_pull_inside_tf_function(bptf_ps):
    """Graph mode: the op rides a py_function boundary; the result is
    shape-annotated and numerically identical."""
    x = tf.constant(np.random.RandomState(2).randn(16).astype(np.float32))

    @tf.function
    def f(t):
        return bptf_ps.push_pull(t, name="tf_graph", average=False) * 2.0

    out = f(x)
    np.testing.assert_allclose(out.numpy(), x.numpy() * 2, rtol=1e-6)


def test_indexed_slices_rowsparse(bptf_ps):
    """tf.IndexedSlices gradients ride the row-sparse PS path and come
    back dense, duplicate ids accumulated."""
    vals = tf.constant(np.ones((3, 4), np.float32))
    idx = tf.constant([1, 5, 1])
    g = tf.IndexedSlices(values=vals, indices=idx, dense_shape=(8, 4))
    out = bptf_ps.push_pull(g, name="tf_sparse", average=False)
    want = np.zeros((8, 4), np.float32)
    want[1] = 2.0  # duplicate id 1 accumulates
    want[5] = 1.0
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-6)


def test_broadcast_and_variables(bptf_ps):
    v = tf.Variable(np.arange(6).reshape(2, 3).astype(np.float32))
    out = bptf_ps.broadcast(v.value(), root_rank=0, name="tf_b")
    np.testing.assert_allclose(out.numpy(), v.numpy())
    # broadcast_variables is a no-op at size 1 but must not error
    bptf_ps.broadcast_variables([v], root_rank=0)


def _toy_model():
    tf.keras.utils.set_random_seed(0)
    return tf.keras.Sequential([
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.Dense(1),
    ])


def test_distributed_gradient_tape_trains(bptf_ps):
    model = _toy_model()
    rng = np.random.RandomState(0)
    x = tf.constant(rng.randn(64, 8).astype(np.float32))
    y = tf.reduce_sum(x, axis=1, keepdims=True)
    opt = tf.keras.optimizers.SGD(0.05)
    losses = []
    for _ in range(30):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(model(x) - y))
        tape = bptf_ps.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_auto_scope_collision_warns(monkeypatch):
    """Two LIVE tapes resolving the same auto-derived scope (the GAN G/D
    identical-signature hazard) get a RuntimeWarning pointing at
    explicit scope=; the documented rebuild-the-tape-every-step pattern
    (previous wrapper dead before the new one resolves) stays silent
    (round-5 advisor finding)."""
    import gc
    import warnings

    from byteps_tpu import tensorflow as bptf

    # warn-once globals: reset so the test is rerunnable in-process
    monkeypatch.setattr(bptf, "_AUTO_SCOPE_WARNED", set())
    bptf._AUTO_SCOPES.clear()

    flat = [np.zeros((3, 4), np.float32)]
    w1 = bptf._TapeWrapper(None, None, False)
    w2 = bptf._TapeWrapper(None, None, False)
    s1 = w1._resolve_scope(flat)
    with pytest.warns(RuntimeWarning, match="cross-sum"):
        s2 = w2._resolve_scope(flat)
    assert s1 == s2
    # rebuild-every-step: the old wrapper is garbage before the new one
    # resolves — a fresh signature (fresh scope) must not warn
    flat2 = [np.zeros((7, 2), np.float32)]
    w3 = bptf._TapeWrapper(None, None, False)
    w3._resolve_scope(flat2)
    del w3
    gc.collect()
    w4 = bptf._TapeWrapper(None, None, False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        w4._resolve_scope(flat2)
    # explicit scopes bypass derivation entirely
    w5 = bptf._TapeWrapper(None, None, False, scope="gen")
    assert w5._resolve_scope(flat) == "gen"


def test_distributed_optimizer_trains(bptf_ps):
    model = _toy_model()
    rng = np.random.RandomState(0)
    x = tf.constant(rng.randn(64, 8).astype(np.float32))
    y = tf.reduce_sum(x, axis=1, keepdims=True)
    opt = bptf_ps.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
    losses = []
    for _ in range(30):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(model(x) - y))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses
    # delegation surface: inner optimizer attrs remain reachable
    assert float(opt.learning_rate) == pytest.approx(0.05)
    with pytest.raises(ValueError, match="backward_passes_per_step"):
        bptf_ps.DistributedOptimizer(tf.keras.optimizers.SGD(0.05),
                                     backward_passes_per_step=2)


def test_keras_fit_with_callbacks(bptf_ps):
    """model.fit end to end with the broadcast + metric-average
    callbacks (reference: keras/callbacks.py)."""
    model = _toy_model()
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = x.sum(axis=1, keepdims=True)
    model.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")
    hist = model.fit(
        x, y, epochs=3, verbose=0, batch_size=32,
        callbacks=[bptf_ps.BroadcastGlobalVariablesCallback(0),
                   bptf_ps.MetricAverageCallback()])
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_adapter_errors_before_init(bps):
    # plain-core fixture initialized the core, so suspend it to get the
    # uninitialized error surface deterministically
    import byteps_tpu.tensorflow as mod
    from byteps_tpu.core.state import GlobalState
    saved = GlobalState._instance
    GlobalState._instance = None
    try:
        with pytest.raises(RuntimeError, match="init"):
            mod.push_pull_async(tf.constant([1.0]), name="t")
    finally:
        GlobalState._instance = saved


_TF_WORKER = r"""
import jax
jax.config.update("jax_platforms", "cpu")  # before byteps_tpu's import
import numpy as np
import tensorflow as tf
import byteps_tpu.tensorflow as bptf

bptf.init()
r = bptf.rank()
assert bptf.size() == 2
x = tf.constant(np.full(1000, float(r + 1), np.float32))
out = bptf.push_pull(x, name="g", average=True)
np.testing.assert_allclose(out.numpy(), np.full(1000, 1.5), rtol=1e-6)
# broadcast: every worker ends with rank 0's value
b = bptf.broadcast(tf.constant(np.full(8, float(r), np.float32)),
                   root_rank=0, name="b0")
np.testing.assert_allclose(b.numpy(), np.zeros(8), rtol=1e-6)
bptf.shutdown()
print("TF_WORKER_OK", r, flush=True)
"""


@pytest.mark.slow  # >30s: tier-1 headroom (runs in the full suite)
def test_two_worker_tf_push_pull(monkeypatch):
    """Two real OS worker processes with the TF adapter through one
    loopback server: push_pull averages, broadcast wins from root."""
    import os
    import subprocess
    import sys

    from byteps_tpu.utils.net import free_port

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = free_port()
    common = {
        **os.environ,
        "DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    common.pop("XLA_FLAGS", None)
    srv = subprocess.Popen(
        [sys.executable, "-m", "byteps_tpu.server"],
        env={**common, "JAX_PLATFORMS": "cpu"}, cwd=repo,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    workers = []
    try:
        for i in range(2):
            env = {**common, "DMLC_WORKER_ID": str(i),
                   "JAX_PLATFORMS": "cpu"}
            workers.append(subprocess.Popen(
                [sys.executable, "-c", _TF_WORKER], env=env, cwd=repo,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        for i, w in enumerate(workers):
            out, _ = w.communicate(timeout=300)
            assert w.returncode == 0, f"worker {i}:\n{out[-3000:]}"
            assert "TF_WORKER_OK" in out
        srv.wait(timeout=30)
    finally:
        for p in [srv, *workers]:
            if p.poll() is None:
                p.kill()


def test_distributed_optimizer_is_real_keras_optimizer(bptf_ps):
    """model.compile must accept it (keras type-validates): the wrapper
    is a dynamic subclass of the wrapped optimizer's class."""
    opt = bptf_ps.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
    assert isinstance(opt, tf.keras.optimizers.Optimizer)
    model = _toy_model()
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = x.sum(axis=1, keepdims=True)
    model.compile(optimizer=opt, loss="mse")
    hist = model.fit(x, y, epochs=3, verbose=0, batch_size=32)
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_indexed_slices_inside_tf_function(bptf_ps):
    """Embedding gradients (IndexedSlices) inside a tf.function train
    step: symbolic slices densify onto the py_function path instead of
    crashing on graph-tensor iteration."""
    tf.keras.utils.set_random_seed(0)
    emb = tf.keras.layers.Embedding(16, 4)
    opt = tf.keras.optimizers.SGD(0.1)
    ids = tf.constant([[1, 5, 1, 7]])

    @tf.function
    def step():
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.square(emb(ids)))
        dtape = bptf_ps.DistributedGradientTape(tape)
        grads = dtape.gradient(loss, emb.trainable_variables)
        opt.apply_gradients(zip(grads, emb.trainable_variables))
        return loss

    l0 = float(step())
    l1 = float(step())
    assert l1 < l0


def test_graph_mode_grads_batch_into_one_py_function(bptf_ps, monkeypatch):
    """Under tf.function, _reduce_grads must route ALL dense gradients
    through a SINGLE batched py_function (one GIL hop per step, not one
    per tensor — measured +112% vs +69% over the raw-scheduler floor,
    examples/benchmark_tf_hop.py), preserving slots for None grads and
    densified IndexedSlices. size() is spoofed to 2 so the reduction
    runs; the loopback server aggregates at num_workers=1, so averaged
    values equal the local gradients."""
    import byteps_tpu.tensorflow as mod

    monkeypatch.setattr(mod, "size", lambda: 2)
    calls = []
    real = mod._graph_batch_push_pull

    def spy(named, compression):
        calls.append([nm for nm, _ in named])
        return real(named, compression)

    monkeypatch.setattr(mod, "_graph_batch_push_pull", spy)

    tf.keras.utils.set_random_seed(0)
    emb = tf.keras.layers.Embedding(16, 4)
    dense = tf.keras.layers.Dense(2)
    ids = tf.constant([[1, 5, 1, 7]])
    # never touches the loss -> a None grad slot (created OUTSIDE the
    # tf.function: variables must be singletons across traces)
    unused = tf.Variable([1.0])

    @tf.function
    def step():
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.square(dense(emb(ids))))
        dtape = mod.DistributedGradientTape(tape, scope="batchtest")
        grads = dtape.gradient(
            loss, [*emb.trainable_variables, *dense.trainable_variables,
                   unused])
        return grads

    grads = step()
    # ONE batch per trace (tf.function may trace more than once — e.g.
    # the variable-lifting pre-trace), each covering embedding
    # (densified slices) + dense kernel + bias; the None slot stays None
    assert calls and all(c == calls[0] and len(c) == 3 for c in calls)
    assert grads[-1] is None
    assert all(g is not None for g in grads[:-1])
    # numeric: averaged-over-1-worker == local gradient
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(tf.square(dense(emb(ids))))
    local = tape.gradient(loss, [*emb.trainable_variables,
                                 *dense.trainable_variables])
    for got, want in zip(grads, local):
        if isinstance(want, tf.IndexedSlices):
            want = tf.convert_to_tensor(want)
        np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-5)


def test_graph_batch_single_tensor_unwraps(bptf_ps, monkeypatch):
    """A one-gradient model under tf.function, with tf.py_function
    FORCED to return a bare tensor for a single-element Tout (TF 2.21
    happens to return a list, but the API has varied) —
    _graph_batch_push_pull's normalization must hand the slot-fill
    logic a list either way."""
    import byteps_tpu.tensorflow as mod

    monkeypatch.setattr(mod, "size", lambda: 2)
    real_py_function = tf.py_function

    def bare_py_function(func, inp, Tout):
        out = real_py_function(func, inp, Tout)
        if isinstance(out, (list, tuple)) and len(out) == 1:
            out = out[0]  # the variant the unwrap guard defends against
        return out

    monkeypatch.setattr(mod.tf, "py_function", bare_py_function)
    v = tf.Variable(np.ones((3,), np.float32))

    @tf.function
    def step():
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(v * 2.0)
        dtape = mod.DistributedGradientTape(tape, scope="single")
        return dtape.gradient(loss, [v])[0]

    g = step()
    np.testing.assert_allclose(g.numpy(), np.full((3,), 2.0), rtol=1e-6)


def test_mirrored_strategy_cross_device_ops(bptf_ps):
    """MirroredStrategy over 2 logical CPU devices with the PS-backed
    cross-device ops: local (cross-replica) reduction is TF's own, the
    cross-worker hop rides push_pull through the real loopback server
    (identity at 1 worker), and training converges under strategy.run.
    Reference: tensorflow/distribute/cross_device_ops.py:585-627."""
    from byteps_tpu.tensorflow.distribute import BytePSCrossDeviceOps

    devices = [d.name for d in tf.config.list_logical_devices("CPU")][:2]
    assert len(devices) == 2
    strat = tf.distribute.MirroredStrategy(
        devices=devices, cross_device_ops=BytePSCrossDeviceOps())
    assert strat.num_replicas_in_sync == 2

    # direct reduce: SUM across replicas, through the PS hop
    def value_fn(ctx):
        return tf.constant(float(ctx.replica_id_in_sync_group + 1))

    per_replica = strat.experimental_distribute_values_from_function(
        value_fn)
    total = strat.reduce(tf.distribute.ReduceOp.SUM, per_replica,
                         axis=None)
    assert float(total) == pytest.approx(3.0)

    # end to end: gradients batch-reduce through the ops inside a step
    with strat.scope():
        tf.keras.utils.set_random_seed(0)
        model = tf.keras.Sequential([tf.keras.layers.Dense(1)])
        opt = tf.keras.optimizers.SGD(0.1)

    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    y = x.sum(axis=1, keepdims=True).astype(np.float32)
    ds = strat.experimental_distribute_dataset(
        tf.data.Dataset.from_tensor_slices((x, y)).batch(16))

    def step(inp, tgt):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(model(inp) - tgt))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    losses = []
    for _ in range(15):
        for batch in ds:
            per_replica_loss = strat.run(step, args=batch)
            losses.append(float(strat.reduce(
                tf.distribute.ReduceOp.MEAN, per_replica_loss,
                axis=None)))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_load_model_rewraps_optimizer(bptf_ps, tmp_path):
    """bps.load_model: a saved keras model comes back with its optimizer
    wrapped as a DistributedOptimizer and keeps training."""
    model = _toy_model()
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = x.sum(axis=1, keepdims=True)
    model.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")
    model.fit(x, y, epochs=1, verbose=0)
    path = str(tmp_path / "m.keras")
    model.save(path)

    loaded = bptf_ps.load_model(path)
    assert type(loaded.optimizer).__name__.startswith("Distributed")
    assert isinstance(loaded.optimizer, tf.keras.optimizers.Optimizer)
    hist = loaded.fit(x, y, epochs=2, verbose=0)
    assert hist.history["loss"][-1] <= hist.history["loss"][0]
