"""Cross-barrier bounded-staleness pipelining (BYTEPS_CROSS_BARRIER /
BYTEPS_STALENESS, the PR 16 tentpole): the server's round-window gate
(a stamped fold up to W rounds ahead is parked and re-dispatched at
publish, never mis-summed; beyond W it error-replies loudly), SIGKILL
failover mid-window recovering bitwise via replay epochs, determinism
of the window bookkeeping across independent server instances, the
staleness-0 bitwise parity contract, and staleness-1 convergence with
the carry engaged end to end through make_ps_train_step."""

import contextlib
import os
import signal
import threading
import time

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PORT = [24800]

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


def _epoch(round_no: int, attempt: int = 0) -> int:
    return (round_no << 16) | attempt


def _windowed_server(num_workers=1, staleness="1"):
    """An in-process server with the staleness window armed. The native
    ctor reads BYTEPS_CROSS_BARRIER/BYTEPS_STALENESS per instance, so
    the env must stay set until the server has actually constructed —
    the listening port accepting connections proves it has."""
    from byteps_tpu.utils.net import wait_port

    env = {"BYTEPS_CROSS_BARRIER": "1", "BYTEPS_STALENESS": staleness}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        port = _PORT[0]
        _PORT[0] += 1
        t = threading.Thread(
            target=run_server,
            args=(port, Config(num_workers=num_workers, num_servers=1)),
            daemon=True)
        t.start()
        wait_port(port, 60)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return port, t


def _init_key(c0, c1, key, n, server=0):
    th = threading.Thread(
        target=c0.init_key, args=(server, key, np.zeros(n, np.float32),
                                  CMD_F32), daemon=True)
    th.start()
    c1.init_key(server, key, np.zeros(n, np.float32), CMD_F32)
    th.join(timeout=15)
    assert not th.is_alive()


# --------------------------------------------------------------------- #
# window gate: defer within W, loud reject beyond W
# --------------------------------------------------------------------- #


@pytest.mark.chaos
def test_window_defers_ahead_round_then_publishes_in_order():
    """A stamped fold ONE round ahead of the open round (the exact
    shape the cross-barrier carry produces when one worker enters step
    k+1 while a peer still drains step k) is PARKED, the open round
    publishes its true sum untouched, and the deferred fold is
    re-dispatched into its own round — both rounds bitwise exact."""
    port, t = _windowed_server(num_workers=2)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    n = 256
    key = 5
    x0 = np.arange(n, dtype=np.float32)
    x1 = np.full(n, 7.0, np.float32)
    _init_key(c0, c1, key, n)

    # round 1 completes normally
    c0.zpush(0, key, x0, CMD_F32, epoch=_epoch(1))
    c1.zpush(0, key, x1, CMD_F32, epoch=_epoch(1))
    out = np.empty(n, np.float32)
    c0.zpull(0, key, out, CMD_F32, exact=True)
    c1.zpull(0, key, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, x0 + x1)

    # w0 folds round 2, then races ahead with round 3 while round 2 is
    # still open — within window 1 this DEFERS (the pre-window gate
    # error-replied it); the push's reply only lands when round 3
    # publishes, so it rides a background thread
    c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(2))
    err = []

    def _ahead():
        try:
            c0.zpush(0, key, x0 * 3, CMD_F32, epoch=_epoch(3))
        except Exception as e:  # noqa: BLE001 - assert below
            err.append(e)

    th = threading.Thread(target=_ahead, daemon=True)
    th.start()
    time.sleep(0.3)  # the ahead fold reaches the server and parks
    # round 2 completes: its aggregate must be EXACTLY round 2's sum.
    # Pull it from w1 — w0 is a round AHEAD (its deferred fold already
    # applied at publish), so w0's unstamped pull correctly parks until
    # round 3 publishes rather than handing it round 2's bytes.
    c1.zpush(0, key, x1 * 2, CMD_F32, epoch=_epoch(2))
    c1.zpull(0, key, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, (x0 + x1) * 2)
    # w1 joins round 3; the deferred w0 fold completes it
    c1.zpush(0, key, x1 * 3, CMD_F32, epoch=_epoch(3))
    th.join(timeout=15)
    assert not th.is_alive() and not err, err
    c0.zpull(0, key, out, CMD_F32, exact=True)
    c1.zpull(0, key, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, (x0 + x1) * 3)

    stats = c0.server_stats(0)
    assert stats["window_deferred"] >= 1, stats
    assert stats.get("window_rejected", 0) == 0, stats

    c0.close()
    c1.close()
    t.join(timeout=10)


@pytest.mark.chaos
def test_beyond_window_rejected_loudly_aggregate_untouched():
    """A stamped fold BEYOND window W error-replies with a round_skew
    flight event and the open round's aggregate is untouched — skew
    past the staleness bound stays a loud, attributable failure, never
    a silent mis-sum (the invariant the window generalizes, not
    weakens)."""
    port, t = _windowed_server(num_workers=2)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    n = 256
    key = 6
    x0 = np.arange(n, dtype=np.float32)
    x1 = np.full(n, 5.0, np.float32)
    _init_key(c0, c1, key, n)

    # w0 opens round 2; its round-4 push is TWO ahead — beyond W=1
    c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(2))
    with pytest.raises(RuntimeError):
        c0.zpush(0, key, x0 * 4, CMD_F32, epoch=_epoch(4))
    evs = c1.drain_flight(0)
    assert any(e["kind"] == "round_skew" for e in evs), evs
    stats = c0.server_stats(0)
    assert stats["window_rejected"] >= 1, stats

    # the open round still completes with its true sum
    c1.zpush(0, key, x1 * 2, CMD_F32, epoch=_epoch(2))
    out = np.empty(n, np.float32)
    c0.zpull(0, key, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, (x0 + x1) * 2)

    c0.close()
    c1.close()
    t.join(timeout=10)


@pytest.mark.chaos
def test_window_bookkeeping_deterministic_across_stacks():
    """Two independent server instances fed the identical skewed
    sequence produce bitwise-identical aggregates AND identical window
    bookkeeping (deferred/rejected counts) — the window state machine
    is a pure function of the fold sequence, with no timing or
    allocation dependence."""
    results = []
    for _ in range(2):
        port, t = _windowed_server(num_workers=2)
        addr = [f"127.0.0.1:{port}"]
        c0 = PSClient(addr, worker_id=0)
        c1 = PSClient(addr, worker_id=1)
        n = 128
        key = 7
        x0 = np.arange(n, dtype=np.float32)
        x1 = np.full(n, 3.0, np.float32)
        _init_key(c0, c1, key, n)
        c0.zpush(0, key, x0, CMD_F32, epoch=_epoch(1))
        c1.zpush(0, key, x1, CMD_F32, epoch=_epoch(1))
        out = np.empty(n, np.float32)
        c0.zpull(0, key, out, CMD_F32, exact=True)
        # deferred ahead-fold, then an out-of-window reject, then the
        # open round completes and the deferred round follows
        c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(2))
        th = threading.Thread(
            target=c0.zpush,
            args=(0, key, x0 * 3, CMD_F32),
            kwargs={"epoch": _epoch(3)}, daemon=True)
        th.start()
        time.sleep(0.3)
        with pytest.raises(RuntimeError):
            c0.zpush(0, key, x0 * 9, CMD_F32, epoch=_epoch(9))
        c1.zpush(0, key, x1 * 2, CMD_F32, epoch=_epoch(2))
        r2 = np.empty(n, np.float32)
        c1.zpull(0, key, r2, CMD_F32, exact=True)  # w0 is a round ahead
        c1.zpush(0, key, x1 * 3, CMD_F32, epoch=_epoch(3))
        th.join(timeout=15)
        assert not th.is_alive()
        r3 = np.empty(n, np.float32)
        c0.zpull(0, key, r3, CMD_F32, exact=True)
        c1.zpull(0, key, r3, CMD_F32, exact=True)
        stats = c0.server_stats(0)
        results.append((r2.tobytes(), r3.tobytes(),
                        stats["window_deferred"],
                        stats["window_rejected"]))
        c0.close()
        c1.close()
        t.join(timeout=10)
    assert results[0] == results[1]
    np.testing.assert_array_equal(
        np.frombuffer(results[0][1], np.float32),
        np.arange(128, dtype=np.float32) * 3 + 9.0)


@pytest.mark.chaos
def test_sigkill_mid_window_recovers_bitwise_via_replay():
    """SIGKILL the server while a deferred fold is parked mid-window:
    both workers re-home the key to a fresh (also windowed) server and
    replay their rounds with bumped attempts — every round's aggregate
    is bitwise the true sum, exactly the PR 6 replay-epoch contract
    extended across the open window."""
    import subprocess
    import sys

    from byteps_tpu.utils.net import free_port, wait_port

    port_a = free_port()
    code = (f"from byteps_tpu.server import run_server; "
            f"from byteps_tpu.config import Config; "
            f"run_server({port_a}, Config(num_workers=2, num_servers=2))")
    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep + os.environ.get(
               "PYTHONPATH", ""),
           "BYTEPS_CROSS_BARRIER": "1", "BYTEPS_STALENESS": "1"}
    proc = subprocess.Popen([sys.executable, "-c", code], env=env)
    port_b, tb = _windowed_server(num_workers=2)
    wait_port(port_a, 60)
    addrs = [f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"]
    c0 = PSClient(addrs, worker_id=0)
    c1 = PSClient(addrs, worker_id=1)
    n = 256
    key = 8
    x0 = np.arange(n, dtype=np.float32)
    x1 = np.full(n, 4.0, np.float32)
    try:
        _init_key(c0, c1, key, n, server=0)
        c0.zpush(0, key, x0, CMD_F32, epoch=_epoch(1))
        c1.zpush(0, key, x1, CMD_F32, epoch=_epoch(1))
        out = np.empty(n, np.float32)
        c0.zpull(0, key, out, CMD_F32, exact=True)
        c1.zpull(0, key, out, CMD_F32, exact=True)

        # open round 2 (w0 folded) and park w0's round-3 fold in the
        # window... then the server dies with the window populated
        c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(2))
        th = threading.Thread(
            target=_push_quiet, args=(c0, 0, key, x0 * 3, _epoch(3)),
            daemon=True)
        th.start()
        time.sleep(0.3)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        time.sleep(0.3)
        assert c0.server_dead(0) and c1.server_dead(0)
        th.join(timeout=15)

        # re-home to the survivor and replay rounds 2 and 3 with
        # bumped attempts — the fresh windowed store folds each round
        # exactly once
        _init_key(c0, c1, key, n, server=1)
        c0.zpush(1, key, x0 * 2, CMD_F32, epoch=_epoch(2, attempt=1))
        c1.zpush(1, key, x1 * 2, CMD_F32, epoch=_epoch(2))
        c0.zpull(1, key, out, CMD_F32, exact=True)
        np.testing.assert_array_equal(out, (x0 + x1) * 2)
        c0.zpush(1, key, x0 * 3, CMD_F32, epoch=_epoch(3, attempt=1))
        c1.zpush(1, key, x1 * 3, CMD_F32, epoch=_epoch(3))
        c0.zpull(1, key, out, CMD_F32, exact=True)
        c1.zpull(1, key, out, CMD_F32, exact=True)
        np.testing.assert_array_equal(out, (x0 + x1) * 3)
        # a replay of a folded round is deduped, never re-folded
        c0.zpush(1, key, x0 * 3, CMD_F32, epoch=_epoch(3, attempt=2))
        c0.zpull(1, key, out, CMD_F32, exact=True)
        np.testing.assert_array_equal(out, (x0 + x1) * 3)
    finally:
        c0.close()
        c1.close()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        tb.join(timeout=10)


def _push_quiet(client, server, key, arr, epoch):
    try:
        client.zpush(server, key, arr, CMD_F32, epoch=epoch)
    except Exception:  # noqa: BLE001 - server death races the reply
        pass


# --------------------------------------------------------------------- #
# JAX train-step contracts: staleness-0 bitwise, staleness-1 engaged
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def _ps_env(extra_env: dict = None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _setup(hidden=(48, 32)):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=hidden, n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    return cfg, params, batch


def _run_steps(params, batch, cfg, steps=4, flush=False, **kw):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    params = jax.tree.map(jnp.array, params)  # private copy (donation)
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    losses = []
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    if flush:
        params, opt = step.flush(params, opt)
    return ([np.asarray(x) for x in jax.tree.leaves(params)], losses)


# the pinned staleness-0 parity matrix: dense (every leaf its own key),
# fused-bucket (biases ride the bucket), host-compressed, fused apply
# (sharded_apply off — the no-sa arm the carry gate must not disturb)
@pytest.mark.parametrize("fusion,kw", [
    ("0", {}),
    ("4096", {}),
    ("0", dict(compression={"compressor": "onebit", "ef": "vanilla"},
               min_compress_bytes=0, device_compress=False)),
    ("0", dict(sharded_apply=False)),
], ids=["dense", "fused-bucket", "onebit", "fused-apply"])
def test_staleness0_bitwise_identical(fusion, kw):
    """BYTEPS_CROSS_BARRIER with staleness 0 is the synchronous path
    BITWISE: the scheduler window is 0, the carry gate never arms, and
    every transport drains exactly as before."""
    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_FUSION_BYTES": fusion}):
        base, _ = _run_steps(params, batch, cfg)
    with _ps_env({"BYTEPS_FUSION_BYTES": fusion,
                  "BYTEPS_CROSS_BARRIER": "1",
                  "BYTEPS_STALENESS": "0"}):
        xb, _ = _run_steps(params, batch, cfg)
    for a, b in zip(base, xb):
        np.testing.assert_array_equal(a, b)


def test_staleness1_carry_engages_and_converges():
    """At staleness 1 the carry actually engages (carried-leaf counter
    nonzero — the engaged-proof the A/B bench pins), training stays
    finite and converges, and ``flush`` folds the outstanding tail so
    the final trees are complete."""
    from byteps_tpu.core.state import get_state

    cfg, params, batch = _setup(hidden=(256, 256, 256))
    # slow the server so the tail of the drain is genuinely pending
    # when the front-of-model leaves land — on an unthrottled loopback
    # every reply can already be in the ready queue at release time and
    # the carry (correctly) has nothing to do. Shard export off: shard
    # subranges keep the synchronous drain by design, and this test
    # needs whole-leaf tail keys for the carry to have something to
    # take.
    with _ps_env({"BYTEPS_FUSION_BYTES": "256",
                  "BYTEPS_CROSS_BARRIER": "1",
                  "BYTEPS_STALENESS": "1",
                  "BYTEPS_LOCAL_SHARD_EXPORT": "0",
                  "BYTEPS_CHAOS_SLOW_SERVER": "10",
                  # bandwidth throttle: serving time scales with bytes,
                  # so the big carry-half weights lag the tiny biases
                  "BYTEPS_SERVER_THROTTLE_MBPS": "100"}):
        state = get_state()
        assert getattr(state.scheduler, "xb_window", 0) == 1
        leaves, losses = _run_steps(params, batch, cfg, steps=12,
                                    flush=True)
        carried = state.metrics.counter("barrier/carried_leaves").value
        drained = state.metrics.counter("barrier/carry_drained").value
    assert carried > 0, "cross-barrier carry never engaged"
    # every carried round is eventually drained (in-step or by flush)
    assert drained <= carried
    for leaf in leaves:
        assert np.isfinite(leaf).all()
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_staleness1_flush_is_idempotent():
    """flush() after flush() (and on a run that carried nothing) is the
    identity — callers can flush at every checkpoint cut safely."""
    import jax

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    cfg, params, batch = _setup(hidden=(256, 256))
    with _ps_env({"BYTEPS_FUSION_BYTES": "256",
                  "BYTEPS_CROSS_BARRIER": "1",
                  "BYTEPS_STALENESS": "1"}):
        tx = optax.adam(1e-2)
        import jax.numpy as jnp
        params = jax.tree.map(jnp.array, params)
        opt = tx.init(params)
        step = make_ps_train_step(
            lambda p, b: mlp.loss_fn(p, b, cfg), tx, get_state().mesh)
        for _ in range(4):
            params, opt, _ = step(params, opt, batch)
        params, opt = step.flush(params, opt)
        p2, o2 = step.flush(params, opt)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------------------- #
# convergence parity: the llama dryrun at staleness 1, health green
# --------------------------------------------------------------------- #

_PIN = ("from byteps_tpu.utils.jax_compat import force_cpu; force_cpu(8); "
        "import runpy, sys; sys.argv = sys.argv[1:]; "
        "runpy.run_path(sys.argv[0], run_name='__main__')")


@pytest.mark.slow
def test_llama_dryrun_staleness1_health_assert_green():
    """The ISSUE's convergence-parity acceptance arm: the llama
    pretrain dryrun trained THROUGH the cross-barrier window at
    staleness 1 (worker AND server armed — the server reads the env
    per instance) finishes with ``--health-assert`` green: no
    divergence sentinel, no nonfinite leaf, no round_skew flight event
    anywhere in the run."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
           "DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port),
           "BYTEPS_FORCE_DISTRIBUTED": "1",
           "BYTEPS_CROSS_BARRIER": "1",
           "BYTEPS_STALENESS": "1"}
    srv = subprocess.Popen(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r); "
         "from byteps_tpu.config import Config; "
         "from byteps_tpu.server import run_server; "
         "run_server(%d, Config(num_workers=1, num_servers=1))"
         % (REPO, port)],
        cwd=REPO, env=env)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PIN,
             os.path.join(REPO, "examples", "llama_pretrain.py"),
             "--size", "tiny", "--steps", "4", "--batch", "4", "--ps",
             "--health-assert"],
            cwd=REPO, capture_output=True, text=True, timeout=420,
            env=env)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert "health assert: no anomaly events" in r.stdout
        srv.wait(timeout=30)  # worker shutdown stops the server
    finally:
        if srv.poll() is None:
            srv.kill()
