"""FSDP / ZeRO-3 parameter sharding (GSPMD tier).

Params + optimizer state live sharded over dp (fsdp_param_specs); XLA
inserts the layer all-gathers and gradient reduce-scatters. Checks:
the layout actually shards (per-device bytes shrink), training matches
the replicated baseline bitwise-ish, and the 2D dp x tp composition
trains with both axes used.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from byteps_tpu.models import llama
from byteps_tpu.parallel import sharding as sh
from byteps_tpu.parallel.mesh import DP_AXIS, TP_AXIS, make_mesh


def _train_step(tx, cfg):
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: llama.loss_fn(p, {"tokens": tokens}, cfg))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss
    return step


def _run(mesh, cfg, param_specs, steps=3):
    tx = optax.adam(1e-2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    opt_state = tx.init(params)
    p_sh = sh.to_shardings(mesh, param_specs)
    o_sh = sh.to_shardings(mesh, sh.mirror_opt_specs(tx, params,
                                                     param_specs))
    b_sh = NamedSharding(mesh, P(DP_AXIS))
    step = jax.jit(_train_step(tx, cfg),
                   in_shardings=(p_sh, o_sh, b_sh),
                   out_shardings=(p_sh, o_sh, NamedSharding(mesh, P())))
    params = jax.tree.map(jax.device_put, params, p_sh)
    opt_state = jax.tree.map(jax.device_put, opt_state, o_sh)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (8, 33)), jnp.int32)
    tokens = jax.device_put(tokens, b_sh)
    losses = []
    with mesh:
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
    return params, losses


def test_fsdp_specs_shard_large_leaves():
    cfg = llama.LlamaConfig.tiny(vocab_size=128, seq=32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    specs = sh.fsdp_param_specs(params, axis_size=8, min_elements=128)
    flat = {jax.tree_util.keystr(k): (v.shape, s) for (k, v), (_, s) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))[0])}
    # embed [128, d]: 128 % 8 == 0 -> first dim sharded over dp
    shape, spec = flat["['embed']"]
    assert spec[0] == DP_AXIS, (shape, spec)
    # norms are tiny -> replicated
    shape, spec = flat["['final_norm']"]
    assert all(e is None for e in spec), (shape, spec)


def test_fsdp_matches_replicated_training():
    # Parity instrumentation, not the production config: fp32 compute so
    # the fsdp-vs-replicated comparison measures the LAYOUT, not bf16
    # rounding-order drift compounding through adam (bf16 diverges ~0.3%
    # by step 3 — rounding, not a sharding bug). remat=True dodges a real
    # jaxlib-0.4.x CPU SPMD miscompilation: value_and_grad of the
    # un-remat'ed block scan with dp-sharded stacked layer params returns
    # a wrong forward value (~1% off) and garbage gradients — the pure
    # forward under the same shardings is correct, and jax.checkpoint
    # around each block (the production default; tiny() turns it off)
    # avoids the bad partition. See docs/troubleshooting.md.
    import dataclasses
    cfg = llama.LlamaConfig.tiny(vocab_size=128, seq=32)
    cfg = dataclasses.replace(cfg, remat=True, dtype=jnp.float32)
    mesh = make_mesh({DP_AXIS: 8})
    params = llama.init_params(jax.random.PRNGKey(0), cfg)

    fsdp = sh.fsdp_param_specs(params, axis_size=8, min_elements=128)
    repl = jax.tree.map(lambda _: P(), params)

    with jax.default_matmul_precision("float32"):
        p_fsdp, l_fsdp = _run(mesh, cfg, fsdp)
        p_repl, l_repl = _run(mesh, cfg, repl)
    np.testing.assert_allclose(l_fsdp, l_repl, rtol=2e-4)
    # param trees agree after training
    a = np.asarray(jax.tree.leaves(p_fsdp)[0])
    b = np.asarray(jax.tree.leaves(p_repl)[0])
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)
    # and the fsdp layout genuinely shards: addressable shard of embed is
    # 1/8 of the full leaf
    embed = p_fsdp["embed"]
    assert embed.addressable_shards[0].data.shape[0] == embed.shape[0] // 8


def test_fsdp_composes_with_tp():
    cfg = llama.LlamaConfig.tiny(vocab_size=128, seq=32)
    mesh = make_mesh({DP_AXIS: 4, TP_AXIS: 2})
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tp = sh.llama_param_specs(None)
    specs = sh.fsdp_param_specs(params, axis_size=4, base_specs=tp,
                                min_elements=128)
    # lm_head [d, V]: tp on dim 1 (vocab-parallel) stays; dp lands on a
    # free divisible dim
    lm = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    by_path = {jax.tree_util.keystr(k): s for k, s in lm}
    assert TP_AXIS in tuple(by_path["['lm_head']"])
    assert DP_AXIS in tuple(by_path["['lm_head']"])
    _, losses = _run(mesh, cfg, specs)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
