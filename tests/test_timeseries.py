"""Time-series plane (core/timeseries.py) + byteps-top console
(tools/top.py): sampler two-stack determinism, bounded memory (ring
cap + series-count cap), None-skip semantics, counter-delta seeding,
the one-way sweep breaker, the pinned SIGTERM term-hook order
(timeseries → archive), JSONL dump/rehydrate through the console's
post-mortem path, the ``--once`` frame schema pin, the LANE-IMBALANCE
verdict trip/no-trip, the ``_TS_STEP_FIELDS`` / ``_STRIPE_REC_FIELDS``
runtime manifest parity, and a loopback e2e with striping + staleness
engaged (slow)."""

import contextlib
import dataclasses
import json
import os
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core import timeseries as ts_mod
from byteps_tpu.core.metrics import (
    MetricsRegistry, StepProfiler, StepReport, classify_step,
)
from byteps_tpu.core.timeseries import TimeSeriesPlane, _TS_STEP_FIELDS
from byteps_tpu.server import run_server
from byteps_tpu.tools import top

_PORT = [24700]


def _report(step, **kw):
    kw.setdefault("wall_ms", 10.0 + step)
    kw.setdefault("compute_ms", 7.0)
    return StepReport(step=step, **kw)


# --------------------------------------------------------------------- #
# unit tier: recorder semantics
# --------------------------------------------------------------------- #


def test_two_stack_determinism():
    """Clockless contract: two recorders fed the same reports produce
    IDENTICAL series — nothing sampled reads a wall clock."""
    def feed(plane):
        for s in range(1, 8):
            plane.observe(_report(
                s, mfu=0.1 * s,
                lane_bytes=((0, 1, 1000 * s), (0, 2, 400 * s)),
                staleness_lag=1, carry_drain_ms=0.5 * s))
        return plane.series()

    a = feed(TimeSeriesPlane(points=64))
    b = feed(TimeSeriesPlane(points=64))
    assert a == b
    assert "step/wall_ms" in a and "step/mfu" in a
    assert a["stripe/s0/lane1/seg_bytes"]["values"][-1] == 7000.0
    assert a["stripe/s0/lane2/seg_bytes"]["steps"] == list(range(1, 8))
    assert a["step/staleness_lag"]["values"] == [1.0] * 7


def test_ring_bounded_drop_oldest():
    plane = TimeSeriesPlane(points=16)
    for s in range(1, 41):
        plane.observe(_report(s))
    ser = plane.series()["step/wall_ms"]
    assert len(ser["values"]) == 16
    assert ser["steps"] == list(range(25, 41))  # oldest 24 dropped
    # the ring never grows past cap regardless of write count
    snap = plane.snapshot(tail=8)
    assert snap["points"] == 16 and snap["steps"] == 40
    assert len(snap["series"]["step/wall_ms"]["values"]) == 8


def test_series_count_capped_and_counted():
    plane = TimeSeriesPlane(points=16)
    plane.MAX_SERIES = 3  # instance shadow: force the cap
    plane.observe(_report(1, mfu=0.3, grad_norm=1.0, pull_wait_ms=2.0))
    snap = plane.snapshot()
    assert snap["series_count"] == 3
    assert snap["dropped_series"] > 0
    # a capped name never records later either
    plane.observe(_report(2, mfu=0.3, grad_norm=1.0, pull_wait_ms=2.0))
    assert plane.snapshot()["series_count"] == 3


def test_none_fields_skipped_not_zeroed():
    plane = TimeSeriesPlane(points=16)
    plane.observe(_report(1))                 # mfu None here
    plane.observe(_report(2, mfu=0.5))
    ser = plane.series()
    assert ser["step/mfu"]["steps"] == [2]    # no zero for step 1
    assert ser["step/wall_ms"]["steps"] == [1, 2]


def test_counter_deltas_seeded_and_gauges_sampled():
    reg = MetricsRegistry()
    c = reg.counter("wire/push_bytes")
    g = reg.gauge("wire/inflight")
    plane = TimeSeriesPlane(points=16, registry=reg)
    c.inc(100)
    g.set(3)
    plane.observe(_report(1))   # seeds the counter base — no delta yet
    c.inc(250)
    g.set(5)
    plane.observe(_report(2))
    ser = plane.series()
    assert ser["counter/wire/push_bytes"]["steps"] == [2]
    assert ser["counter/wire/push_bytes"]["values"] == [250.0]
    assert ser["gauge/wire/inflight"]["values"] == [3.0, 5.0]


def test_breaker_trips_one_way(monkeypatch):
    monkeypatch.setattr(ts_mod, "_BREAKER_BUDGET_S", -1.0)
    plane = TimeSeriesPlane(points=16)
    for s in range(1, 4):       # three consecutive over-budget sweeps
        plane.observe(_report(s))
    assert plane.snapshot()["breaker_tripped"] is True
    before = plane.series()["step/wall_ms"]["steps"]
    plane.observe(_report(4))   # tripped: silently a no-op
    assert plane.series()["step/wall_ms"]["steps"] == before


def test_disabled_plane_records_nothing():
    plane = TimeSeriesPlane(points=16, enabled=False)
    plane.observe(_report(1))
    assert plane.series() == {}
    assert plane.dump_jsonl(reason="x") is None


def test_ts_step_fields_manifest_is_live():
    """Runtime half of the byteps-lint _TS_ manifest rule: every
    sampled name is a real StepReport field (a rename would silently
    kill its series)."""
    fields = {f.name for f in dataclasses.fields(StepReport)}
    missing = [n for n in _TS_STEP_FIELDS if n not in fields]
    assert not missing, missing


# --------------------------------------------------------------------- #
# SIGTERM term-hook chain: pinned order
# --------------------------------------------------------------------- #


def test_term_hooks_run_in_pinned_order():
    from byteps_tpu.core import flight

    saved = list(flight._term_hooks)
    del flight._term_hooks[:]
    ran = []
    try:
        # registration order is archive FIRST — the order pin, not
        # registration order, must decide execution order
        flight.add_term_hook(lambda: ran.append("archive"),
                             order=flight.TERM_ORDER_ARCHIVE)
        flight.add_term_hook(lambda: ran.append("timeseries"),
                             order=flight.TERM_ORDER_TIMESERIES)
        flight.add_term_hook(lambda: 1 / 0,
                             order=flight.TERM_ORDER_TIMESERIES)
        flight.run_term_hooks()   # the raising hook must not break it
    finally:
        flight._term_hooks[:] = saved
    assert ran == ["timeseries", "archive"]


# --------------------------------------------------------------------- #
# dump artifact + byteps-top
# --------------------------------------------------------------------- #


def test_dump_jsonl_roundtrip_through_top(tmp_path):
    plane = TimeSeriesPlane(points=16, dump_dir=str(tmp_path))
    for s in range(1, 6):
        plane.observe(_report(s, lane_bytes=((0, 1, 100),)))
    path = plane.dump_jsonl(reason="test")
    assert path and os.path.basename(path).startswith("timeseries-")
    with open(path) as f:
        header = json.loads(f.readline())
    assert header["kind"] == "timeseries" and header["reason"] == "test"
    # the console's post-mortem path: artifact detect -> rehydrate
    snap = top.load_snapshot(file=path)
    ts = snap["timeseries"]
    assert ts["series"]["step/wall_ms"]["values"] == \
        plane.series()["step/wall_ms"]["values"]
    assert ts["series"]["stripe/s0/lane1/seg_bytes"]["steps"] == \
        [1, 2, 3, 4, 5]
    frame = top.build_frame(snap)
    assert "byteps-top" in frame and "stripe/s0/lane1/seg_bytes" in frame


def test_term_dump_lands_in_dump_dir(tmp_path):
    plane = TimeSeriesPlane(points=16, dump_dir=str(tmp_path))
    plane.observe(_report(1))
    plane.term_dump()
    assert os.path.exists(
        os.path.join(str(tmp_path), f"timeseries-{os.getpid()}.jsonl"))


def test_once_frame_schema_pinned():
    """The --once machine-readable frame: CI consumers key on these
    exact top-level names — additions are fine elsewhere, these keys
    must not move."""
    plane = TimeSeriesPlane(points=16)
    plane.observe(_report(1, pull_p95_ms=30.0, compute_ms=5.0))
    snap = {"timeseries": plane.snapshot(),
            "steps": {"last": plane and _report(
                1, pull_p95_ms=30.0, compute_ms=5.0).as_dict()},
            "flight": {"events": 2, "dropped": 0},
            "fleet": {"server": {"0": {}}, "source": "wire"}}
    frame = top.once_frame(snap)
    assert set(frame) == {
        "schema", "steps", "series_count", "breaker_tripped",
        "verdict", "series", "health_flags", "flight", "fleet"}
    assert frame["schema"] == "byteps-top/1"
    assert frame["verdict"] and "-bound" in frame["verdict"]
    assert frame["series"]["step/wall_ms"] == {
        "points": 1, "last": 11.0, "min": 11.0, "max": 11.0}
    assert frame["flight"]["events"] == 2
    assert frame["fleet"]["servers"] == 1


# --------------------------------------------------------------------- #
# per-stripe lane attribution: fields + verdict
# --------------------------------------------------------------------- #


def test_lane_fields_lower_median_two_lanes():
    fields = StepProfiler._lane_fields(
        {(0, 1): 0, (0, 2): 0}, {(0, 1): 800, (0, 2): 200})
    assert fields["lane_count"] == 2
    assert fields["lane_share_max"] == pytest.approx(0.8)
    assert fields["lane_share_min"] == pytest.approx(0.2)
    # LOWER median: a 2-lane pair can still trip the 2x bar
    assert fields["lane_share_median"] == pytest.approx(0.2)
    assert fields["lane_max_id"] == 1 and fields["lane_min_id"] == 2
    assert fields["lane_server"] == 0
    assert set(fields["lane_bytes"]) == {(0, 1, 800), (0, 2, 200)}


def test_lane_imbalance_verdict_trips_and_names_lane():
    r = _report(1, lane_count=2, lane_share_max=0.8,
                lane_share_min=0.2, lane_share_median=0.2,
                lane_max_id=1, lane_min_id=2, lane_server=0)
    msg = classify_step(r)
    assert "LANE-IMBALANCE" in msg
    assert "lane 2 slowest" in msg and "server 0" in msg


def test_lane_imbalance_verdict_quiet_when_balanced():
    r = _report(1, lane_count=2, lane_share_max=0.55,
                lane_share_min=0.45, lane_share_median=0.45,
                lane_max_id=1, lane_min_id=2, lane_server=0)
    assert "LANE-IMBALANCE" not in classify_step(r)
    # single lane can never trip (no pair to skew against)
    r1 = _report(2, lane_count=1, lane_share_max=1.0,
                 lane_share_min=1.0, lane_share_median=1.0,
                 lane_max_id=1, lane_min_id=1, lane_server=0)
    assert "LANE-IMBALANCE" not in classify_step(r1)


def test_stripe_manifest_matches_native_layout():
    """Runtime half of the wire_layout lint: the LOADED .so's field
    manifest must equal the Python parser's mirror."""
    from byteps_tpu.server import (
        _STRIPE_REC_FIELDS, native_stripe_field_names,
    )

    names = native_stripe_field_names()
    if not names:
        pytest.skip("stale .so without the stripe-field manifest ABI")
    assert tuple(names) == _STRIPE_REC_FIELDS


# --------------------------------------------------------------------- #
# integration tier: a real loopback PS run feeds the plane
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def _ps_env(extra_env=None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _train_rounds(steps=3, hidden=(48, 32), **kw):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=hidden, n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    return step, params, opt


def test_e2e_plane_rides_real_train_steps(tmp_path):
    with _ps_env() as bps:
        _train_rounds(steps=3)
        ts = bps.get_timeseries()
        assert ts["enabled"] is True
        assert ts["series"]["step/wall_ms"]["steps"] == [1, 2, 3]
        assert len(ts["series"]["counter/wire/push_requests"]
                   ["values"]) == 2  # first observe seeds the base
        # prefix/tail filters
        sub = bps.get_timeseries(prefix="step/", tail=1)
        assert all(n.startswith("step/") for n in sub["series"])
        assert len(sub["series"]["step/wall_ms"]["values"]) == 1
        # the snapshot section serves the same plane
        snap = bps.get_metrics()
        assert snap["timeseries"]["steps"] == 3
        assert snap["timeseries"]["breaker_tripped"] is False
        # --once over the local snapshot: live verdict, live series
        frame = top.once_frame(snap)
        assert frame["schema"] == "byteps-top/1"
        assert frame["steps"] == 3 and frame["verdict"]


def test_e2e_timeseries_off_disarms_surface():
    with _ps_env({"BYTEPS_TIMESERIES": "0"}) as bps:
        _train_rounds(steps=2)
        assert bps.get_timeseries() == {"enabled": False}
        assert bps.get_metrics()["timeseries"]["enabled"] is False


def test_e2e_stripe_and_staleness_series_engaged():
    """The ts_ab engaged-proof as a test: striped data conns (IPC off,
    2 lanes, >=2MB leaves) + bounded staleness under the slow-server
    knob must land nonzero per-lane stripe series AND staleness-lag
    series, and STRIPE_PULL must answer over the wire."""
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step

    env = {"BYTEPS_ENABLE_IPC": "0", "BYTEPS_WIRE_STRIPES": "2",
           "BYTEPS_CROSS_BARRIER": "1", "BYTEPS_STALENESS": "1",
           "BYTEPS_CHAOS_SLOW_SERVER": "5",
           "BYTEPS_LOCAL_SHARD_EXPORT": "0"}
    with _ps_env(env) as bps:
        rng = np.random.RandomState(0)
        params = {f"w{i}": jnp.asarray(
            rng.randn(768, 768), jnp.float32) for i in range(2)}

        def loss_fn(p, b):
            h = jnp.tanh(b @ p["w0"])
            return jnp.mean((h @ p["w1"]) ** 2)

        tx = optax.adam(1e-3)
        opt = tx.init(params)
        batch = jnp.asarray(rng.randn(16, 768), jnp.float32)
        step = make_ps_train_step(loss_fn, tx, get_state().mesh)
        for _ in range(5):
            params, opt, loss = step(params, opt, batch)
        float(loss)
        if hasattr(step, "flush"):
            params, opt = step.flush(params, opt)
        series = bps.get_timeseries()["series"]
        stripe = {n: s for n, s in series.items()
                  if n.startswith("stripe/")}
        assert stripe, sorted(series)
        assert any(sum(s["values"]) > 0 for s in stripe.values())
        assert any(n in series for n in (
            "step/staleness_lag", "step/carry_drain_ms",
            "step/carried_leaves")), sorted(series)
        # the wire half: STRIPE_PULL answers with per-conn records
        client = get_state()._fleet_client()
        assert client is not None
        recs = client.stripe_stats(0, timeout_s=5)
        assert recs and {"conn", "seg_bytes"} <= set(recs[0])
        assert any(r["seg_bytes"] > 0 for r in recs)
