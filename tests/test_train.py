"""End-to-end training tests on the 8-device CPU mesh: the framework's
equivalent of the reference's example-as-system-test pattern
(tests/test_tensorflow_keras.py, example/pytorch/train_mnist_byteps.py).

Checks: loss decreases through distributed_optimizer; plain-psum and ZeRO
steps agree; tiny llama trains.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.core.state import get_state
from byteps_tpu.jax import distributed_optimizer
from byteps_tpu.jax.train import (
    make_train_step, make_zero_train_step, init_zero_state,
)
from byteps_tpu.models import mlp, llama


def synthetic_classification(n=256, dim=784, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype(np.float32)
    w = rng.randn(dim, classes).astype(np.float32)
    y = np.argmax(x @ w, axis=-1).astype(np.int32)
    return {"x": x, "y": y}


def test_mlp_trains(bps):
    mesh = get_state().mesh
    cfg = mlp.MLPConfig(in_dim=784, hidden=(64,), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    tx = distributed_optimizer(optax.sgd(0.1))
    step = make_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx, mesh)
    opt_state = tx.init(params)
    batch = synthetic_classification()

    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses
    acc = float(mlp.accuracy(params, batch, cfg))
    assert acc > 0.5, acc


def test_zero_step_matches_plain(bps):
    """ZeRO (RS + sharded update + AG) must match plain psum allreduce."""
    mesh = get_state().mesh
    cfg = mlp.MLPConfig(in_dim=32, hidden=(16,), n_classes=4)
    params0 = mlp.init_params(jax.random.PRNGKey(1), cfg)
    batch = synthetic_classification(n=64, dim=32, classes=4, seed=1)
    loss = lambda p, b: mlp.loss_fn(p, b, cfg)

    tx_plain = distributed_optimizer(optax.sgd(0.05))
    step_plain = make_train_step(loss, tx_plain, mesh, donate=False)
    p_plain, s_plain = params0, tx_plain.init(params0)

    tx_zero = optax.sgd(0.05)  # grads already averaged by reduce_scatter
    step_zero = make_zero_train_step(loss, tx_zero, mesh, params0, donate=False)
    p_zero = params0
    s_zero = init_zero_state(params0, tx_zero, mesh)

    for _ in range(3):
        p_plain, s_plain, l_plain = step_plain(p_plain, s_plain, batch)
        p_zero, s_zero, l_zero = step_zero(p_zero, s_zero, batch)

    for a, b in zip(jax.tree.leaves(p_plain), jax.tree.leaves(p_zero)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)
    assert abs(float(l_plain) - float(l_zero)) < 1e-5


def test_tiny_llama_trains(bps):
    mesh = get_state().mesh
    cfg = llama.LlamaConfig.tiny(vocab_size=64, seq=32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tx = distributed_optimizer(optax.adam(1e-2))
    step = make_train_step(lambda p, b: llama.loss_fn(p, b, cfg), tx, mesh)
    opt_state = tx.init(params)

    rng = np.random.RandomState(0)
    # learnable structure: token t+1 = (t + 1) % 17
    start = rng.randint(0, 17, size=(16, 1))
    seq = (start + np.arange(33)[None, :]) % 17
    batch = {"tokens": seq.astype(np.int32)}

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_fused_adam_matches_optax(bps):
    """byteps_tpu.jax.optim.fused_adam_step (bench.py's fused_adam train
    variant and the MFU harness share it) must track optax.adam: same
    loss trajectory and params within float tolerance after 5 steps."""
    from byteps_tpu.jax.optim import fused_adam_step

    cfg = llama.LlamaConfig.tiny(vocab_size=64, seq=16)
    p0 = llama.init_params(jax.random.PRNGKey(0), cfg)
    tok = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 17)), jnp.int32)
    loss_fn = lambda q, t: llama.loss_fn(q, {"tokens": t}, cfg)  # noqa: E731

    init, step = fused_adam_step(loss_fn, mu_dtype=jnp.float32)
    tx = optax.adam(1e-3)

    def ref_step(p, o, t):
        loss, g = jax.value_and_grad(lambda q: loss_fn(q, t))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    pa, oa = jax.tree.map(jnp.copy, p0), init(p0)
    pb, ob = jax.tree.map(jnp.copy, p0), tx.init(p0)
    stepj, refj = jax.jit(step), jax.jit(ref_step)
    for _ in range(5):
        pa, oa, la = stepj(pa, oa, tok)
        pb, ob, lb = refj(pb, ob, tok)
    assert abs(float(la) - float(lb)) < 1e-3
    for x, y in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=5e-5)
    # the production mu_dtype (bf16) still trains: loss decreases
    init16, step16 = fused_adam_step(loss_fn)
    p, o = jax.tree.map(jnp.copy, p0), init16(p0)
    s16 = jax.jit(step16)
    losses = []
    for _ in range(8):
        p, o, loss = s16(p, o, tok)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_llama_forward_shapes(bps):
    cfg = llama.LlamaConfig.tiny(vocab_size=64, seq=16)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, 64)
    # logits stay in the compute dtype; loss does the fp32 math
    assert logits.dtype == cfg.dtype
    n = llama.param_count(params)
    assert n > 0


def test_llama_causality(bps):
    """Changing a future token must not affect past logits."""
    cfg = llama.LlamaConfig.tiny(vocab_size=32, seq=8)
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, 6].set(20)
    l1 = llama.forward(params, t1, cfg)
    l2 = llama.forward(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :6]), np.asarray(l2[0, :6]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 6:]), np.asarray(l2[0, 6:]))
