"""byteps-lint (byteps_tpu/tools/lint, docs/static-analysis.md).

Two layers:

- fixture proofs: every rule fires on a seeded violation (including a
  deliberately skewed wire-header constant and a mis-documented
  BYTEPS_* default), stays quiet on the known-good twin, and honors
  per-line suppression;
- the real repo: ``run_lint(REPO)`` must be CLEAN with all five rules
  active — the PR gate ci/checks.sh runs — and the full-repo pass must
  stay under 30 s so it can live inside tier-1.

The CLI contract (exit codes 0/1/2, ``path:line: [rule] message``) is
pinned here because ci/checks.sh and editor integrations parse it.
"""

import os
import re
import subprocess
import sys
import textwrap
import time

import pytest

from byteps_tpu.tools.lint import all_rules, run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_tree(root, files):
    for rel, content in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(content))
    return str(root)


def _rules_hit(findings):
    return {f.rule for f in findings}


# --------------------------------------------------------------------- #
# wire-layout
# --------------------------------------------------------------------- #

_CC_GOOD = """
    static constexpr uint32_t kMagic = 0xB17E5002;
    enum WireCodec : uint8_t {
      kCodecUntagged = 0,
      kCodecDense = 1,
      kCodecLossless = 2,
    };
    #pragma pack(push, 1)
    struct MsgHeader {
      uint32_t magic;
      uint8_t op;
      uint8_t flags;
      uint16_t sender;
      uint32_t rid;
      uint64_t key;
      uint32_t cmd;
      uint32_t len;
      uint64_t epoch;
      uint32_t codec;
    };
    #pragma pack(pop)
    static_assert(sizeof(MsgHeader) == 40, "header layout");
"""

_PY_MIRROR_GOOD = """
    WIRE_MAGIC = 0xB17E5002
    WIRE_HEADER_FMT = "<IBBHIQIIQI"
    WIRE_HEADER_BYTES = 40
    WIRE_CODEC_IDS = {"dense": 1, "lossless": 2}
"""


def test_wire_layout_clean_fixture(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD,
    })
    assert run_lint(root, ["wire-layout"]) == []


def test_wire_layout_skewed_header_size(tmp_path):
    # THE drift class: the native header grew (36 -> 40) and the Python
    # header-size constant was not updated
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD.replace(
            "WIRE_HEADER_BYTES = 40", "WIRE_HEADER_BYTES = 36"),
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "36" in findings[0].message and "40" in findings[0].message
    assert findings[0].path == os.path.join("server", "client.py")


def test_wire_layout_magic_skew(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD.replace(
            "WIRE_MAGIC = 0xB17E5002", "WIRE_MAGIC = 0xB17E5001"),
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "0xb17e5001" in findings[0].message.lower()


def test_wire_layout_field_order_skew(tmp_path):
    # epoch/codec swapped relative to the struct declaration
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD.replace(
            '"<IBBHIQIIQI"', '"<IBBHIQIIIQ"'),
    })
    findings = run_lint(root, ["wire-layout"])
    assert any("field order" in f.message for f in findings)


def test_wire_layout_native_assert_vs_fields(tmp_path):
    # the struct grew a field but the static_assert was left behind:
    # caught on the native side alone
    cc = _CC_GOOD.replace("uint32_t codec;",
                          "uint32_t codec;\n      uint32_t extra;")
    root = _write_tree(tmp_path, {
        "native/ps.cc": cc,
        "server/client.py": _PY_MIRROR_GOOD,
    })
    findings = run_lint(root, ["wire-layout"])
    assert any("static_assert" in f.message and "44" in f.message
               for f in findings)


def test_wire_layout_codec_id_skew(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD.replace(
            '"lossless": 2', '"lossless": 3'),
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "kCodecLossless" in findings[0].message


def test_wire_layout_missing_mirror(tmp_path):
    # a tree with a native header but no Python mirror is a finding,
    # not a silent pass — the rule must never be vacuous
    root = _write_tree(tmp_path, {"native/ps.cc": _CC_GOOD})
    findings = run_lint(root, ["wire-layout"])
    assert any("mirror" in f.message for f in findings)


def test_wire_layout_suppression(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD.replace(
            "WIRE_HEADER_BYTES = 40",
            "WIRE_HEADER_BYTES = 36  # bps-lint: disable=wire-layout"),
    })
    assert run_lint(root, ["wire-layout"]) == []


# --------------------------------------------------------------------- #
# wire-layout: slot/record-layout manifests (the _STAT_SLOTS contract,
# machine-checked instead of comment-enforced) + control-op ids
# --------------------------------------------------------------------- #

_CC_SLOTS = _CC_GOOD + """
    static const char* const kStatSlotNames[] = {
        "recv_ns", "recv_count", "fold_ns"};
    enum Op : uint8_t {
      PUSH = 2,
      STATS_PULL = 12,
      TRACE_DRAIN = 13,
    };
    enum CtrlLimits : uint32_t {
      kCtrlDrainBatch = 1024,
    };
    #pragma pack(push, 1)
    struct TraceRec {
      uint64_t key;
      uint64_t t0;
      uint32_t rid;
      uint16_t sender;
      uint8_t op;
      uint8_t kind;
    };
    #pragma pack(pop)
    static_assert(sizeof(TraceRec) == 24, "trace record layout");
    static const char* const kTraceRecFields[] = {
        "key", "t0", "rid", "sender", "op", "kind"};
"""

_PY_SLOTS = _PY_MIRROR_GOOD + """
    _STAT_SLOTS = ("recv_ns", "recv_count", "fold_ns")
    TRACE_REC_FMT = "<QQIHBB"
    _TRACE_REC_FIELDS = ("key", "t0", "rid", "sender", "op", "kind")
    WIRE_CTRL_OPS = {"STATS_PULL": 12, "TRACE_DRAIN": 13}
    WIRE_CTRL_LIMITS = {"kCtrlDrainBatch": 1024}
"""


def test_slot_layout_clean_fixture(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_SLOTS,
        "server/client.py": _PY_SLOTS,
    })
    assert run_lint(root, ["wire-layout"]) == []


def test_slot_layout_renamed_slot(tmp_path):
    # the historical class: a slot renamed/retyped native-side with the
    # Python mirror (which PARSES the wire vector) left behind
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_SLOTS.replace('"fold_ns"', '"fold_bytes"'),
        "server/client.py": _PY_SLOTS,
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "slot 2" in findings[0].message
    assert "fold_ns" in findings[0].message
    assert "fold_bytes" in findings[0].message


def test_slot_layout_truncated_mirror(tmp_path):
    # native appended a slot, mirror not extended: append-only violated
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_SLOTS.replace(
            '"fold_ns"};', '"fold_ns", "fold_bytes"};'),
        "server/client.py": _PY_SLOTS,
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "3 vs 4 slots" in findings[0].message


def test_slot_layout_reordered_mirror_fails_both_directions(tmp_path):
    # a REORDER is a violation even with identical membership (the
    # vector is positional), and the missing-native direction fires too
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_SLOTS,
        "server/client.py": _PY_SLOTS.replace(
            '("recv_ns", "recv_count", "fold_ns")',
            '("recv_count", "recv_ns", "fold_ns")'),
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1 and "slot 0" in findings[0].message
    # native manifest without any Python mirror: loud, never vacuous
    root2 = _write_tree(tmp_path / "two", {
        "native/ps.cc": _CC_SLOTS,
        "server/client.py": _PY_SLOTS.replace(
            '_STAT_SLOTS = ("recv_ns", "recv_count", "fold_ns")', ""),
    })
    findings = run_lint(root2, ["wire-layout"])
    assert any("_STAT_SLOTS" in f.message and "mirror" in f.message
               for f in findings)


def test_trace_rec_fmt_size_skew(tmp_path):
    # the record struct grew native-side; the struct-format mirror that
    # PARSES the drained ring bytes still packs the old size
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_SLOTS.replace(
            "uint64_t t0;", "uint64_t t0;\n      uint64_t t1;").replace(
            "sizeof(TraceRec) == 24", "sizeof(TraceRec) == 32").replace(
            '"key", "t0",', '"key", "t0", "t1",'),
        "server/client.py": _PY_SLOTS.replace(
            '_TRACE_REC_FIELDS = ("key", "t0",',
            '_TRACE_REC_FIELDS = ("key", "t0", "t1",'),
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "TRACE_REC_FMT packs 24" in findings[0].message
    assert "32" in findings[0].message


def test_ctrl_op_id_skew(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_SLOTS,
        "server/client.py": _PY_SLOTS.replace(
            '"TRACE_DRAIN": 13', '"TRACE_DRAIN": 14'),
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "TRACE_DRAIN" in findings[0].message
    assert "unknown op" in findings[0].message


def test_ctrl_limit_skew(tmp_path):
    # the server grew its drain batch; the client buffer mirror would
    # under-size and replies would drain silently empty
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_SLOTS.replace("kCtrlDrainBatch = 1024",
                                          "kCtrlDrainBatch = 4096"),
        "server/client.py": _PY_SLOTS,
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "kCtrlDrainBatch" in findings[0].message
    assert "silently empty" in findings[0].message


# training-health record (PR 15): the HEALTH_PULL reply's fixed-width
# HealthRec rides the same slot-manifest machinery as the trace/flight
# records — manifest + struct size diffed against the Python mirror
_CC_HEALTH = _CC_SLOTS + """
    #pragma pack(push, 1)
    struct HealthRec {
      uint64_t key;
      uint64_t round;
      uint64_t sumsq_bits;
      uint64_t absmax_bits;
      uint64_t nonfinite;
      uint64_t elems;
    };
    #pragma pack(pop)
    static_assert(sizeof(HealthRec) == 48, "health record layout");
    static const char* const kHealthRecFields[] = {
        "key", "round", "sumsq_bits", "absmax_bits", "nonfinite",
        "elems"};
"""

_PY_HEALTH = _PY_SLOTS + """
    HEALTH_REC_FMT = "<QQQQQQ"
    _HEALTH_REC_FIELDS = ("key", "round", "sumsq_bits", "absmax_bits",
                          "nonfinite", "elems")
"""


def test_health_rec_clean_fixture(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_HEALTH,
        "server/client.py": _PY_HEALTH,
    })
    assert run_lint(root, ["wire-layout"]) == []


def test_health_rec_renamed_field(tmp_path):
    # the drift class: a field renamed native-side while the Python
    # parser (which reassembles the double bit patterns) lags
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_HEALTH.replace('"sumsq_bits"',
                                           '"sumsq"'),
        "server/client.py": _PY_HEALTH,
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "_HEALTH_REC_FIELDS" in findings[0].message
    assert "sumsq" in findings[0].message


def test_health_rec_fmt_size_skew(tmp_path):
    # the record grew native-side; the struct-format mirror that sizes
    # the client's reply buffer still packs the old 48 bytes
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_HEALTH.replace(
            "uint64_t elems;", "uint64_t elems;\n      uint64_t rsvd;"
        ).replace("sizeof(HealthRec) == 48",
                  "sizeof(HealthRec) == 56").replace(
            '"nonfinite",\n        "elems"};',
            '"nonfinite",\n        "elems", "rsvd"};'),
        "server/client.py": _PY_HEALTH.replace(
            '"nonfinite", "elems")', '"nonfinite", "elems", "rsvd")'),
    })
    findings = run_lint(root, ["wire-layout"])
    assert len(findings) == 1
    assert "HEALTH_REC_FMT packs 48" in findings[0].message
    assert "56" in findings[0].message


# --------------------------------------------------------------------- #
# guarded-by
# --------------------------------------------------------------------- #

_LOCKS_FIXTURE = """
    import threading

    class Sched:
        def __init__(self):
            self._mu = threading.Lock()
            self._cv = threading.Condition(self._mu)
            self._state = {}     # guarded-by: _mu|_cv
            self._plain = 0      # unannotated: never checked

        def good(self):
            with self._mu:
                return dict(self._state)

        def good_cv(self):
            with self._cv:
                self._state[1] = 2

        def good_nested_lambda(self):
            with self._cv:
                return (lambda: len(self._state))()

        def bad(self):
            return self._state.get(1)

        def bad_closure_defined_under_lock(self):
            with self._mu:
                def later():
                    # runs on an unknown thread AFTER the with exits:
                    # lexical nesting must not count as holding
                    return self._state
                return later

        def suppressed(self):
            # documented racy read
            return len(self._state)  # bps-lint: disable=guarded-by

        def _drain_locked(self):
            return self._state.popitem()

        def unrelated(self):
            return self._plain
"""


def test_guarded_by_fixture(tmp_path):
    root = _write_tree(tmp_path, {"sched.py": _LOCKS_FIXTURE})
    findings = run_lint(root, ["guarded-by"])
    lines = sorted(f.line for f in findings)
    msgs = [f.message for f in findings]
    assert len(findings) == 2, findings
    assert all("Sched._state" in m for m in msgs)
    assert any("bad()" in m for m in msgs)
    assert any("later()" in m for m in msgs)
    assert lines == sorted(lines)


def test_guarded_by_annotation_above_and_wrapped(tmp_path):
    root = _write_tree(tmp_path, {"m.py": """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                # guarded-by: _mu
                self._above = []
                self._wrapped = (1 +
                                 2)  # guarded-by: _mu

            def bad(self):
                return self._above, self._wrapped
    """})
    findings = run_lint(root, ["guarded-by"])
    assert {m for f in findings for m in [f.message.split(" is ")[0]]} \
        == {"C._above", "C._wrapped"}


_MIXED_LOCKS_FIXTURE = """
    import threading

    class Plane:
        def __init__(self):
            self._mu = threading.Lock()
            self._ingest_mu = threading.Lock()
            self._tensors = {}    # guarded-by: _mu
            self._last = 0        # guarded-by: _ingest_mu

        def _unannotated_locked(self):
            # which lock does the caller hold? the class mixes two, so
            # the bare *_locked convention must NOT exempt this
            return self._tensors

        def _annotated_locked(self):  # caller-holds: _mu
            return self._tensors

        # caller-holds: _mu
        def _above_style_locked(self):
            return self._tensors

        def _wrong_lock_locked(self):  # caller-holds: _mu
            # annotated for _mu but touches _ingest_mu state: the exact
            # wrong-side-of-the-lock class the rule exists for
            return self._last
"""


def test_guarded_by_locked_convention_not_blanket(tmp_path):
    # In a class with MULTIPLE lock groups, *_locked alone is no longer
    # an exemption: the caller-held lock must be named, and a
    # caller-holds annotation only covers attributes under THAT lock.
    root = _write_tree(tmp_path, {"plane.py": _MIXED_LOCKS_FIXTURE})
    findings = run_lint(root, ["guarded-by"])
    by_fn = {}
    for f in findings:
        m = re.search(r"but (\w+)\(\)", f.message)
        by_fn.setdefault(m.group(1), []).append(f.message)
    assert set(by_fn) == {"_unannotated_locked", "_wrong_lock_locked"}, \
        findings
    assert "caller-holds" in by_fn["_unannotated_locked"][0]  # the hint
    assert "Plane._last" in by_fn["_wrong_lock_locked"][0]


def test_guarded_by_locked_single_group_stays_exempt(tmp_path):
    # With ONE lock family in the class the convention is unambiguous:
    # unannotated *_locked methods keep working (the common case —
    # registry/scheduler — must not need annotation churn). The family
    # is the INTERSECTION of the attrs' alternatives, so mixing '_mu'
    # with '_mu|_cv' (a Condition and the Lock it wraps) still counts
    # as one family.
    root = _write_tree(tmp_path, {"m.py": """
        import threading

        class Q:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)
                self._heap = []   # guarded-by: _mu|_cv
                self._n = 0       # guarded-by: _mu|_cv
                self._closed = False  # guarded-by: _mu

            def _pop_locked(self):
                self._n -= 1
                if not self._closed:
                    return self._heap.pop()
    """})
    assert run_lint(root, ["guarded-by"]) == []


def test_guarded_by_orphaned_annotation_is_a_finding(tmp_path):
    # An annotation the rule cannot bind to an attribute guards
    # NOTHING — silently dropping it would disarm the protection the
    # author believes they added.
    root = _write_tree(tmp_path, {"m.py": """
        import threading

        # guarded-by: _mu

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                # guarded-by: _mu

                self._orphan = 0

            def bad(self):
                return self._orphan
    """})
    findings = run_lint(root, ["guarded-by"])
    assert len(findings) == 2, findings
    assert all("guards nothing" in f.message for f in findings)
    assert {f.line for f in findings} == {4, 9}


# --------------------------------------------------------------------- #
# device-thread
# --------------------------------------------------------------------- #

_TAP_FIXTURE = """
    import functools
    import numpy as np
    from jax.experimental import io_callback

    def build(pool, holder):
        def _good_tap(i, step_arr, arr):
            pool.submit(ingest, i, step_arr, arr)

        def _bad_tap(i, step_arr, arr):
            v = np.asarray(arr)          # materializes on device thread
            holder["f"].result()         # blocks on a future
            return int(step_arr)         # materializes a scalar

        def program(x):
            io_callback(functools.partial(_good_tap, 0), None, x, x)
            io_callback(_bad_tap, None, x, x)

        def ingest(i, step_arr, arr):
            # runs on the pool worker, NOT the device thread: int() and
            # asarray() here are the correct place and must not flag
            return int(step_arr), np.asarray(arr)

        return program
"""


def test_device_thread_fixture(tmp_path):
    root = _write_tree(tmp_path, {"taps.py": _TAP_FIXTURE})
    findings = run_lint(root, ["device-thread"])
    assert len(findings) == 3, findings
    assert all("_bad_tap" in f.message for f in findings)
    kinds = " ".join(f.message for f in findings)
    assert "np.asarray" in kinds
    assert ".result()" in kinds
    assert "int()" in kinds


def test_device_thread_lock_and_queue_get(tmp_path):
    root = _write_tree(tmp_path, {"taps.py": """
        from jax.experimental import io_callback

        def build(q, mu):
            def _tap(i, arr):
                with mu:
                    pass
                q.get(timeout=1)

            def program(x):
                io_callback(_tap, None, x)

            return program
    """})
    findings = run_lint(root, ["device-thread"])
    msgs = " ".join(f.message for f in findings)
    assert "acquires lock" in msgs and ".get()" in msgs


def test_device_thread_benign_joins_not_flagged(tmp_path):
    # str.join / os.path.join are not Thread.join: args or a literal
    # receiver mean "not the blocking shape"; a bare thread.join() is
    root = _write_tree(tmp_path, {"taps.py": """
        import os
        from jax.experimental import io_callback

        def build(pool, thread):
            def _tap(i, arr):
                name = "/".join(["a", "b"])
                path = os.path.join("a", "b")
                pool.submit(name, path, arr)

            def _bad_tap(i, arr):
                thread.join()

            def program(x):
                io_callback(_tap, None, x)
                io_callback(_bad_tap, None, x)

            return program
    """})
    findings = run_lint(root, ["device-thread"])
    assert len(findings) == 1, findings
    assert "_bad_tap" in findings[0].message
    assert ".join()" in findings[0].message


def test_device_thread_suppression(tmp_path):
    root = _write_tree(tmp_path, {"taps.py": """
        from jax.experimental import io_callback

        def build(pool):
            def _tap(i, arr):
                return int(i)  # bps-lint: disable=device-thread

            def program(x):
                io_callback(_tap, None, x)

            return program
    """})
    assert run_lint(root, ["device-thread"]) == []


def test_device_thread_method_and_lambda_taps_scanned(tmp_path):
    # self._tap and lambda callbacks must be resolved and scanned, not
    # skipped: a refactor from a nested def to a bound method must not
    # take the tap out of the rule's sight.
    root = _write_tree(tmp_path, {"taps.py": """
        import functools
        from jax.experimental import io_callback

        class Exporter:
            def _bad_tap(self, i, arr):
                return arr.item()

            def program(self, x):
                io_callback(functools.partial(self._bad_tap, 0), None, x)
                io_callback(lambda arr: arr.tolist(), None, x)
    """})
    findings = run_lint(root, ["device-thread"])
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 2, findings
    assert "_bad_tap" in msgs and ".item()" in msgs
    assert "<lambda>" in msgs and ".tolist()" in msgs


def test_device_thread_unresolvable_tap_is_a_finding(tmp_path):
    # Fail closed: a callback the rule cannot scan (imported name,
    # factory-call result) is a finding at the registration site —
    # never a vacuous pass — and suppressible there with a WHY.
    root = _write_tree(tmp_path, {"taps.py": """
        from jax.experimental import io_callback
        from elsewhere import imported_tap

        def build(make_tap):
            def program(x):
                io_callback(imported_tap, None, x)
                io_callback(make_tap(), None, x)
                # reviewed: the factory returns a pure enqueue closure
                io_callback(make_tap(), None, x)  # bps-lint: disable=device-thread

            return program
    """})
    findings = run_lint(root, ["device-thread"])
    assert len(findings) == 2, findings
    msgs = " ".join(f.message for f in findings)
    assert "'imported_tap' is not defined in this module" in msgs
    assert "cannot be resolved" in msgs


def test_device_thread_keyword_callback_and_deferred_lambda(tmp_path):
    # callback= keyword registration is a registration (fail closed on
    # it too); a lambda BUILT inside the tap body runs later on a
    # worker thread, exactly like a nested def, and must not flag.
    root = _write_tree(tmp_path, {"taps.py": """
        from jax.experimental import io_callback
        from elsewhere import imported_tap

        def build(pool, q):
            def _tap(i, arr):
                pool.submit(lambda: q.get())
                q.get(block=False)

            def program(x):
                io_callback(_tap, None, x)
                io_callback(callback=imported_tap,
                            result_shape_dtypes=None)

            return program
    """})
    findings = run_lint(root, ["device-thread"])
    assert len(findings) == 1, findings
    assert "'imported_tap' is not defined in this module" \
        in findings[0].message


def test_device_thread_inline_lambdas_still_scanned(tmp_path):
    # Only lambdas handed to a DEFERRAL site run later; a sorted key=
    # or an immediately-invoked lambda executes on the device thread
    # and must flag like inline code.
    root = _write_tree(tmp_path, {"taps.py": """
        from jax.experimental import io_callback

        def build(handles, mu):
            def _tap(i, arr):
                best = min(handles, key=lambda h: h.result())
                (lambda: mu.acquire())()

            def program(x):
                io_callback(_tap, None, x)

            return program
    """})
    findings = run_lint(root, ["device-thread"])
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 2, findings
    assert ".result()" in msgs and ".acquire()" in msgs


def test_guarded_by_conflicting_annotations_are_a_finding(tmp_path):
    # A re-annotation naming a DIFFERENT lock is author error; an
    # identical re-annotation (reassignment site) is fine. The FIRST
    # annotation stays enforced (union would accept either lock —
    # weaker than either annotation alone), so the _cv-held access to
    # the _mu-guarded attr also fires.
    root = _write_tree(tmp_path, {"m.py": """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)
                self._heap = []   # guarded-by: _mu
                self._same = 0    # guarded-by: _mu

            def reset(self):
                with self._cv:
                    self._heap = []   # guarded-by: _cv
                with self._mu:
                    self._same = 0    # guarded-by: _mu
    """})
    findings = run_lint(root, ["guarded-by"])
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 2, findings
    assert "conflicting" in msgs and "C._heap" in msgs
    assert "without holding the lock" in msgs


# --------------------------------------------------------------------- #
# env-sync
# --------------------------------------------------------------------- #

_ENV_CONFIG = """
    DEFAULT_FOO_BYTES = 4096000

    def _env_int(name, default):
        return default

    def _env_bool(name, default=False):
        return default

    def from_env():
        return (_env_int("BYTEPS_FOO_BYTES", DEFAULT_FOO_BYTES),
                _env_int("BYTEPS_BAR", 7),
                _env_bool("BYTEPS_BAZ"))
"""

_ENV_DOC = """
    # Environment variables

    | Variable | Default | Meaning |
    |---|---|---|
    | `BYTEPS_FOO_BYTES` | 4096000 | partition size |
    | `BYTEPS_BAR` | 7 | bar knob |
    | `BYTEPS_BAZ` | 0 | baz switch |
"""


def test_env_sync_clean_fixture(tmp_path):
    root = _write_tree(tmp_path, {
        "config.py": _ENV_CONFIG,
        "docs/env.md": _ENV_DOC,
    })
    assert run_lint(root, ["env-sync"]) == []


def test_env_sync_undocumented_read(tmp_path):
    root = _write_tree(tmp_path, {
        "config.py": _ENV_CONFIG + (
            "    SECRET = _env_int(\"BYTEPS_UNDOCUMENTED\", 1)\n"),
        "docs/env.md": _ENV_DOC,
    })
    findings = run_lint(root, ["env-sync"])
    assert len(findings) == 1
    assert "BYTEPS_UNDOCUMENTED" in findings[0].message
    assert findings[0].path == "config.py"


def test_env_sync_stale_doc_row(tmp_path):
    root = _write_tree(tmp_path, {
        "config.py": _ENV_CONFIG,
        "docs/env.md": _ENV_DOC + (
            "| `BYTEPS_REMOVED_KNOB` | 1 | nothing reads this |\n"),
    })
    findings = run_lint(root, ["env-sync"])
    assert len(findings) == 1
    assert "BYTEPS_REMOVED_KNOB" in findings[0].message
    assert findings[0].path.endswith("env.md")


def test_env_sync_misdocumented_default(tmp_path):
    # acceptance fixture: a deliberately mis-documented BYTEPS_* default
    root = _write_tree(tmp_path, {
        "config.py": _ENV_CONFIG,
        "docs/env.md": _ENV_DOC.replace(
            "| `BYTEPS_FOO_BYTES` | 4096000 |",
            "| `BYTEPS_FOO_BYTES` | 4194304 |"),
    })
    findings = run_lint(root, ["env-sync"])
    assert len(findings) == 1
    assert "4194304" in findings[0].message
    assert "4096000" in findings[0].message


def test_env_sync_bool_default_mismatch(tmp_path):
    root = _write_tree(tmp_path, {
        "config.py": _ENV_CONFIG,
        "docs/env.md": _ENV_DOC.replace(
            "| `BYTEPS_BAZ` | 0 |", "| `BYTEPS_BAZ` | 1 |"),
    })
    findings = run_lint(root, ["env-sync"])
    assert len(findings) == 1 and "BYTEPS_BAZ" in findings[0].message


def test_env_sync_docstring_mention_is_not_a_read(tmp_path):
    # a knob quoted only in a docstring must not count as read: the
    # stale table row fires (direction 2) and no undocumented-read
    # false positive appears (direction 1)
    root = _write_tree(tmp_path, {
        "config.py": _ENV_CONFIG + (
            '\n    def helper():\n'
            '        """Quotes "BYTEPS_GHOST_KNOB" without reading it."""\n'
            '        return None\n'),
        "docs/env.md": _ENV_DOC + (
            "| `BYTEPS_GHOST_KNOB` | 1 | only a docstring quotes it |\n"),
    })
    findings = run_lint(root, ["env-sync"])
    assert len(findings) == 1
    assert "BYTEPS_GHOST_KNOB" in findings[0].message
    assert "nothing in the code reads it" in findings[0].message


def test_env_sync_native_getenv(tmp_path):
    # native getenv() reads are scanned too (the chaos/IPC knob class)
    root = _write_tree(tmp_path, {
        "config.py": _ENV_CONFIG,
        "native/ps.cc": 'int f() { return getenv("BYTEPS_NATIVE_ONLY") '
                        '!= 0; }\n',
        "docs/env.md": _ENV_DOC,
    })
    findings = run_lint(root, ["env-sync"])
    assert len(findings) == 1
    assert "BYTEPS_NATIVE_ONLY" in findings[0].message


# --------------------------------------------------------------------- #
# metrics-schema
# --------------------------------------------------------------------- #

_METRICS_CODE = """
    def wire(metrics):
        metrics.counter("wire/push_requests")
        metrics.gauge("wire/inflight")
        for tier in ("dense", "onebit"):
            metrics.gauge(f"codec/active/{tier}")
"""

_METRICS_DOC = """
    # Observability

    ```schema
    counters.wire/push_requests
    gauges.wire/inflight
    gauges.codec/active/dense
    ```
"""


def test_metrics_schema_clean_fixture(tmp_path):
    root = _write_tree(tmp_path, {
        "wire.py": _METRICS_CODE,
        "docs/observability.md": _METRICS_DOC,
    })
    assert run_lint(root, ["metrics-schema"]) == []


def test_metrics_schema_undocumented_instrument(tmp_path):
    root = _write_tree(tmp_path, {
        "wire.py": _METRICS_CODE.replace(
            'metrics.gauge("wire/inflight")',
            'metrics.gauge("wire/inflight")\n'
            '        metrics.counter("wire/new_thing")'),
        "docs/observability.md": _METRICS_DOC,
    })
    findings = run_lint(root, ["metrics-schema"])
    assert len(findings) == 1
    assert "wire/new_thing" in findings[0].message
    assert findings[0].path == "wire.py"


def test_metrics_schema_dead_doc_entry(tmp_path):
    root = _write_tree(tmp_path, {
        "wire.py": _METRICS_CODE,
        "docs/observability.md": _METRICS_DOC.replace(
            "counters.wire/push_requests",
            "counters.wire/push_requests\n"
            "counters.wire/ghost_counter"),
    })
    findings = run_lint(root, ["metrics-schema"])
    assert len(findings) == 1
    assert "wire/ghost_counter" in findings[0].message
    assert findings[0].path.endswith("observability.md")


def test_metrics_schema_kind_mismatch(tmp_path):
    # documented as a counter, created as a gauge: both directions fire
    root = _write_tree(tmp_path, {
        "wire.py": _METRICS_CODE,
        "docs/observability.md": _METRICS_DOC.replace(
            "gauges.wire/inflight", "counters.wire/inflight"),
    })
    findings = run_lint(root, ["metrics-schema"])
    assert len(findings) == 2
    assert all("wire/inflight" in f.message for f in findings)


def test_metrics_schema_tracer_calls_ignored(tmp_path):
    root = _write_tree(tmp_path, {
        "wire.py": _METRICS_CODE + (
            "\n\ndef trace(tracer):\n"
            '    tracer.counter("bps:queue_depth", {})\n'),
        "docs/observability.md": _METRICS_DOC,
    })
    assert run_lint(root, ["metrics-schema"]) == []


# --------------------------------------------------------------------- #
# CLI contract
# --------------------------------------------------------------------- #

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "byteps_tpu.tools.lint", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120)


def test_cli_clean_exit_zero(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD,
    })
    proc = _run_cli("--root", root)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "byteps-lint: clean (5 rule(s) run)" in proc.stdout


def test_cli_findings_exit_one_and_format(tmp_path):
    root = _write_tree(tmp_path, {
        "native/ps.cc": _CC_GOOD,
        "server/client.py": _PY_MIRROR_GOOD.replace(
            "WIRE_HEADER_BYTES = 40", "WIRE_HEADER_BYTES = 36"),
    })
    proc = _run_cli("--root", root)
    assert proc.returncode == 1
    # pinned finding format: path:line: [rule] message
    assert re.search(
        r"^server[/\\]client\.py:\d+: \[wire-layout\] ", proc.stdout, re.M)
    assert re.search(r"byteps-lint: 1 finding\(s\)", proc.stdout)


def test_cli_unknown_rule_exit_two(tmp_path):
    proc = _run_cli("--root", str(tmp_path), "--rules", "nonsense")
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_cli_list_names_all_rules():
    proc = _run_cli("--list")
    assert proc.returncode == 0
    for rule in ("wire-layout", "guarded-by", "device-thread",
                 "env-sync", "metrics-schema"):
        assert rule in proc.stdout


# --------------------------------------------------------------------- #
# the real repo
# --------------------------------------------------------------------- #

def test_rule_registry_has_at_least_five_rules():
    assert len(all_rules()) >= 5
    assert len({r.name for r in all_rules()}) == len(all_rules())


def test_real_repo_is_clean_and_fast():
    """THE gate: every invariant rule passes over the live tree, and
    the full pass stays well under the 30 s budget that keeps it
    viable inside tier-1 and ci/checks.sh."""
    t0 = time.perf_counter()
    findings = run_lint(REPO)
    elapsed = time.perf_counter() - t0
    assert findings == [], "\n".join(f.format() for f in findings)
    assert elapsed < 30.0, f"full-repo lint took {elapsed:.1f}s"


def test_real_repo_guarded_by_is_not_vacuous():
    """The lock-discipline rule only means something if the hot-path
    classes actually carry annotations — a refactor that drops them
    all would silently disarm the rule."""
    from byteps_tpu.tools.lint.base import Project
    from byteps_tpu.tools.lint.locks import _class_annotations

    project = Project(REPO)
    annotated = {}
    for path in project.py_files():
        tree = project.tree(path)
        if tree is None:
            continue
        for cls, attrs in _class_annotations(project, path, tree,
                                             []).items():
            annotated[cls] = annotated.get(cls, 0) + len(attrs)
    for cls in ("ScheduledQueue", "PipelineScheduler", "TensorRegistry",
                "MetricsRegistry", "PSClient", "CodecPlane"):
        assert annotated.get(cls), f"{cls} lost its guarded-by annotations"
